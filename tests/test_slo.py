"""SLO engine (docs/slo.md): objective grammar, error budgets,
multi-window multi-burn-rate alerting, console endpoints.

Layers:

* spec — the signal grammar (``<base>_pNN`` / ``fleet_goodput`` /
  ``metric:<family>[:pNN]``), validation, and object round-trips;
* windows — sliding-window budget math: burn rates, budget consumed,
  the long-window guard, and the short-window reset;
* lifecycle — one ``SLOBudgetBurn`` Event + a True ``SLOBurnRate``
  condition per onset (idempotent while the burn persists), cleared
  with ``SLOBudgetRecovered``; spec edits reset windows, deletes drop
  state;
* signals — lifecycle-trace feeds (queue_delay / restart_mttr), the
  request-span harvester (ttft / queue), the fleet_goodput gauge, and
  registry ``metric:`` reads through the new ``Histogram.quantile``;
* console — ``/api/v1/slo/list`` + ``/api/v1/slo/status/{name}``
  (501 when gated off) and operator gate wiring;
* e2e — THE acceptance flow: a TTFT SLO over the serving replay fires
  exactly one burn alert during the flash-crowd window, reports budget
  consumed within 1% of the hand-computed value from the same spans,
  and clears after recovery (2 seeds); and the disabled path leaves a
  chaos-seeded day byte-identical (no SLO objects, no conditions, no
  ``kubedl_slo_*`` families, 501 endpoints).
"""

import pytest

from kubedl_tpu import trace
from kubedl_tpu.api import common as c
from kubedl_tpu.api.slo import (BurnWindow, DEFAULT_ALERTING, SLOSpec,
                                new_slo, parse_signal)
from kubedl_tpu.console.proxy import DataProxy
from kubedl_tpu.console.server import ConsoleConfig, ConsoleServer
from kubedl_tpu.controllers.chaos import ChaosAPIServer, ChaosConfig
from kubedl_tpu.controllers.engine import EngineConfig, JobEngine
from kubedl_tpu.controllers.registry import OperatorConfig, build_operator
from kubedl_tpu.controllers.testing import (TestJobController, new_test_job,
                                            run_all_pods, set_pod_phase)
from kubedl_tpu.core import features as ft
from kubedl_tpu.core import meta as m
from kubedl_tpu.core.apiserver import APIServer
from kubedl_tpu.core.clock import SimClock
from kubedl_tpu.core.events import Recorder
from kubedl_tpu.core.manager import Manager
from kubedl_tpu.metrics.registry import Registry, SLOMetrics
from kubedl_tpu.telemetry import (FleetTelemetry, REASON_SLO_BURN,
                                  REASON_SLO_RECOVERED, SLO_BURN_RATE,
                                  SLOEvaluator)
from kubedl_tpu.utils import status as st
from kubedl_tpu.utils.retry import RetryPolicy
from kubedl_tpu.utils.stats import percentile

pytestmark = pytest.mark.slo


def make_eval(clock, api=None, **kw):
    return SLOEvaluator(api=api, clock=clock, **kw)


def feed(ev, clock, signal, values, step=1.0, labels=None):
    for v in values:
        clock.advance(step)
        ev.observe(signal, v, clock(), labels)


# ---------------------------------------------------------------------------
# spec / signal grammar
# ---------------------------------------------------------------------------


def test_signal_grammar():
    assert parse_signal("ttft_p99") == ("event", "ttft", 0.99, None)
    assert parse_signal("queue_p90") == ("event", "queue", 0.90, None)
    assert parse_signal("queue_delay_p99") == \
        ("event", "queue_delay", 0.99, None)
    assert parse_signal("restart_mttr_p50") == \
        ("event", "restart_mttr", 0.50, None)
    assert parse_signal("fleet_goodput") == \
        ("gauge", "fleet_goodput", None, None)
    assert parse_signal("metric:kubedl_x_seconds") == \
        ("metric", "kubedl_x_seconds", None, 0.99)
    assert parse_signal("metric:kubedl_x_seconds:p50") == \
        ("metric", "kubedl_x_seconds", None, 0.50)
    for bad in ("", "nope", "ttft", "nope_p99", "metric:",
                "metric:x:q50"):
        if bad == "ttft":
            # a bare event base is legal only with an explicit goal
            assert parse_signal("ttft") == ("event", "ttft", None, None)
            continue
        with pytest.raises(ValueError):
            parse_signal(bad)


def test_spec_from_obj_defaults_and_validation():
    spec = SLOSpec.from_obj(new_slo("t", "ttft_p99", 30.0))
    assert spec.goal == 0.99 and spec.comparator == "lte"
    assert spec.budget == pytest.approx(0.01)
    assert spec.alerting == DEFAULT_ALERTING
    assert spec.good(30.0) and not spec.good(30.1)
    # fleet_goodput flips the comparator: bigger is better
    gp = SLOSpec.from_obj(new_slo("g", "fleet_goodput", 0.3, goal=0.95))
    assert gp.comparator == "gte"
    assert gp.good(0.3) and not gp.good(0.29)
    # explicit goal overrides the suffix; goal 1.0 leaves no budget
    s2 = SLOSpec.from_obj(new_slo("t2", "ttft_p99", 1.0, goal=0.9))
    assert s2.goal == 0.9
    with pytest.raises(ValueError):
        new_slo("bad", "ttft_p99", 1.0, goal=1.0)
    with pytest.raises(ValueError):
        SLOSpec.from_obj({"metadata": {"name": "x"},
                          "spec": {"signal": "ttft_p99",
                                   "objective": {}}})   # no target
    with pytest.raises(ValueError):
        new_slo("bad", "ttft_p99", 1.0,
                alerting=[{"severity": "page", "shortSeconds": 60,
                           "longSeconds": 30, "burn": 2.0}])  # long<short
    # selector round-trips sorted
    s3 = SLOSpec.from_obj(new_slo("t3", "queue_delay_p99", 60.0,
                                  selector={"queue": "prod"}))
    assert s3.selector == (("queue", "prod"),)
    assert s3.matches({"queue": "prod", "kind": "TFJob"})
    assert not s3.matches({"queue": "best"}) and not s3.matches(None)
    # review regressions: an explicit windowSeconds 0 is rejected, not
    # silently replaced by the 30d default; duplicate alerting
    # severities are rejected (state is severity-keyed — shared names
    # would clobber each other's firing flag and flap every pass)
    with pytest.raises(ValueError):
        new_slo("bad", "ttft_p99", 1.0, window_s=0.0)
    with pytest.raises(ValueError):
        new_slo("bad", "ttft_p99", 1.0, alerting=[
            {"severity": "page", "shortSeconds": 60, "longSeconds": 300,
             "burn": 10.0},
            {"severity": "page", "shortSeconds": 300,
             "longSeconds": 1800, "burn": 5.0}])


# ---------------------------------------------------------------------------
# window math / budget accounting
# ---------------------------------------------------------------------------


def _single_pair(short=60.0, long_=300.0, burn=10.0):
    return [{"severity": "page", "shortSeconds": short,
             "longSeconds": long_, "burn": burn}]


def test_budget_consumed_matches_hand_math(clock):
    ev = make_eval(clock)
    ev.add(new_slo("t", "ttft_p99", 1.0, goal=0.9, window_s=10_000.0,
                   alerting=_single_pair()))
    feed(ev, clock, "ttft", [0.5] * 90 + [2.0] * 10)
    s = ev.evaluate(clock())[0]
    # 10 bad / 100 total / (1 - 0.9) budget = consumed exactly 1.0
    assert s["samples"] == 100 and s["goodSamples"] == 90
    assert s["compliance"] == pytest.approx(0.9)
    assert s["budgetConsumed"] == pytest.approx(1.0)
    assert s["budgetRemaining"] == pytest.approx(0.0)


def test_windows_slide_and_prune(clock):
    ev = make_eval(clock)
    ev.add(new_slo("t", "ttft_p99", 1.0, goal=0.5, window_s=50.0,
                   alerting=_single_pair(short=10.0, long_=20.0)))
    feed(ev, clock, "ttft", [5.0] * 10)       # all bad, 1/s
    s = ev.evaluate(clock())[0]
    assert s["budgetConsumed"] == pytest.approx(2.0)
    # 100s later every sample has aged out of the 50s window
    clock.advance(100.0)
    s = ev.evaluate(clock())[0]
    assert s["samples"] == 0 and s["budgetConsumed"] is None
    assert s["budgetRemaining"] == 1.0


def test_long_window_guards_and_short_window_resets(clock):
    """The SRE shape: a short bad blip alone must not page (the long
    window vetoes it); once paging, fresh good samples in the short
    window clear the alert even while the long window stays bad."""
    ev = make_eval(clock)
    ev.add(new_slo("t", "ttft_p99", 1.0, goal=0.5, window_s=100_000.0,
                   alerting=_single_pair(short=20.0, long_=2_000.0,
                                         burn=1.5)))
    # a long good history, then a blip of 3 bad samples: the 20s window
    # burns hot but the 2000s window stays quiet -> no alert
    feed(ev, clock, "ttft", [0.5] * 200, step=5.0)
    feed(ev, clock, "ttft", [9.9] * 3, step=1.0)
    s = ev.evaluate(clock())[0]
    assert s["alerts"]["page"]["firing"] is False
    # sustained badness floods both windows -> fire
    feed(ev, clock, "ttft", [9.9] * 300, step=5.0)
    s = ev.evaluate(clock())[0]
    assert s["alerts"]["page"]["firing"] is True
    assert s["alerts"]["page"]["fired"] == 1
    # recovery: good samples push the SHORT window clean; the long
    # window is still mostly bad, but the alert resets
    feed(ev, clock, "ttft", [0.5] * 30, step=1.0)
    s = ev.evaluate(clock())[0]
    assert s["alerts"]["page"]["firing"] is False
    assert s["burnRates"]["2000s"] > 1.0      # long window still hot


def test_selector_routes_samples(clock):
    ev = make_eval(clock)
    ev.add(new_slo("prod-q", "queue_delay_p99", 60.0, goal=0.5,
                   window_s=1e6, selector={"queue": "prod"}))
    feed(ev, clock, "queue_delay", [10.0] * 4, labels={"queue": "prod"})
    feed(ev, clock, "queue_delay", [999.0] * 4, labels={"queue": "best"})
    s = ev.evaluate(clock())[0]
    assert s["samples"] == 4 and s["compliance"] == 1.0


# ---------------------------------------------------------------------------
# alert lifecycle on the SLO object (condition + Events, idempotent)
# ---------------------------------------------------------------------------


def _api_eval(api, clock, metrics=None):
    return SLOEvaluator(api=api, clock=clock, metrics=metrics,
                        recorder=Recorder(api))


def test_alert_lifecycle_condition_and_events_idempotent(api, clock):
    api.create(new_slo("ttft", "ttft_p99", 1.0, goal=0.9,
                       window_s=100_000.0,
                       alerting=_single_pair(short=60.0, long_=300.0,
                                             burn=2.0)))
    mt = SLOMetrics(Registry())
    ev = _api_eval(api, clock, metrics=mt)
    ev.evaluate(clock())                       # discover the object
    feed(ev, clock, "ttft", [9.0] * 50)        # sustained burn
    ev.evaluate(clock())
    obj = api.get("SLO", "default", "ttft")
    conds = [cd for cd in obj["status"]["conditions"]
             if cd.get("type") == SLO_BURN_RATE]
    assert len(conds) == 1 and conds[0]["status"] == "True"
    assert conds[0]["reason"] == REASON_SLO_BURN
    burns = [e for e in api.list("Event")
             if e.get("reason") == REASON_SLO_BURN]
    assert len(burns) == 1 and burns[0]["type"] == "Warning"
    assert mt.alerts.value(slo="ttft", severity="page") == 1
    assert mt.alerts_active.value(slo="ttft") == 1
    assert mt.budget_remaining.value(slo="ttft") < 1.0

    # burn persists: repeated evaluation writes NOTHING new
    feed(ev, clock, "ttft", [9.0] * 20)
    ev.evaluate(clock())
    assert len([e for e in api.list("Event")
                if e.get("reason") == REASON_SLO_BURN]) == 1
    assert mt.alerts.value(slo="ttft", severity="page") == 1

    # recovery: the short window drains -> condition False + one
    # Recovered event
    feed(ev, clock, "ttft", [0.1] * 80)
    ev.evaluate(clock())
    obj = api.get("SLO", "default", "ttft")
    conds = [cd for cd in obj["status"]["conditions"]
             if cd.get("type") == SLO_BURN_RATE]
    assert len(conds) == 1 and conds[0]["status"] == "False"
    assert conds[0]["reason"] == REASON_SLO_RECOVERED
    rec = [e for e in api.list("Event")
           if e.get("reason") == REASON_SLO_RECOVERED]
    assert len(rec) == 1 and rec[0]["type"] == "Normal"
    assert mt.alerts_active.value(slo="ttft") == 0
    assert [a["event"] for a in ev.alert_log] == ["fire", "clear"]


def test_retired_state_clears_alert_and_gauges(api, clock):
    """Review regression: a firing SLO whose spec is edited (or whose
    object is deleted) must close out its alert lifecycle — condition
    False + Recovered event + zeroed gauges — never strand a True
    SLOBurnRate on the object or a stale alerts_active=1 in the
    exposition."""
    api.create(new_slo("t", "ttft_p99", 1.0, goal=0.9, window_s=1e6,
                       alerting=_single_pair(short=60.0, long_=300.0,
                                             burn=2.0)))
    mt = SLOMetrics(Registry())
    ev = _api_eval(api, clock, metrics=mt)
    ev.evaluate(clock())
    feed(ev, clock, "ttft", [9.0] * 50)
    ev.evaluate(clock())
    assert mt.alerts_active.value(slo="t") == 1
    # spec edit while firing: windows reset AND the alert clears
    obj = api.get("SLO", "default", "t")
    obj["spec"]["objective"]["target"] = 2.0
    api.update(obj)
    ev.evaluate(clock())
    assert mt.alerts_active.value(slo="t") == 0
    assert mt.burn_rate.value(slo="t", window="60s") == 0.0
    obj = api.get("SLO", "default", "t")
    cond = [cd for cd in obj["status"]["conditions"]
            if cd.get("type") == SLO_BURN_RATE]
    assert cond and cond[0]["status"] == "False"
    assert any(e.get("reason") == REASON_SLO_RECOVERED
               for e in api.list("Event"))
    assert [a["event"] for a in ev.alert_log] == ["fire", "clear"]
    # delete while firing: gauges reset (no object left to write on)
    feed(ev, clock, "ttft", [9.0] * 50)
    ev.evaluate(clock())
    assert mt.alerts_active.value(slo="t") == 1
    api.delete("SLO", "default", "t")
    ev.evaluate(clock())
    assert mt.alerts_active.value(slo="t") == 0
    assert [a["event"] for a in ev.alert_log] == \
        ["fire", "clear", "fire", "clear"]
    # the deleted objective's gauge series VANISH from the exposition
    # (a frozen budget_remaining would keep dashboards alerting on an
    # objective that no longer exists)
    expo = mt.registry.expose()
    assert 'kubedl_slo_budget_remaining_ratio{slo="t"}' not in expo
    assert 'kubedl_slo_alerts_active{slo="t"}' not in expo
    assert 'kubedl_slo_burn_rate{slo="t"' not in expo
    # the onset COUNTER keeps its history (counter semantics)
    assert 'kubedl_slo_alerts_total{slo="t",severity="page"} 2.0' in expo


def test_mixed_severity_clear_keeps_condition_truthful(api, clock):
    """Review regression: when the page pair clears while the ticket
    pair still fires, the condition must stay True and name the
    still-firing severity — never carry a 'back under threshold'
    message mid-incident."""
    api.create(new_slo(
        "t", "ttft_p99", 1.0, goal=0.5, window_s=1e6,
        alerting=[
            {"severity": "page", "shortSeconds": 20, "longSeconds": 100,
             "burn": 1.5},
            {"severity": "ticket", "shortSeconds": 100,
             "longSeconds": 300, "burn": 1.0},
        ]))
    ev = _api_eval(api, clock)
    ev.evaluate(clock())
    feed(ev, clock, "ttft", [9.0] * 50)       # both pairs fire
    s = ev.evaluate(clock())[0]
    assert s["alerts"]["page"]["firing"] and s["alerts"]["ticket"]["firing"]
    obj = api.get("SLO", "default", "t")
    cond = next(cd for cd in obj["status"]["conditions"]
                if cd.get("type") == SLO_BURN_RATE)
    assert "page" in cond["message"] and "ticket" in cond["message"]
    # 25 fresh good samples clear the 20s page window; the ticket
    # windows still hold the bad run
    feed(ev, clock, "ttft", [0.1] * 25)
    s = ev.evaluate(clock())[0]
    assert not s["alerts"]["page"]["firing"]
    assert s["alerts"]["ticket"]["firing"]
    obj = api.get("SLO", "default", "t")
    cond = next(cd for cd in obj["status"]["conditions"]
                if cd.get("type") == SLO_BURN_RATE)
    assert cond["status"] == "True"           # still an incident
    assert cond["reason"] == REASON_SLO_BURN
    assert "ticket" in cond["message"]
    assert "back under threshold" not in cond["message"]
    # ...while the Event stream records the page recovery itself
    assert any(e.get("reason") == REASON_SLO_RECOVERED
               and e["message"].startswith("page:")
               for e in api.list("Event"))


def test_metric_quantile_p0_not_treated_as_unset(clock):
    """Review regression: an explicit p0 (the declared minimum) must
    not fall back to the p99 through a falsy-zero default."""
    reg = Registry()
    h = reg.histogram("kubedl_min_seconds", "", (), buckets=(1.0, 10.0))
    for v in (0.5, 9.0, 9.0, 9.0):
        h.observe(v)
    ev = make_eval(clock, registry=reg)
    ev.add(new_slo("min", "metric:kubedl_min_seconds:p0", 2.0,
                   goal=0.5, window_s=1e6))
    clock.advance(1.0)
    ev.evaluate(clock())
    s = ev.status("min")
    # p0 estimate sits in the first bucket (< 2.0) -> good; the p99
    # (~10) would have been judged bad
    assert s["samples"] == 1 and s["goodSamples"] == 1


def test_spec_edit_resets_windows_and_delete_drops_state(api, clock):
    api.create(new_slo("t", "ttft_p99", 1.0, window_s=1e6))
    ev = _api_eval(api, clock)
    ev.evaluate(clock())
    feed(ev, clock, "ttft", [0.5] * 5)
    assert ev.evaluate(clock())[0]["samples"] == 5
    # target edit = a new objective: windows restart from zero
    obj = api.get("SLO", "default", "t")
    obj["spec"]["objective"]["target"] = 2.0
    api.update(obj)
    assert ev.evaluate(clock())[0]["samples"] == 0
    api.delete("SLO", "default", "t")
    assert ev.evaluate(clock()) == []
    assert ev.status("t") is None


def test_invalid_slo_object_is_skipped_not_fatal(api, clock):
    api.create({"apiVersion": "slo.kubedl.io/v1alpha1", "kind": "SLO",
                "metadata": {"name": "broken"},
                "spec": {"signal": "nope_p99",
                         "objective": {"target": 1.0}}})
    # an out-of-range quantile must be rejected at PARSE time — an
    # unchecked one would crash every evaluation pass (and with it
    # every reconcile riding maybe_scan) inside Histogram.quantile
    api.create({"apiVersion": "slo.kubedl.io/v1alpha1", "kind": "SLO",
                "metadata": {"name": "bad-q"},
                "spec": {"signal": "metric:kubedl_x",
                         "objective": {"target": 1.0, "quantile": 5.0}}})
    api.create(new_slo("ok", "ttft_p99", 1.0, window_s=1e6))
    ev = _api_eval(api, clock, metrics=None)
    ev.registry = Registry()
    statuses = ev.evaluate(clock())
    assert [s["name"] for s in statuses] == ["ok"]
    listed = ev.statuses()
    assert [s["name"] for s in listed] == ["ok", "bad-q", "broken"]
    assert "quantile" in listed[1]["invalid"]
    assert "unknown signal" in listed[2]["invalid"]


def test_preset_uid_honored_for_slo_only(api, clock):
    """The deterministic-replay seam: SLO creates keep a caller-set uid
    (so the replay's control objects never consume the uid factory),
    while every other kind still gets a fresh server-assigned uid — a
    stale fetched dict must never recreate a job under its old
    identity."""
    obj = api.create(new_slo("pinned", "ttft_p99", 1.0, uid="slo-pinned"))
    assert m.uid(obj) == "slo-pinned"
    job = new_test_job("j", workers=1)
    job["metadata"]["uid"] = "stale-uid"
    created = api.create(job)
    assert m.uid(created) != "stale-uid"


# ---------------------------------------------------------------------------
# signal feeds: gauge, registry metric, lifecycle traces, request spans
# ---------------------------------------------------------------------------


def test_fleet_goodput_gauge_signal(clock):
    class Acct:
        jobs = 0

        def fleet_goodput(self):
            return self.ratio
    acct = Acct()
    ev = make_eval(clock, goodput=acct)
    ev.add(new_slo("gp", "fleet_goodput", 0.3, goal=0.5, window_s=1e6))
    ev.evaluate(clock())                      # jobs == 0: no sample yet
    assert ev.status("gp")["samples"] == 0
    acct.jobs, acct.ratio = 5, 0.6
    clock.advance(1.0)
    ev.evaluate(clock())
    acct.ratio = 0.1
    clock.advance(1.0)
    s = ev.evaluate(clock())[0]
    assert s["samples"] == 2 and s["goodSamples"] == 1


def test_registry_metric_signals_histogram_and_gauge(clock):
    reg = Registry()
    h = reg.histogram("kubedl_step_seconds", "", (),
                      buckets=(0.1, 0.5, 1.0, 5.0))
    g = reg.gauge("kubedl_depth", "", ())
    ev = make_eval(clock, registry=reg)
    ev.add(new_slo("step-p50", "metric:kubedl_step_seconds:p50", 0.6,
                   goal=0.5, window_s=1e6))
    ev.add(new_slo("depth", "metric:kubedl_depth", 10.0, goal=0.5,
                   window_s=1e6))
    # never-written series yield NO samples (a typo'd family/selector
    # must not fabricate an always-0.0 signal)
    ev.evaluate(clock())
    assert ev.status("step-p50")["samples"] == 0
    assert ev.status("depth")["samples"] == 0
    for v in (0.2, 0.2, 0.2, 2.0):
        h.observe(v)
    g.set(99.0)
    clock.advance(1.0)
    ev.evaluate(clock())
    s = ev.status("step-p50")
    assert s["samples"] == 1 and s["goodSamples"] == 1   # p50 ~ 0.3
    d = ev.status("depth")
    assert d["samples"] == 1 and d["goodSamples"] == 0   # 99 > 10
    g.set(3.0)
    clock.advance(1.0)
    ev.evaluate(clock())
    d = ev.status("depth")
    assert d["samples"] == 2 and d["goodSamples"] == 1
    # a selector key the family doesn't carry must yield NO samples —
    # _Metric._key would silently drop it and read the wrong (global)
    # series while the operator believes the objective is scoped
    ev.add(new_slo("scoped", "metric:kubedl_depth", 10.0, goal=0.5,
                   window_s=1e6, selector={"queue": "prod"}))
    clock.advance(1.0)
    ev.evaluate(clock())
    assert ev.status("scoped")["samples"] == 0


def test_histogram_quantile_against_percentile():
    """The quantile estimator vs utils/stats.percentile on samples
    spread uniformly through the buckets: linear interpolation within a
    bucket must land within one bucket's width of the sample truth."""
    reg = Registry()
    h = reg.histogram("h", "", (), buckets=(0.25, 0.5, 0.75, 1.0))
    samples = [i / 100.0 for i in range(1, 101)]          # 0.01..1.00
    for v in samples:
        h.observe(v)
    for q in (0.25, 0.5, 0.9, 0.99):
        est = h.quantile(q)
        truth = percentile(samples, q, method="linear")
        assert abs(est - truth) <= 0.05, (q, est, truth)
    # exact at bucket boundaries
    assert h.quantile(0.5) == pytest.approx(0.5)
    assert h.quantile(1.0) == pytest.approx(1.0)


def test_histogram_quantile_edges():
    reg = Registry()
    h = reg.histogram("h", "", ("kind",), buckets=(1.0, 2.0))
    assert h.quantile(0.5, kind="a") is None              # empty
    h.observe(99.0, kind="a")                             # +Inf only
    assert h.quantile(0.99, kind="a") == pytest.approx(2.0)  # clamped
    h.observe(0.5, kind="b")                              # labels route
    assert h.quantile(0.5, kind="b") == pytest.approx(0.5)
    assert h.quantile(0.5, kind="a") == pytest.approx(2.0)
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_lifecycle_trace_feed_via_fleet_telemetry(api, clock):
    """on_job_terminal feeds queue_delay + restart_mttr samples labelled
    with the job's queue, and maybe_scan drives the evaluator."""
    tr = trace.Tracer(enabled=True, clock=clock)
    ev = _api_eval(api, clock)
    api.create(new_slo("qd", "queue_delay_p99", 5.0, goal=0.5,
                       window_s=1e6, selector={"queue": "prod"}))
    api.create(new_slo("mttr", "restart_mttr_p50", 10.0, goal=0.5,
                       window_s=1e6))
    tel = FleetTelemetry(api, tr, job_kinds=("TestJob",), slo=ev)
    api.create(new_test_job(
        "j1", workers=1,
        run_policy={"schedulingPolicy": {"queue": "prod"}}))
    job = api.get("TestJob", "default", "j1")
    tid, root = trace.job_trace_context(job)
    t = clock()
    plan = (("Queuing", 10.0), ("Running", 5.0), ("Restarting", 4.0),
            ("Running", 20.0), ("Succeeded", 0.0))
    for phase, dur in plan:
        tr.record(phase, t, t + dur, trace_id=tid, parent_id=root,
                  component="lifecycle",
                  attributes={"phase": phase, "job": "default/j1"})
        t += dur
    tel.maybe_scan(clock())                  # registers the objectives
    tel.on_job_terminal(job)
    qd = ev.status("qd")
    assert qd["samples"] == 1 and qd["goodSamples"] == 0   # 10s > 5s
    mttr = ev.status("mttr")
    assert mttr["samples"] == 1 and mttr["goodSamples"] == 1   # 4s <= 10s


def test_request_span_harvester_dedup(clock):
    tr = trace.Tracer(enabled=True, clock=clock)
    ev = make_eval(clock, tracer=tr)
    ev.add(new_slo("ttft", "ttft_p99", 1.0, window_s=1e6))
    ev.add(new_slo("q", "queue_p99", 1.0, window_s=1e6))
    t = clock()
    tr.record("request.queue", t, t + 0.4, trace_id="a" * 32,
              component="serving")
    tr.record("request.prefill", t + 0.4, t + 0.9, trace_id="a" * 32,
              component="serving")
    tr.record("request.queue", t, t + 2.0, trace_id="b" * 32,
              component="serving", attributes={"resumed": True})
    ev.evaluate(clock())
    assert ev.status("ttft")["samples"] == 1       # 0.9s TTFT, good
    assert ev.status("ttft")["goodSamples"] == 1
    assert ev.status("q")["samples"] == 1          # resumed excluded
    ev.evaluate(clock())                           # same ring: no dupes
    assert ev.status("ttft")["samples"] == 1
    assert ev.status("q")["samples"] == 1


def test_harvester_ring_clearing_mode_frees_completed_requests(clock):
    """Review regression: in prune=False (ring-clearing) mode the
    harvester frees a request's bookkeeping when its root span
    completes — a day of tens of thousands of requests must not grow
    _seen/_done/_qstart for the whole run."""
    from kubedl_tpu.telemetry.slo import RequestSpanHarvester
    harv = RequestSpanHarvester(prune=False)
    t = clock()
    for i in range(5):
        tid = f"{i:032x}"
        spans = [
            trace.Span(tid, f"q{i}", "request.queue", t, t + 0.2),
            trace.Span(tid, f"p{i}", "request.prefill", t + 0.2, t + 0.5),
            trace.Span(tid, f"r{i}", "serving.request", t, t + 1.0),
        ]
        out = harv.feed(spans)        # cleared-ring batches
        assert [o[0] for o in out] == ["queue", "ttft"]
        t += 2.0
    assert harv._seen == {} and harv._done == {}
    assert harv._qstart == {} and harv._trace_spans == {}


# ---------------------------------------------------------------------------
# console + operator wiring
# ---------------------------------------------------------------------------


def _console(proxy):
    return ConsoleServer(proxy, ConsoleConfig(host="127.0.0.1", port=0,
                                              users={}))


def _route(server, method, path, params=None):
    status, payload, _ = server.route(method, path, params or {}, b"", None)
    return status, payload


def test_console_slo_endpoints(api, clock):
    api.create(new_slo("ttft", "ttft_p99", 1.0, window_s=1e6))
    ev = _api_eval(api, clock)
    ev.evaluate(clock())
    tr = trace.Tracer(enabled=True, clock=clock)
    tel = FleetTelemetry(api, tr, job_kinds=("TestJob",), slo=ev)
    server = _console(DataProxy(api, None, None, telemetry=tel))
    try:
        status, payload = _route(server, "GET", "/api/v1/slo/list")
        assert status == 200
        assert [s["name"] for s in payload["data"]] == ["ttft"]
        status, payload = _route(server, "GET", "/api/v1/slo/status/ttft")
        assert status == 200
        assert payload["data"]["budgetRemaining"] == 1.0
        status, _ = _route(server, "GET", "/api/v1/slo/status/ghost")
        assert status == 404
        # an EXISTING object with a bad spec answers 200 + the parse
        # error (the drill-down must agree with the listing, not 404)
        api.create({"apiVersion": "slo.kubedl.io/v1alpha1", "kind": "SLO",
                    "metadata": {"name": "broke"},
                    "spec": {"signal": "nope_p99",
                             "objective": {"target": 1.0}}})
        ev.evaluate(clock())
        status, payload = _route(server, "GET",
                                 "/api/v1/slo/status/broke")
        assert status == 200
        assert "unknown signal" in payload["data"]["invalid"]
    finally:
        server._httpd.server_close()


def test_console_slo_501_when_gated_off(api, clock):
    # telemetry on but SLO off is STILL 501 — the gates are separate
    tr = trace.Tracer(enabled=True, clock=clock)
    tel = FleetTelemetry(api, tr, job_kinds=("TestJob",))
    for proxy in (DataProxy(api, None, None),
                  DataProxy(api, None, None, telemetry=tel)):
        server = _console(proxy)
        try:
            status, payload = _route(server, "GET", "/api/v1/slo/list")
            assert status == 501 and "SLO engine" in payload["msg"]
            status, _ = _route(server, "GET", "/api/v1/slo/status/x")
            assert status == 501
        finally:
            server._httpd.server_close()


def test_operator_gate_wiring_slo():
    op = build_operator(APIServer(), OperatorConfig(workloads=[]))
    assert op.telemetry is None
    assert "kubedl_slo_" not in op.metrics_registry.expose()
    gates = ft.FeatureGates()
    gates.set(ft.SLO_ENGINE, True)
    op2 = build_operator(APIServer(), OperatorConfig(workloads=[],
                                                     feature_gates=gates))
    # SLO implies telemetry implies tracing
    assert op2.telemetry is not None and op2.telemetry.slo is not None
    assert op2.tracer.enabled
    assert "kubedl_slo_budget_remaining_ratio" in \
        op2.metrics_registry.expose()
    # the flag route works too, and telemetry-without-slo stays slo-less
    op3 = build_operator(APIServer(), OperatorConfig(workloads=[],
                                                     enable_slo=True))
    assert op3.telemetry.slo is not None
    op4 = build_operator(APIServer(), OperatorConfig(workloads=[],
                                                     enable_telemetry=True))
    assert op4.telemetry is not None and op4.telemetry.slo is None
    assert "kubedl_slo_" not in op4.metrics_registry.expose()


# ---------------------------------------------------------------------------
# THE acceptance e2e: TTFT SLO over the serving replay
# ---------------------------------------------------------------------------


def _crowd_window(arrivals, width=15.0):
    """The densest ``width``-second arrival window (the flash crowd)."""
    times = sorted(a.arrival_s for a in arrivals)
    best, best_n, j = times[0], 0, 0
    for i, t in enumerate(times):
        while times[j] < t - width:
            j += 1
        if i - j + 1 > best_n:
            best_n, best = i - j + 1, times[j]
    return best, best + width


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [3, 11])
def test_e2e_ttft_slo_burn_fires_once_and_clears(seed):
    """Acceptance: one flash crowd in the serving day pushes TTFT past
    the objective; the SLO fires exactly one SLOBudgetBurn Event + True
    condition inside the crowd window, reports budget consumed within
    1% of the hand-computed value from the same spans, and clears after
    recovery."""
    from kubedl_tpu.replay import ServingReplay
    from kubedl_tpu.replay.workload import Profile, generate

    profile = Profile(
        name="slo-e2e", sim_seconds=3600.0, jobs=0, job_bursts=0,
        burst_frac=0.0, chaos_preemptions=0, capacity={},
        serving_requests=220, serving_bursts=1, serving_burst_frac=0.85,
        lanes=2, max_len=64, kv_block=8, pool_blocks=48, prefixes=4,
        serving_trace_capacity=16384)
    wl = generate(profile, seed)
    # drain_every=32: evaluate the burn windows while the crowd is hot
    # (the bench default of 512 samples too coarsely for a 15s crowd)
    replay = ServingReplay(wl, drain_every=32)
    api = APIServer(clock=replay.clock)
    target, goal = 0.6, 0.9
    api.create(new_slo(
        "serving-ttft", "ttft_p99", target, goal=goal,
        window_s=4.0 * profile.sim_seconds,
        alerting=_single_pair(short=30.0, long_=120.0, burn=3.0)))
    mt = SLOMetrics(Registry())
    replay.slo = SLOEvaluator(api=api, clock=replay.clock, metrics=mt,
                              recorder=Recorder(api),
                              evaluate_interval_s=5.0)
    res = replay.run()
    assert res["errors"] == 0

    # exactly one onset + one recovery, in order
    burns = [e for e in api.list("Event")
             if e.get("reason") == REASON_SLO_BURN]
    recovered = [e for e in api.list("Event")
                 if e.get("reason") == REASON_SLO_RECOVERED]
    assert len(burns) == 1, (seed, [a for a in replay.slo.alert_log])
    assert len(recovered) == 1, (seed, replay.slo.alert_log)
    assert [a["event"] for a in replay.slo.alert_log] == \
        ["fire", "clear"], seed
    assert mt.alerts.value(slo="serving-ttft", severity="page") == 1

    # the onset lands inside the flash-crowd window (plus evaluation
    # cadence slack)
    lo, hi = _crowd_window(wl.serving)
    t0 = replay.clock.t0
    fire_t = replay.slo.alert_log[0]["t"] - t0
    assert lo - 1.0 <= fire_t <= hi + 60.0, (seed, fire_t, lo, hi)

    # cleared after recovery: condition False, Recovered event after Burn
    obj = api.get("SLO", "default", "serving-ttft")
    cond = [cd for cd in obj["status"]["conditions"]
            if cd.get("type") == SLO_BURN_RATE]
    assert len(cond) == 1 and cond[0]["status"] == "False"
    assert cond[0]["reason"] == REASON_SLO_RECOVERED

    # budget consumed matches the hand-computed value from the SAME
    # spans the replay reports (the compliance window spans the run)
    status = replay.slo.status("serving-ttft")
    assert status["samples"] == len(res["ttfts_s"]) == len(wl.serving)
    bad = sum(1 for v in res["ttfts_s"] if v > target)
    hand = (bad / len(res["ttfts_s"])) / (1.0 - goal)
    assert bad > 0, seed
    assert status["budgetConsumed"] == pytest.approx(hand, rel=0.01), seed


# ---------------------------------------------------------------------------
# disabled path: byte-identical behavior (the PR 5/7 convention)
# ---------------------------------------------------------------------------


def test_disabled_path_leaves_no_artifacts(clock):
    """Gate off (the default): a chaos-seeded day leaves no SLO objects,
    no SLOBurnRate conditions, no kubedl_slo_* metric families, and the
    console endpoints answer 501."""
    inner = APIServer(clock=clock)
    chaos = ChaosAPIServer(inner, ChaosConfig(
        seed=7, conflict_on_status_update=0.1, error_on_create=0.08,
        max_faults=10))
    op = build_operator(chaos, OperatorConfig(workloads=[]))
    assert op.telemetry is None
    manager = Manager(chaos, clock=clock)
    engine = JobEngine(
        chaos, TestJobController(),
        EngineConfig(retry_policy=RetryPolicy(attempts=4, base=0.01,
                                              cap=0.05),
                     retry_sleep=clock.advance,
                     backoff_jitter_seed=1))
    assert engine.telemetry is None
    manager.register(engine)
    for i in range(3):
        inner.create(new_test_job(f"plain-{i}", workers=2))
        clock.advance(1.0)
    manager.run_until_idle(max_iterations=2000)
    run_all_pods(chaos)
    manager.run_until_idle(max_iterations=2000)
    for pod in inner.list("Pod"):
        set_pod_phase(chaos, pod, "Succeeded", exit_code=0)
    manager.run_until_idle(max_iterations=2000)
    for i in range(3):
        job = inner.get("TestJob", "default", f"plain-{i}")
        assert st.is_succeeded(c.JobStatus.from_dict(job.get("status")))
        assert not any(cd.get("type") == SLO_BURN_RATE
                       for cd in m.get_in(job, "status", "conditions",
                                          default=[]) or [])
    assert inner.list("SLO") == []
    assert not any(e.get("reason") in (REASON_SLO_BURN,
                                       REASON_SLO_RECOVERED)
                   for e in inner.list("Event"))
    assert "kubedl_slo_" not in op.metrics_registry.expose()
    server = _console(DataProxy(inner, None, None))
    try:
        status, _ = _route(server, "GET", "/api/v1/slo/list")
        assert status == 501
        status, _ = _route(server, "GET", "/api/v1/slo/status/x")
        assert status == 501
        status, _ = _route(server, "GET", "/api/v1/telemetry/goodput")
        assert status == 501
    finally:
        server._httpd.server_close()
