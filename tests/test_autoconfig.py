"""Multi-dimensional serving autoconfig (Morphling-depth, VERDICT r3 #6):
{batch x int8 x speculative-k} searched under p99-latency + TTFT SLOs,
with the chosen config rendered into predictor env by the operator."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.serving import (Candidate, ServingSLO, autoconfigure_multi)
from kubedl_tpu.serving.autoconfig import probe_candidate

#: compile-heavy compute suite: excluded from `make test`'s fast path
pytestmark = pytest.mark.slow


def fake_measure(cand: Candidate):
    """Deterministic cost model: int8 halves per-token latency but
    changes outputs; speculative amortizes target passes (faster, still
    greedy-identical); bigger batches raise throughput AND latency.
    (Speculative composes with any lane count — the engine runs draft
    rounds per lane.)"""
    lat = 10.0
    if cand.quantize == "int8":
        lat *= 0.55
    if cand.speculative_k > 0:
        lat *= 0.5
    lat *= 1.0 + 0.15 * (cand.batch - 1)
    tps = cand.batch * 1000.0 / lat
    return {"batch": cand.batch, "quantize": cand.quantize or "",
            "speculative_k": cand.speculative_k,
            "decode_tokens_per_s": round(tps, 2),
            "p50_latency_ms": lat, "p99_latency_ms": lat * 1.1,
            "ttft_ms": 30.0 + 5.0 * cand.batch}


def test_latency_bound_slo_picks_int8_speculative():
    """Under a tight per-token SLO only the int8+speculative family
    fits; the search must find it rather than a bigger-batch fp config."""
    slo = ServingSLO(p99_latency_ms=4.0, ttft_ms=100.0)
    res = autoconfigure_multi(measure=fake_measure, slo=slo,
                              batches=(1, 2, 4), spec_ks=(0, 4))
    assert res.best.quantize == "int8"
    assert res.best.speculative_k == 4
    assert res.best_probe["p99_latency_ms"] <= 4.0
    # every reported measurement carries the TTFT the SLO constrained
    assert all("ttft_ms" in p for p in res.measurements)


def test_quality_pinned_slo_excludes_int8():
    """Quality-pinned: target quantization is off the table entirely
    (never probed), and the winner is the best full-precision config —
    speculative stays allowed because it is greedy-identical."""
    slo = ServingSLO(p99_latency_ms=20.0, pinned_quality=True)
    res = autoconfigure_multi(measure=fake_measure, slo=slo,
                              batches=(1, 2, 4), spec_ks=(0, 4))
    assert res.best.quantize is None
    assert all(p["quantize"] == "" for p in res.measurements)
    # throughput-max among feasible fp configs (batch grows tps under
    # this cost model until the SLO bites)
    feasible = [p for p in res.measurements
                if p["p99_latency_ms"] <= 20.0]
    assert res.best_probe["decode_tokens_per_s"] == max(
        p["decode_tokens_per_s"] for p in feasible)


def test_nothing_feasible_returns_least_violating():
    slo = ServingSLO(p99_latency_ms=0.001)
    res = autoconfigure_multi(measure=fake_measure, slo=slo,
                              batches=(1, 2), spec_ks=(0, 4))
    # the least-bad config is the lowest-latency point in the space
    assert res.best.quantize == "int8" and res.best.speculative_k == 4


def test_env_contract_roundtrip():
    cand = Candidate(batch=4, quantize="int8", speculative_k=2,
                     kv_block=32, pool_blocks=64)
    env = cand.to_env()
    assert env == {"KUBEDL_SERVING_LANES": "4",
                   "KUBEDL_SERVING_QUANTIZE": "int8",
                   "KUBEDL_SERVING_SPEC_K": "2",
                   "KUBEDL_SERVING_KV_BLOCK": "32",
                   "KUBEDL_SERVING_POOL_BLOCKS": "64"}


@pytest.fixture(scope="module")
def tiny_models():
    tcfg = dataclasses.replace(llama.tiny(vocab=128), dtype=jnp.float32)
    tparams = llama.init_params(tcfg, jax.random.PRNGKey(0))
    dcfg = dataclasses.replace(llama.tiny(vocab=128), d_model=64,
                               n_layers=1, n_heads=2, n_kv_heads=2,
                               d_ff=128, dtype=jnp.float32)
    dparams = llama.init_params(dcfg, jax.random.PRNGKey(1))
    return (tcfg, tparams), (dcfg, dparams)


def test_live_probe_all_dimensions(tiny_models):
    """Real engines: every dimension of the space is probeable and the
    probes carry the SLO-relevant numbers."""
    model, draft = tiny_models
    for cand in (Candidate(batch=2),
                 Candidate(batch=1, quantize="int8"),
                 Candidate(batch=1, speculative_k=2),
                 # speculative x continuous batching: the draft-k
                 # dimension probes the LANE path (VERDICT r4 next #3)
                 Candidate(batch=2, speculative_k=2)):
        probe = probe_candidate(model, cand, prompt_len=8, new_tokens=4,
                                draft=draft, repeats=2)
        assert probe is not None
        assert probe["decode_tokens_per_s"] > 0
        assert probe["ttft_ms"] > 0
        assert probe["p99_latency_ms"] >= probe["p50_latency_ms"]
    # speculative without a draft model is unbuildable, not an error
    assert probe_candidate(model, Candidate(speculative_k=2),
                           prompt_len=8, new_tokens=4) is None


@pytest.fixture
def op_serving(api):
    from kubedl_tpu.controllers.registry import OperatorConfig, build_operator
    return build_operator(api, OperatorConfig(gang_scheduler_name=""))


def test_operator_renders_autoconfig_env(api, op_serving):
    """The write-back half: the Inference CR's autoconfig annotation
    lands in every predictor container's env."""
    from kubedl_tpu.core import meta as m
    from kubedl_tpu.platform.serving import ANNOTATION_AUTOCONFIG

    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "svc", "namespace": "default",
                     "annotations": {ANNOTATION_AUTOCONFIG: json.dumps(
                         {"batch": 4, "quantize": "int8",
                          "speculativeK": 2,
                          "draftPath": "/models/draft"})}},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "main", "replicas": 1, "template": {"spec": {
                "containers": [{"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    deploy = api.get("Deployment", "default", "svc-main")
    ct = m.get_in(deploy, "spec", "template", "spec", "containers")[0]
    env = {e["name"]: e.get("value") for e in ct["env"]}
    assert env["KUBEDL_SERVING_LANES"] == "4"
    assert env["KUBEDL_SERVING_QUANTIZE"] == "int8"
    assert env["KUBEDL_SERVING_SPEC_K"] == "2"
    assert env["KUBEDL_SERVING_DRAFT_PATH"] == "/models/draft"
    # the predictor Service targets the entrypoint's bound port
    assert env["KUBEDL_SERVING_PORT"] == "8000"


def test_speculative_without_draft_degrades(api, op_serving):
    """speculativeK without draftPath must serve non-speculatively (the
    entrypoint would CrashLoop otherwise), not render a broken config."""
    from kubedl_tpu.core import meta as m
    from kubedl_tpu.platform.serving import ANNOTATION_AUTOCONFIG

    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "nodraft", "namespace": "default",
                     "annotations": {ANNOTATION_AUTOCONFIG: json.dumps(
                         {"batch": 2, "speculativeK": 4})}},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "main", "replicas": 1, "template": {"spec": {
                "containers": [{"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    deploy = api.get("Deployment", "default", "nodraft-main")
    ct = m.get_in(deploy, "spec", "template", "spec", "containers")[0]
    env = {e["name"]: e.get("value") for e in ct["env"]}
    assert env["KUBEDL_SERVING_SPEC_K"] == "0"
    assert "KUBEDL_SERVING_DRAFT_PATH" not in env


def test_operator_tolerates_bad_autoconfig_values(api, op_serving):
    """Valid JSON with junk values must degrade to a warning, not a
    reconcile retry-loop."""
    from kubedl_tpu.core import meta as m
    from kubedl_tpu.platform.serving import ANNOTATION_AUTOCONFIG

    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "bad", "namespace": "default",
                     "annotations": {ANNOTATION_AUTOCONFIG:
                                     '{"batch": "fast"}'}},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "main", "replicas": 1, "template": {"spec": {
                "containers": [{"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    deploy = api.get("Deployment", "default", "bad-main")
    ct = m.get_in(deploy, "spec", "template", "spec", "containers")[0]
    env = {e["name"] for e in ct.get("env", [])}
    assert "KUBEDL_SERVING_LANES" not in env  # config skipped, deploy fine


def test_predictor_autoscale_renders_hpa(api, op_serving):
    """autoScale on a predictor creates an autoscaling/v2 HPA owned by
    the Inference, and the Deployment diff adopts the HPA's live replica
    count instead of stomping it (VERDICT parity+: the reference only
    stores an ObjectReference to an external autoscaler)."""
    from kubedl_tpu.core import meta as m

    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "auto", "namespace": "default"},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "main", "replicas": 1,
             "autoScale": {"minReplicas": 2, "maxReplicas": 5},
             "template": {"spec": {"containers": [
                 {"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    hpa = api.get("HorizontalPodAutoscaler", "default", "auto-main")
    assert hpa["spec"]["minReplicas"] == 2
    assert hpa["spec"]["maxReplicas"] == 5
    assert hpa["spec"]["scaleTargetRef"]["name"] == "auto-main"
    assert hpa["spec"]["metrics"][0]["resource"]["name"] == "cpu"
    assert m.get_in(hpa, "metadata", "ownerReferences")[0]["kind"] \
        == "Inference"

    # simulate the HPA scaling the deployment; a later reconcile must
    # not reset replicas back to the predictor spec
    deploy = api.get("Deployment", "default", "auto-main")
    deploy["spec"]["replicas"] = 4
    api.update(deploy)
    inf = api.get("Inference", "default", "auto")
    inf["metadata"]["labels"] = {"touch": "1"}   # force a respec
    api.update(inf)
    op_serving.run_until_idle(max_iterations=50)
    assert api.get("Deployment", "default",
                   "auto-main")["spec"]["replicas"] == 4

    # dropping autoScale deletes the HPA
    inf = api.get("Inference", "default", "auto")
    del inf["spec"]["predictors"][0]["autoScale"]
    api.update(inf)
    op_serving.run_until_idle(max_iterations=50)
    assert api.try_get("HorizontalPodAutoscaler", "default",
                       "auto-main") is None


def test_predictor_autoscale_invalid_is_skipped(api, op_serving):
    """maxReplicas < minReplicas: warning event, no HPA, predictor still
    deploys."""
    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "badscale", "namespace": "default"},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "p", "replicas": 1,
             "autoScale": {"minReplicas": 4, "maxReplicas": 2},
             "template": {"spec": {"containers": [
                 {"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    assert api.get("Deployment", "default", "badscale-p")
    assert api.try_get("HorizontalPodAutoscaler", "default",
                       "badscale-p") is None
    events = [e for e in api.list("Event", "default")
              if e.get("reason") == "InvalidAutoScale"]
    assert events


def test_predictor_removal_prunes_hpa(api, op_serving):
    """Removing a predictor (not just its autoScale) deletes its HPA
    along with the Deployment/Service."""
    inf = {
        "apiVersion": "serving.kubedl.io/v1alpha1", "kind": "Inference",
        "metadata": {"name": "prune", "namespace": "default"},
        "spec": {"framework": "JAXServing", "predictors": [
            {"name": "a", "replicas": 1,
             "autoScale": {"minReplicas": 1, "maxReplicas": 3},
             "template": {"spec": {"containers": [
                 {"name": "srv", "image": "img"}]}}},
            {"name": "b", "replicas": 1,
             "template": {"spec": {"containers": [
                 {"name": "srv", "image": "img"}]}}}]},
    }
    api.create(inf)
    op_serving.run_until_idle(max_iterations=50)
    assert api.get("HorizontalPodAutoscaler", "default", "prune-a")

    inf = api.get("Inference", "default", "prune")
    inf["spec"]["predictors"] = inf["spec"]["predictors"][1:]   # drop a
    api.update(inf)
    op_serving.run_until_idle(max_iterations=50)
    assert api.try_get("Deployment", "default", "prune-a") is None
    assert api.try_get("HorizontalPodAutoscaler", "default",
                       "prune-a") is None
    assert api.get("Deployment", "default", "prune-b")
