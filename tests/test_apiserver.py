"""In-memory API server semantics: CRUD, RV conflicts, finalizers, GC."""

import pytest

from kubedl_tpu.core import meta as m
from kubedl_tpu.core.apiserver import AlreadyExists, Conflict, NotFound


def mk(kind="PyTorchJob", name="job1", ns="default", spec=None):
    return m.new_obj("training.kubedl.io/v1alpha1", kind, name, ns,
                     spec=spec if spec is not None else {"x": 1})


def test_create_get_list_delete(api):
    obj = api.create(mk())
    assert m.uid(obj)
    assert m.generation(obj) == 1
    got = api.get("PyTorchJob", "default", "job1")
    assert got["spec"] == {"x": 1}
    assert api.list("PyTorchJob") and not api.list("TFJob")
    api.delete("PyTorchJob", "default", "job1")
    with pytest.raises(NotFound):
        api.get("PyTorchJob", "default", "job1")


def test_create_duplicate(api):
    api.create(mk())
    with pytest.raises(AlreadyExists):
        api.create(mk())


def test_update_conflict_and_generation(api):
    obj = api.create(mk())
    stale_rv = m.resource_version(obj)
    obj["spec"] = {"x": 2}
    obj = api.update(obj)
    assert m.generation(obj) == 2  # spec change bumps generation

    # stale writer loses
    stale = mk(spec={"x": 3})
    stale["metadata"]["resourceVersion"] = stale_rv
    with pytest.raises(Conflict):
        api.update(stale)

    # status update does not bump generation
    obj["status"] = {"phase": "Running"}
    obj = api.update_status(obj)
    assert m.generation(obj) == 2
    assert api.get("PyTorchJob", "default", "job1")["status"] == {"phase": "Running"}


def test_status_update_does_not_touch_spec(api):
    obj = api.create(mk())
    upd = {"apiVersion": obj["apiVersion"], "kind": "PyTorchJob",
           "metadata": {"name": "job1", "namespace": "default"},
           "spec": {"x": 999}, "status": {"ok": True}}
    api.update(upd, subresource="status")
    got = api.get("PyTorchJob", "default", "job1")
    assert got["spec"] == {"x": 1}
    assert got["status"] == {"ok": True}


def test_finalizer_blocks_delete(api):
    obj = mk()
    obj["metadata"]["finalizers"] = ["kubedl.io/preempt-protector"]
    api.create(obj)
    api.delete("PyTorchJob", "default", "job1")
    got = api.get("PyTorchJob", "default", "job1")
    assert m.is_deleting(got)
    got["metadata"]["finalizers"] = []
    api.update(got)
    with pytest.raises(NotFound):
        api.get("PyTorchJob", "default", "job1")


def test_cascading_gc(api):
    owner = api.create(mk())
    pod = m.new_obj("v1", "Pod", "job1-worker-0", "default", spec={})
    m.set_controller_ref(pod, owner)
    api.create(pod)
    assert len(api.list("Pod")) == 1
    api.delete("PyTorchJob", "default", "job1")
    assert api.list("Pod") == []


def test_label_selector_list(api):
    for i in range(3):
        p = m.new_obj("v1", "Pod", f"p{i}", "default",
                      labels={"job-name": "j" if i < 2 else "k"})
        api.create(p)
    assert len(api.list("Pod", selector={"job-name": "j"})) == 2
    assert len(api.list("Pod", selector={"matchLabels": {"job-name": "k"}})) == 1
    sel = {"matchExpressions": [{"key": "job-name", "operator": "In", "values": ["j"]}]}
    assert len(api.list("Pod", selector=sel)) == 2


def test_patch_merge(api):
    api.create(mk())
    api.patch_merge("PyTorchJob", "default", "job1",
                    {"metadata": {"annotations": {"a": "1"}}})
    got = api.get("PyTorchJob", "default", "job1")
    assert got["metadata"]["annotations"] == {"a": "1"}
    # deep merge keeps siblings, None deletes
    api.patch_merge("PyTorchJob", "default", "job1",
                    {"metadata": {"annotations": {"b": "2"}}})
    api.patch_merge("PyTorchJob", "default", "job1",
                    {"metadata": {"annotations": {"a": None}}})
    got = api.get("PyTorchJob", "default", "job1")
    assert got["metadata"]["annotations"] == {"b": "2"}


def test_watch_events(api):
    events = []
    cancel = api.watch(lambda t, o: events.append((t, m.name(o))))
    api.create(mk())
    obj = api.get("PyTorchJob", "default", "job1")
    obj["spec"] = {"x": 5}
    api.update(obj)
    api.delete("PyTorchJob", "default", "job1")
    assert events == [("ADDED", "job1"), ("MODIFIED", "job1"), ("DELETED", "job1")]
    cancel()
    api.create(mk(name="job2"))
    assert len(events) == 3
