"""Durable, sharded control plane (docs/durability.md).

Four layers:

* **journal** — WAL round trips, snapshot rotation, torn-tail tolerance,
  resourceVersion resumption, fsync group-commit accounting;
* **resumable watches** — bookmark replay from the bounded per-kind event
  ring, too-old fallback (counted), informer resume vs full relist;
* **sharded ownership** — consistent shard hash, shard-deterministic
  ``run_until_idle`` order, per-shard lease handoff between two operator
  candidates, unowned shards parking until the lease comes back;
* **THE crash-mid-storm chaos e2e** — a seeded fault storm is killed
  mid-flight, a fresh operator recovers the exact pre-crash store from
  snapshot + WAL replay, informers resume via bookmark with zero full
  relists, and the recovered world converges to parity with a
  never-crashed reference run.

Gate-off behavior is byte-identical to the pre-durability control plane
and pinned here (no journal, no ring, deletes allocate no
resourceVersion, no ``kubedl_journal_*``/``kubedl_watch_*``/
``kubedl_shard_*`` families, one reconcile shard).
"""

import copy
import os

import pytest

from kubedl_tpu.api.common import JobStatus
from kubedl_tpu.client.informers import Informer
from kubedl_tpu.controllers.chaos import ChaosAPIServer, ChaosConfig
from kubedl_tpu.controllers.engine import EngineConfig, JobEngine
from kubedl_tpu.controllers.registry import OperatorConfig, build_operator
from kubedl_tpu.controllers.testing import (TestJobController, new_test_job,
                                            set_pod_phase)
from kubedl_tpu.core import meta as m
from kubedl_tpu.core.apiserver import APIServer, TooOldResourceVersion
from kubedl_tpu.core.clock import SimClock
from kubedl_tpu.core.journal import Journal, JournalCorrupt
from kubedl_tpu.core.leaderelection import ShardLeaseSet
from kubedl_tpu.core.manager import Manager, Reconciler, Request, shard_for
from kubedl_tpu.metrics.registry import DurabilityMetrics, Registry
from kubedl_tpu.scheduling.gang import CoschedulerPlugin
from kubedl_tpu.utils import status as st
from kubedl_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.durability


def cm(name, data=None):
    obj = m.new_obj("v1", "ConfigMap", name)
    if data is not None:
        obj["data"] = data
    return obj


# ---------------------------------------------------------------------------
# journal: WAL + snapshots + recovery
# ---------------------------------------------------------------------------


def test_wal_replay_round_trips_the_store(tmp_path, clock):
    api = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    api.create(cm("a", {"k": "1"}))
    b = api.create(cm("b"))
    b["data"] = {"k": "2"}
    api.update(b)
    api.create(cm("gone"))
    api.delete("ConfigMap", "default", "gone")
    rv = api.latest_resource_version()

    # "restart": a fresh store recovers from the same directory
    api2 = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    assert api2.latest_resource_version() == rv
    assert {m.name(o) for o in api2.list("ConfigMap")} == {"a", "b"}
    assert api2.get("ConfigMap", "default", "b")["data"] == {"k": "2"}
    # canonical state is exactly the pre-restart canonical state
    assert api2._objs == api._objs
    # the rv counter resumed: the next write is above everything replayed
    c = api2.create(cm("c"))
    assert m.resource_version(c) == rv + 1


def test_snapshot_rotation_and_recovery_from_snapshot_plus_tail(tmp_path,
                                                                clock):
    j = Journal(str(tmp_path), snapshot_every=5)
    api = APIServer(clock=clock, journal=j)
    for i in range(12):
        api.create(cm(f"o-{i:02d}"))
    assert j.snapshots_written >= 2
    # rotation dropped old generations: one snapshot + the live WAL +
    # the most recent sealed WAL (retained because a commit racing a
    # checkpoint lands in the pre-rotation file with an rv ABOVE the
    # snapshot's — filename rv bounds a file's minimum record rv only)
    names = sorted(os.listdir(tmp_path))
    assert sum(n.startswith("snap-") for n in names) == 1
    assert sum(n.startswith("wal-") for n in names) == 2

    j2 = Journal(str(tmp_path))
    api2 = APIServer(clock=clock, journal=j2)
    assert len(api2.list("ConfigMap")) == 12
    assert api2.latest_resource_version() == api.latest_resource_version()
    # provenance: newest snapshot plus a non-empty WAL tail
    assert j2.recovered_from["snapshot_rv"] > 0
    assert j2.recovered_from["wal_records"] == 2  # 12 commits, snap at 10


def test_torn_wal_tail_is_tolerated(tmp_path, clock):
    api = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    api.create(cm("ok"))
    [wal] = [n for n in os.listdir(tmp_path) if n.startswith("wal-")]
    with open(tmp_path / wal, "a") as f:
        f.write('{"t": "c", "rv": 99, "k": ["ConfigMap", "d')  # crash mid-append
    j2 = Journal(str(tmp_path))
    api2 = APIServer(clock=clock, journal=j2)
    assert [m.name(o) for o in api2.list("ConfigMap")] == ["ok"]
    assert j2.recovered_from["torn_records"] == 1
    assert api2.latest_resource_version() == 1


def test_append_after_torn_tail_does_not_glue_records(tmp_path, clock):
    """Review fix: reopening a WAL whose tail was torn by a crash must
    terminate the garbage line first — otherwise the first acknowledged
    post-restart append glues onto it and a SECOND recovery drops it."""
    api = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    api.create(cm("before"))
    [wal] = [n for n in os.listdir(tmp_path) if n.startswith("wal-")]
    with open(tmp_path / wal, "a") as f:
        f.write('{"t": "c", "rv": 9, "k": ["ConfigMap"')  # torn tail
    # restart 1: recovery tolerates the tear, then ACKNOWLEDGES a write
    api2 = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    api2.create(cm("after"))
    # restart 2: the acknowledged record must have survived
    api3 = APIServer(clock=clock, journal=Journal(str(tmp_path)))
    assert {m.name(o) for o in api3.list("ConfigMap")} \
        == {"before", "after"}
    assert api3.latest_resource_version() \
        == api2.latest_resource_version()


def test_recovery_falls_back_past_a_torn_snapshot(tmp_path, clock):
    j = Journal(str(tmp_path), snapshot_every=3)
    api = APIServer(clock=clock, journal=j)
    for i in range(4):
        api.create(cm(f"o-{i}"))
    # a torn NEWER snapshot (crash mid-checkpoint before the rename
    # completed would normally leave only a .tmp; simulate the rename
    # having landed on garbage bytes)
    with open(tmp_path / "snap-0000000000000099.json", "w") as f:
        f.write('{"rv": 99, "objects": [{"kind"')
    j2 = Journal(str(tmp_path))
    api2 = APIServer(clock=clock, journal=j2)
    assert len(api2.list("ConfigMap")) == 4
    assert j2.recovered_from["snapshot_rv"] == 3


def test_checkpoint_keeps_records_that_raced_it(tmp_path, clock):
    """Review fix: a commit racing the (outside-the-lock) checkpoint
    lands in the pre-rotation WAL generation with an rv ABOVE the
    snapshot's — the rotation must not unlink that file, or an
    acknowledged write is lost and the recovered rv counter regresses."""
    j = Journal(str(tmp_path), snapshot_every=10**9)
    api = APIServer(clock=clock, journal=j)
    for i in range(5):
        api.create(cm(f"o-{i}"))
    # the _maybe_snapshot interleaving: claim (rv, snaps), then another
    # writer commits before write_snapshot runs
    rv, snaps = api.latest_resource_version(), dict(api._snaps)
    api.create(cm("raced"))
    j.write_snapshot(rv, snaps)

    j2 = Journal(str(tmp_path))
    api2 = APIServer(clock=clock, journal=j2)
    assert api2.try_get("ConfigMap", "default", "raced") is not None
    assert api2.latest_resource_version() == rv + 1
    assert j2.recovered_from["wal_records"] == 1


def test_all_snapshots_unreadable_raises(tmp_path):
    with open(tmp_path / "snap-0000000000000001.json", "w") as f:
        f.write("not json")
    with pytest.raises(JournalCorrupt):
        Journal(str(tmp_path)).recover()


def test_fsync_group_commit_batches(tmp_path, clock):
    reg = Registry()
    dm = DurabilityMetrics(reg)
    j = Journal(str(tmp_path), fsync_every=8, metrics=dm)
    api = APIServer(clock=clock, journal=j, durability_metrics=dm)
    for i in range(20):
        api.create(cm(f"o-{i}"))
    assert dm.journal_appends.value() == 20
    # 20 appends / fsync_every=8 -> exactly 2 group fsyncs
    assert dm.journal_fsync.count() == 2
    j.flush()
    assert dm.journal_fsync.count() == 3


def test_empty_dir_recovers_to_empty(tmp_path):
    rv, objs = Journal(str(tmp_path)).recover()
    assert rv == 0 and objs == {}


# ---------------------------------------------------------------------------
# resumable watches: the bounded per-kind event ring
# ---------------------------------------------------------------------------


def test_watch_from_replays_only_post_bookmark_events(clock):
    api = APIServer(clock=clock, watch_ring=64)
    api.create(cm("a"))
    api.create(cm("b"))
    bookmark = api.latest_resource_version()
    api.create(cm("c"))
    cc = api.get("ConfigMap", "default", "c")
    cc["data"] = {"x": "1"}
    api.update(cc)
    api.delete("ConfigMap", "default", "a")

    events = []
    cancel, caught_up = api.watch_from(
        lambda t, o: events.append((t, m.name(o), m.resource_version(o))),
        bookmark)
    assert events == [("ADDED", "c", 3), ("MODIFIED", "c", 4),
                      ("DELETED", "a", 5)]  # tombstone carries the rv
    assert caught_up == api.latest_resource_version() == 5
    # live events flow after the replay
    api.create(cm("d"))
    assert events[-1] == ("ADDED", "d", 6)
    cancel()
    api.create(cm("e"))
    assert events[-1] == ("ADDED", "d", 6)


def test_watch_from_too_old_bookmark_counts_a_relist(clock):
    dm = DurabilityMetrics(Registry())
    api = APIServer(clock=clock, watch_ring=2, durability_metrics=dm)
    for i in range(6):
        api.create(cm(f"o-{i}"))
    with pytest.raises(TooOldResourceVersion):
        api.watch_from(lambda t, o: None, 0, kinds=("ConfigMap",))
    assert dm.watch_relists.value(reason="too_old") == 1
    # a fresh bookmark still resumes fine
    _, rv = api.watch_from(lambda t, o: None,
                           api.latest_resource_version(),
                           kinds=("ConfigMap",))
    assert rv == api.latest_resource_version()
    assert dm.watch_relists.value(reason="too_old") == 1


def test_ring_floors_are_per_kind(clock):
    api = APIServer(clock=clock, watch_ring=3)
    api.create(new_test_job("tj", workers=1))
    for i in range(6):                 # evicts ConfigMap entries only
        api.create(cm(f"o-{i}"))
    with pytest.raises(TooOldResourceVersion):
        api.watch_from(lambda t, o: None, 0, kinds=("ConfigMap",))
    # the TestJob ring never overflowed: bookmark 0 replays its ADDED
    got = []
    api.watch_from(lambda t, o: got.append(m.name(o)), 0,
                   kinds=("TestJob",))
    assert got == ["tj"]


def test_plain_store_has_no_ring_and_counts_the_fallback(clock):
    api = APIServer(clock=clock)
    with pytest.raises(TooOldResourceVersion):
        api.watch_from(lambda t, o: None, 0)


def test_informer_resumes_from_bookmark_without_relist(clock):
    api = APIServer(clock=clock, watch_ring=64)
    api.create(cm("a"))
    inf = Informer(api, "ConfigMap")
    inf.start()
    assert inf.lister().get("default", "a") is not None

    inf.disconnect()                     # dropped watch connection
    api.create(cm("b"))
    aa = api.get("ConfigMap", "default", "a")
    aa["data"] = {"v": "2"}
    api.update(aa)
    api.create(new_test_job("foreign", workers=1))  # other kinds advance rv

    inf.resume()
    assert inf.bookmark_resumes == 1 and inf.full_relists == 0
    assert inf.lister().get("default", "b") is not None
    assert inf.lister().get("default", "a")["data"] == {"v": "2"}
    # live again
    api.create(cm("c"))
    assert inf.lister().get("default", "c") is not None


def test_relist_fallback_repairs_stale_and_ghost_cache_entries(clock):
    """Review fix: the too-old fallback must be a client-go Replace(),
    not an add-only start() — objects modified or deleted while the
    informer was disconnected would otherwise stay stale/ghost in the
    cache forever (and their handlers would never hear the delete)."""
    api = APIServer(clock=clock, watch_ring=2)
    inf = Informer(api, "ConfigMap")
    deletes, updates = [], []
    inf.add_event_handler(on_update=lambda old, new: updates.append(
        m.name(new)), on_delete=lambda o: deletes.append(m.name(o)))
    api.create(cm("stale", {"v": "1"}))
    api.create(cm("ghost"))
    inf.start()
    inf.disconnect()

    upd = api.get("ConfigMap", "default", "stale")
    upd["data"] = {"v": "2"}
    api.update(upd)
    api.delete("ConfigMap", "default", "ghost")
    for i in range(4):                   # evict the bookmark from the ring
        api.create(cm(f"filler-{i}"))

    inf.resume()
    assert inf.full_relists == 1
    assert inf.lister().get("default", "stale")["data"] == {"v": "2"}
    assert inf.lister().get("default", "ghost") is None
    assert deletes == ["ghost"] and "stale" in updates
    assert {m.name(o) for o in inf.lister().list()} \
        == {m.name(o) for o in api.list("ConfigMap")}


def test_informer_cache_is_level_based_against_stale_events(clock):
    """Review fix: a replayed event racing a newer live delivery (or a
    chaos-duplicated one) must never regress the cache — MODIFIED below
    the cached rv is dropped, and a stale DELETED tombstone cannot
    remove a newer recreated object."""
    api = APIServer(clock=clock, watch_ring=64)
    inf = Informer(api, "ConfigMap")
    api.create(cm("a", {"v": "1"}))
    inf.start()
    fresh = inf.lister().get("default", "a")
    stale = copy.deepcopy(fresh)
    upd = api.get("ConfigMap", "default", "a")
    upd["data"] = {"v": "2"}
    api.update(upd)                      # cache now at the newer rv

    inf._on_event("MODIFIED", stale)     # replayed old snapshot
    assert inf.lister().get("default", "a")["data"] == {"v": "2"}
    inf._on_event("DELETED", stale)      # stale tombstone
    assert inf.lister().get("default", "a") is not None
    # a legitimate delete (tombstone at/above the cached rv) applies
    api.delete("ConfigMap", "default", "a")
    assert inf.lister().get("default", "a") is None
    # review fix: a replayed stale MODIFIED landing AFTER the delete
    # must not resurrect the object (deletion popped the cache level;
    # the tombstone map keeps it)
    inf._on_event("MODIFIED", stale)
    assert inf.lister().get("default", "a") is None
    # a genuine recreate carries a higher rv and clears the tombstone
    api.create(cm("a", {"v": "3"}))
    assert inf.lister().get("default", "a")["data"] == {"v": "3"}


def test_informer_falls_back_to_full_relist_when_too_old(clock):
    dm = DurabilityMetrics(Registry())
    api = APIServer(clock=clock, watch_ring=2, durability_metrics=dm)
    inf = Informer(api, "ConfigMap")
    inf.start()
    inf.disconnect()
    for i in range(8):                   # blow the ring while disconnected
        api.create(cm(f"o-{i}"))
    inf.resume()
    assert inf.full_relists == 1 and inf.bookmark_resumes == 0
    assert dm.watch_relists.value(reason="too_old") == 1
    assert len(inf.lister().list()) == 8
    assert inf.has_synced()


# ---------------------------------------------------------------------------
# gate-off contract: byte-identical pre-durability behavior
# ---------------------------------------------------------------------------


def test_disabled_gate_is_byte_identical(api, clock):
    """THE pin: a plain store journals nothing, rings nothing, and a
    delete allocates NO resourceVersion — exactly the pre-durability rv
    stream. The durable store's delete allocates one (etcd revision
    semantics) — that difference is gate-on only."""
    api.create(cm("a"))
    api.create(cm("b"))
    api.delete("ConfigMap", "default", "a")
    assert api.latest_resource_version() == 2   # delete did not bump
    assert api._journal is None and api._ring_size == 0
    assert api._event_ring == {}

    durable = APIServer(clock=clock, watch_ring=8)
    durable.create(cm("a"))
    durable.create(cm("b"))
    durable.delete("ConfigMap", "default", "a")
    assert durable.latest_resource_version() == 3  # tombstone rv


def test_disabled_operator_has_no_durability_families_and_one_shard():
    op = build_operator(config=OperatorConfig(workloads=["PyTorchJob"]))
    body = op.metrics_registry.expose()
    assert "kubedl_journal_" not in body
    assert "kubedl_watch_relists_total" not in body
    assert "kubedl_shard_owned_keys" not in body
    assert op.manager.shards == 1
    assert op.api._journal is None and op.api._ring_size == 0


def test_gate_on_operator_registers_families_shards_and_recovers(tmp_path):
    cfg = OperatorConfig(workloads=["PyTorchJob"], enable_durability=True,
                         journal_dir=str(tmp_path / "j"),
                         snapshot_every=50, reconcile_shards=4)
    op = build_operator(config=cfg)
    assert op.manager.shards == 4
    body = op.metrics_registry.expose()
    assert "kubedl_journal_appends_total" in body
    assert "kubedl_watch_relists_total" in body

    template = {"spec": {"containers": [{
        "name": "pytorch", "image": "img:v1",
        "ports": [{"name": "pytorchjob-port", "containerPort": 23456}]}]}}
    op.api.create(m.new_obj(
        "training.kubedl.io/v1alpha1", "PyTorchJob", "pj",
        spec={"pytorchReplicaSpecs": {"Master": {
            "replicas": 1, "restartPolicy": "Never",
            "template": template}}}))
    for _ in range(10):
        op.manager.run_until_idle(max_iterations=10_000)
        pending = [p for p in op.api.list("Pod")
                   if (p.get("status") or {}).get("phase",
                                                  "Pending") != "Running"]
        if not pending:
            break
        for pod in pending:
            set_pod_phase(op.api, pod, "Running", container="pytorch")
    jobs = op.api.list("PyTorchJob")
    assert st.is_running(JobStatus.from_dict(jobs[0].get("status")))
    assert op.api._journal.appends > 0

    # the operator binary restarts: the world comes back from the journal
    op2 = build_operator(config=cfg)
    assert {m.name(j) for j in op2.api.list("PyTorchJob")} == {"pj"}
    assert st.is_running(JobStatus.from_dict(
        op2.api.list("PyTorchJob")[0].get("status")))
    assert len(op2.api.list("Pod")) == len(op.api.list("Pod"))


# ---------------------------------------------------------------------------
# sharded reconcile ownership
# ---------------------------------------------------------------------------


def test_shard_hash_is_stable_and_balanced():
    assert shard_for("default", "job-1", 1) == 0
    one = shard_for("ns-a", "job-7", 8)
    assert shard_for("ns-a", "job-7", 8) == one    # stable across calls
    counts = [0] * 4
    for i in range(1000):
        counts[shard_for("default", f"job-{i:04d}", 4)] += 1
    assert sum(counts) == 1000
    assert all(150 <= c <= 350 for c in counts), counts


class _OrderRecorder(Reconciler):
    kind = "TestJob"

    def __init__(self):
        self.order = []

    def reconcile(self, req):
        self.order.append(req.name)


def _dispatch_order(clock, shards):
    api = APIServer(clock=clock)
    mgr = Manager(api, clock=clock, shards=shards)
    rec = mgr.register(_OrderRecorder())
    for i in range(24):
        api.create(new_test_job(f"j-{i:02d}", workers=1))
    mgr.run_until_idle(max_iterations=10_000)
    return rec.order


def test_run_until_idle_order_is_identical_across_shard_counts(clock):
    """The determinism contract BENCH_CLUSTER.json's byte-identity rides
    on: the synchronous drain pops the globally-earliest (ready_at, seq)
    entry whatever the shard count."""
    assert _dispatch_order(clock, 1) == _dispatch_order(clock, 5) \
        == _dispatch_order(clock, 16)


def test_unowned_shards_park_until_the_lease_comes_back(clock):
    api = APIServer(clock=clock)
    owned = {0}
    dm = DurabilityMetrics(Registry())
    mgr = Manager(api, clock=clock, shards=4,
                  shard_owner=lambda i: i in owned,
                  durability_metrics=dm)
    rec = mgr.register(_OrderRecorder())
    names = [f"j-{i:02d}" for i in range(16)]
    for n in names:
        api.create(new_test_job(n, workers=1))
    mine = {n for n in names if shard_for("default", n, 4) == 0}
    assert 0 < len(mine) < len(names)

    mgr.run_until_idle(max_iterations=10_000)
    assert set(rec.order) == mine          # only the owned shard drained
    assert mgr.pending() > 0
    # per-shard occupancy is visible while keys wait for their owner
    waiting = sum(int(dm.shard_owned_keys.value(shard=str(i)))
                  for i in range(1, 4))
    assert waiting == len(names) - len(mine)

    owned.update({1, 2, 3})                # lease handoff: we own it all
    mgr.run_until_idle(max_iterations=10_000)
    assert set(rec.order) == set(names)
    assert mgr.pending() == 0


def test_shard_lease_handoff_between_candidates(clock):
    api = APIServer(clock=clock)
    a = ShardLeaseSet(api, 2, identity="op-a", clock=clock)
    b = ShardLeaseSet(api, 2, identity="op-b", clock=clock)
    assert a.step() == {0, 1}              # first candidate takes all
    assert b.step() == set()
    clock.advance(5.0)
    assert a.step() == {0, 1}              # renewal holds the fleet
    assert b.step() == set()
    assert a.owned() == {0, 1} and b.owned() == set()

    # op-a dies (stops renewing); after lease_duration on op-b's OWN
    # clock the record reads stale and op-b takes both shards over
    clock.advance(16.0)
    assert b.step() == {0, 1}
    assert a.step() == set()               # demoted on its next round
    assert not a.owns(0) and b.owns(0) and b.owns(1)
    # handoff is visible in the Lease objects themselves
    for i in range(2):
        lease = api.get("Lease", "kubedl-system", f"kubedl-shard-{i}")
        assert lease["spec"]["holderIdentity"] == "op-b"
        assert int(lease["spec"]["leaseTransitions"]) >= 1


def test_sharded_managers_split_ownership_and_converge(clock):
    """Two managers over one store, each holding one shard's lease:
    every job is reconciled by exactly one of them, and together they
    cover the world — the N-process deployment in miniature."""
    api = APIServer(clock=clock)
    a_set = ShardLeaseSet(api, 2, identity="op-a", clock=clock)
    assert a_set.step() == {0, 1}
    a_set.electors[1].release()            # op-a keeps shard 0 only
    b_set = ShardLeaseSet(api, 2, identity="op-b", clock=clock)
    assert b_set.step() == {1}

    mgr_a = Manager(api, clock=clock, shards=2, shard_owner=a_set.owns)
    mgr_b = Manager(api, clock=clock, shards=2, shard_owner=b_set.owns)
    rec_a = mgr_a.register(_OrderRecorder())
    rec_b = mgr_b.register(_OrderRecorder())
    names = [f"j-{i:02d}" for i in range(12)]
    for n in names:
        api.create(new_test_job(n, workers=1))
    mgr_a.run_until_idle(max_iterations=10_000)
    mgr_b.run_until_idle(max_iterations=10_000)
    assert set(rec_a.order) & set(rec_b.order) == set()
    assert set(rec_a.order) | set(rec_b.order) == set(names)
    assert {shard_for("default", n, 2) for n in rec_a.order} == {0}
    assert {shard_for("default", n, 2) for n in rec_b.order} == {1}


# ---------------------------------------------------------------------------
# THE crash-mid-storm chaos e2e (acceptance)
# ---------------------------------------------------------------------------

N_STORM_JOBS = 6


def _uid_factory(seed):
    state = {"n": 0}

    def factory():
        state["n"] += 1
        return f"dur-{seed}-{state['n']:06d}"
    return factory


def _build_stack(inner, clock, seed, budget):
    chaos = ChaosAPIServer(inner, ChaosConfig(
        seed=seed, conflict_on_status_update=0.15, error_on_create=0.10,
        drop_watch_events=0.05, max_faults=budget))
    manager = Manager(chaos, clock=clock, shards=2)
    engine = JobEngine(
        chaos, TestJobController(),
        EngineConfig(enable_gang_scheduling=True,
                     retry_policy=RetryPolicy(attempts=5, base=0.01,
                                              cap=0.05),
                     retry_sleep=clock.advance,
                     backoff_jitter_seed=seed,
                     restart_backoff_base=5.0, restart_backoff_cap=30.0),
        gang=CoschedulerPlugin(chaos))
    manager.register(engine)
    return chaos, manager


def _drive(manager, clock, inner, rounds=1):
    """One storm round: drain, resync-nudge every job (the stand-in for
    the informer relist that repairs chaos-dropped watch events), play
    kubelet, then advance the sim clock to the manager's next deadline
    so requeue nets and restart backoffs fire when scheduled."""
    for _ in range(rounds):
        manager.run_until_idle(max_iterations=20_000)
        for job in inner.list("TestJob"):
            manager.enqueue(Request("TestJob", "default", m.name(job)))
        manager.run_until_idle(max_iterations=20_000)
        for pod in inner.list("Pod"):
            ph = (pod.get("status") or {}).get("phase", "Pending")
            if ph == "Pending" and not m.is_deleting(pod):
                set_pod_phase(inner, pod, "Running")
        manager.run_until_idle(max_iterations=20_000)
        dl = manager.next_deadline()
        if dl is not None:
            clock.advance_to(dl - clock.t0 + 1e-6)
        else:
            clock.advance(2.0)
        manager.run_until_idle(max_iterations=20_000)


def _jobs_status(inner):
    return {m.name(j): JobStatus.from_dict(j.get("status"))
            for j in inner.list("TestJob")}


def _drive_to_succeeded(manager, clock, inner, max_rounds=120):
    for _ in range(max_rounds):
        _drive(manager, clock, inner, rounds=1)
        for name, s in _jobs_status(inner).items():
            if st.is_succeeded(s) or not st.is_running(s):
                continue
            job = inner.get("TestJob", "default", name)
            for p in inner.list_owned("Pod", m.uid(job),
                                      namespace="default"):
                if (p.get("status") or {}).get("phase") == "Running":
                    set_pod_phase(inner, p, "Succeeded", exit_code=0)
        manager.run_until_idle(max_iterations=20_000)
        statuses = _jobs_status(inner)
        if len(statuses) == N_STORM_JOBS and all(
                st.is_succeeded(s) for s in statuses.values()):
            return
    raise AssertionError(
        f"storm never converged: "
        f"{ {n: s.conditions[-1].type if s.conditions else '?' for n, s in _jobs_status(inner).items()} }")


def _submit(inner, i):
    inner.create(new_test_job(
        f"storm-{i}", workers=2, restart_policy="ExitCode",
        tpu_policy={"acceleratorType": "v5p-16"}))


def _run_storm(seed, clock, journal_dir=None, crash=False,
               dur_metrics=None):
    """The scripted storm. With ``crash=True`` the operator process-model
    is killed right after the chaos preemption and a fresh one recovers
    from the journal; returns (final inner api, crash diagnostics)."""
    journal = Journal(str(journal_dir), snapshot_every=25,
                      fsync_every=16) if journal_dir else None
    inner = APIServer(clock=clock, uid_factory=_uid_factory(seed),
                      journal=journal,
                      watch_ring=2048 if journal else 0,
                      durability_metrics=dur_metrics)
    chaos, manager = _build_stack(inner, clock, seed, budget=25)
    informer = Informer(inner, "TestJob")   # the "console process"
    informer.start()

    for i in range(3):
        _submit(inner, i)
    for _ in range(40):
        _drive(manager, clock, inner, rounds=1)
        statuses = _jobs_status(inner)
        if len(statuses) == 3 and all(st.is_running(s)
                                      for s in statuses.values()):
            break
    else:
        raise AssertionError(
            f"seed {seed}: storm phase 1 never reached Running")

    # the storm's disruption: a chaos node preemption mid-run
    victim = sorted(m.name(p) for p in inner.list("Pod"))[0]
    chaos.preempt("default", victim)
    manager.run_until_idle(max_iterations=20_000)

    diag = {}
    if crash:
        # make sure the WAL has a tail past the newest snapshot, then
        # kill the operator: no close(), no flush beyond the per-record
        # write(2) — exactly what a SIGKILL leaves behind
        i = 0
        while journal._since_snapshot == 0:
            inner.create(cm(f"crash-marker-{i}"))
            i += 1
        pre_objs = copy.deepcopy(inner._objs)
        pre_rv = inner.latest_resource_version()
        informer.disconnect()               # its server just went away

        journal2 = Journal(str(journal_dir), snapshot_every=25,
                           fsync_every=16)
        recovered = APIServer(clock=clock, uid_factory=_uid_factory(seed + 7),
                              journal=journal2, watch_ring=2048,
                              durability_metrics=dur_metrics)
        # exact pre-crash store: objects AND the rv counter
        assert recovered._objs == pre_objs
        assert recovered.latest_resource_version() == pre_rv
        assert journal2.recovered_from["snapshot_rv"] > 0, \
            "recovery must have used a snapshot"
        assert journal2.recovered_from["wal_records"] > 0, \
            "recovery must have replayed a WAL tail"
        diag["recovered_from"] = dict(journal2.recovered_from)

        # the surviving informer resumes via bookmark: no full relist
        informer.api = recovered
        informer.resume()
        assert informer.bookmark_resumes == 1
        assert informer.full_relists == 0
        inner = recovered
        chaos, manager = _build_stack(inner, clock, seed + 1000, budget=10)
        # restart relist: the manager's startup enqueue (this is the
        # operator's own boot list, not an informer relist)
        for j in inner.list("TestJob"):
            manager.enqueue(Request("TestJob", "default", m.name(j)))

    for i in range(3, N_STORM_JOBS):
        _submit(inner, i)
    _drive_to_succeeded(manager, clock, inner)

    # informer cache converged with the store (bookmark stream stayed
    # gapless through the crash)
    cached = {m.name(o) for o in informer.lister().list()}
    assert cached == {m.name(o) for o in inner.list("TestJob")}
    return inner, diag


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_crash_mid_storm_recovers_to_parity(tmp_path, seed):
    """Acceptance: kill/restart of the operator process-model mid
    3-seed storm recovers from snapshot+WAL replay and converges to
    parity with a never-crashed reference run — with informers resumed
    via bookmark and zero full relists after recovery."""
    dm = DurabilityMetrics(Registry())
    crashed, diag = _run_storm(seed, SimClock(),
                               journal_dir=tmp_path / "journal",
                               crash=True, dur_metrics=dm)
    reference, _ = _run_storm(seed, SimClock())

    # parity with the never-crashed run: same job set, every job
    # completed in both worlds
    a, b = _jobs_status(crashed), _jobs_status(reference)
    assert set(a) == set(b)
    assert all(st.is_succeeded(s) for s in a.values()), \
        f"crashed run did not converge (recovery: {diag})"
    assert all(st.is_succeeded(s) for s in b.values())
    # < 1 full relist per informer after recovery — actually zero
    assert dm.watch_relists.value(reason="too_old") == 0
    assert dm.watch_relists.value(reason="ring_disabled") == 0
    # both worlds settled to the same pod population per job
    pods_a = sorted(m.name(p) for p in crashed.list("Pod"))
    pods_b = sorted(m.name(p) for p in reference.list("Pod"))
    assert pods_a == pods_b


# ---------------------------------------------------------------------------
# bench regression gate plumbing (tamper test, like bench_scheduler's)
# ---------------------------------------------------------------------------


def _bench_doc(**overrides):
    doc = {
        "benchmark": "controlplane_settle",
        "jobs": 10000, "replicas": 16,
        "shards1": {"jobs_per_sec_settled": 100.0,
                    "reconcile_ms": {"p50": 0.4, "p99": 3.0}},
        "shards4": {"jobs_per_sec_settled": 320.0,
                    "reconcile_ms": {"p50": 0.4, "p99": 3.0}},
        "speedup_sharded_settle": 3.2,
        "durability": {"relists_avoided": 32, "full_relists": 0},
        "legacy_200x8": {"speedup_settle_throughput": 5.7},
    }
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(doc.get(k), dict):
            doc[k] = {**doc[k], **v}
        else:
            doc[k] = v
    return doc


def test_bench_regression_gate_detects_tampering():
    import bench_controlplane as bench
    old = _bench_doc()
    assert bench.check_regression(_bench_doc(), old) == []
    # sharded settle throughput collapse: flagged
    worse = _bench_doc(shards4={"jobs_per_sec_settled": 150.0,
                                "reconcile_ms": {"p50": 0.4, "p99": 3.0}},
                       speedup_sharded_settle=1.5)
    assert any("shards4" in p or "speedup" in p
               for p in bench.check_regression(worse, old))
    # p99 blow-up: flagged
    slow = _bench_doc(shards4={"jobs_per_sec_settled": 320.0,
                               "reconcile_ms": {"p50": 0.4, "p99": 30.0}})
    assert any("p99" in p for p in bench.check_regression(slow, old))
    # a re-scaled run is a new baseline, not a regression
    rescaled = _bench_doc(jobs=500)
    rescaled["shards4"]["jobs_per_sec_settled"] = 1.0
    assert bench.check_regression(rescaled, old) == []


def test_bench_gate_requires_sharded_speedup():
    import bench_controlplane as bench
    ok = _bench_doc()
    assert bench.evaluate_gate(ok) == []
    slow = _bench_doc(speedup_sharded_settle=1.4)
    assert any("speedup" in p for p in bench.evaluate_gate(slow))
    worse_p99 = _bench_doc(
        shards4={"jobs_per_sec_settled": 320.0,
                 "reconcile_ms": {"p50": 0.4, "p99": 30.0}})
    assert any("p99" in p for p in bench.evaluate_gate(worse_p99))
