"""Speculative decoding: greedy output identity + acceptance accounting."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.serving.engine import GenerateConfig, InferenceEngine
from kubedl_tpu.serving.speculative import SpecStats, SpeculativeEngine

#: compile-heavy compute suite: excluded from `make test`'s fast path
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def models():
    tcfg = dataclasses.replace(llama.tiny(vocab=128), dtype=jnp.float32)
    tparams = llama.init_params(tcfg, jax.random.PRNGKey(0))
    dcfg = dataclasses.replace(
        llama.tiny(vocab=128), d_model=64, n_layers=1, n_heads=2,
        n_kv_heads=2, d_ff=128, dtype=jnp.float32)
    dparams = llama.init_params(dcfg, jax.random.PRNGKey(1))
    return tcfg, tparams, dcfg, dparams


def _plain_greedy(tcfg, tparams, prompt, n):
    eng = InferenceEngine(tcfg, tparams, GenerateConfig(max_len=128))
    return eng.generate([prompt], n)[0]


def test_output_identical_to_plain_greedy(models):
    """The defining property: speculative greedy == plain greedy, token
    for token, regardless of how good the draft is."""
    tcfg, tparams, dcfg, dparams = models
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, k=4, max_len=128)
    for prompt in ([5, 7, 11], [3], [2, 4, 6, 8, 10, 12]):
        want = _plain_greedy(tcfg, tparams, prompt, 12)
        got = spec.generate(prompt, 12)
        assert got == want, (prompt, got, want)


def test_self_draft_accepts_everything(models):
    """Draft == target: every proposal must be accepted (k+1 tokens per
    target pass) and the output still matches plain greedy."""
    tcfg, tparams, _, _ = models
    spec = SpeculativeEngine(tcfg, tparams, tcfg, tparams, k=3, max_len=128)
    stats = SpecStats()
    got = spec.generate([5, 7, 11], 10, stats=stats)
    assert got == _plain_greedy(tcfg, tparams, [5, 7, 11], 10)
    assert stats.proposed > 0
    assert stats.acceptance_rate == 1.0


def test_stats_and_vocab_guard(models):
    tcfg, tparams, dcfg, dparams = models
    stats = SpecStats()
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, k=4, max_len=128)
    spec.generate([9, 1], 8, stats=stats)
    assert 0.0 <= stats.acceptance_rate <= 1.0
    bad = dataclasses.replace(dcfg, vocab_size=64)
    with pytest.raises(ValueError):
        SpeculativeEngine(tcfg, tparams, bad,
                          llama.init_params(bad, jax.random.PRNGKey(2)))


def test_int8_draft(models):
    tcfg, tparams, dcfg, dparams = models
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, k=4, max_len=128,
                             quantize_draft="int8")
    got = spec.generate([5, 7, 11], 8)
    assert got == _plain_greedy(tcfg, tparams, [5, 7, 11], 8)


def test_capacity_guard(models):
    tcfg, tparams, dcfg, dparams = models
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, max_len=32)
    with pytest.raises(ValueError):
        spec.generate([1] * 30, 8)


def test_stop_sequences_match_static_engine(models):
    """gen.eos_id / stop_sequences truncate speculative output exactly
    where the static engine's shared hit_stop rule truncates greedy
    decoding (ADVICE r3: generate() used to ignore stops entirely)."""
    tcfg, tparams, dcfg, dparams = models
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, k=4, max_len=128)
    prompt = [5, 7, 11]
    plain = _plain_greedy(tcfg, tparams, prompt, 16)

    # pick a token that actually occurs mid-stream as the stop anchor so
    # the test exercises a truncation, not just the no-stop path
    anchor_idx = len(plain) // 2
    eos = plain[anchor_idx]
    gen = GenerateConfig(max_len=128, eos_id=eos)
    eng = InferenceEngine(tcfg, tparams, gen)
    want = eng.generate([prompt], 16)[0]
    got = spec.generate(prompt, 16, gen=gen)
    assert got == want
    assert len(got) <= anchor_idx + 1

    # multi-token stop sequence ending at the anchor
    if anchor_idx >= 1:
        stop = tuple(plain[anchor_idx - 1:anchor_idx + 1])
        gen2 = GenerateConfig(max_len=128, stop_sequences=(stop,))
        eng2 = InferenceEngine(tcfg, tparams, gen2)
        want2 = eng2.generate([prompt], 16)[0]
        got2 = spec.generate(prompt, 16, gen=gen2)
        assert got2 == want2


def test_no_gen_config_is_unchanged(models):
    """Without a GenerateConfig the engine still emits max_new_tokens."""
    tcfg, tparams, dcfg, dparams = models
    spec = SpeculativeEngine(tcfg, tparams, dcfg, dparams, k=3, max_len=128)
    assert len(spec.generate([1, 2], 9)) == 9
