"""Tensor-parallel serving over a local mesh: params shard by logical
specs, the KV cache by kv-heads, and outputs stay EXACTLY equal to the
unsharded engines (f32 greedy) — model-parallel serving of models too
big for one chip, on one host's mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import MeshConfig, build_mesh
from kubedl_tpu.serving.batching import ContinuousBatchingEngine
from kubedl_tpu.serving.engine import GenerateConfig, InferenceEngine

#: compile-heavy compute suite: excluded from `make test`'s fast path
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(llama.tiny(vocab=128), n_heads=4,
                              n_kv_heads=2, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, tp=2), jax.devices()[:2])
    return cfg, params, mesh


def test_static_engine_tp_exact(setup):
    cfg, params, mesh = setup
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=64))
    tp = InferenceEngine(cfg, params, GenerateConfig(max_len=64),
                         mesh=mesh)
    prompts = [[5, 7, 11], [3], [9, 2]]
    assert tp.generate(prompts, 8) == solo.generate(prompts, 8)


def test_continuous_engine_tp_exact_with_prefix(setup):
    cfg, params, mesh = setup
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=96))
    eng = ContinuousBatchingEngine(cfg, params, lanes=2, max_len=96,
                                   mesh=mesh)
    reqs = [([5, 7, 11], 6), ([3], 4), ([9, 2, 4], 5)]
    for (p, n), toks in zip(reqs, eng.run(reqs)):
        assert toks == solo.generate([p], n)[0], p
    # the prefix KV block shards and reloads correctly under tp
    eng.register_prefix([7, 7, 7, 7])
    got = eng.run([([7, 7, 7, 7, 1], 5)])[0]
    assert got == solo.generate([[7, 7, 7, 7, 1]], 5)[0]


def test_mqa_cache_replicates(setup):
    """nkv=1 does not divide tp=2: the cache must replicate its kv axis
    and still decode exactly."""
    cfg, _, mesh = setup
    mcfg = dataclasses.replace(cfg, n_kv_heads=1)
    params = llama.init_params(mcfg, jax.random.PRNGKey(1))
    solo = InferenceEngine(mcfg, params, GenerateConfig(max_len=64))
    tp = InferenceEngine(mcfg, params, GenerateConfig(max_len=64),
                         mesh=mesh)
    assert tp.generate([[4, 4, 2]], 6) == solo.generate([[4, 4, 2]], 6)


def test_http_server_over_tp_mesh(setup):
    """The FULL serving path — InferenceServer HTTP predict — over a
    mesh-sharded continuous-batching engine (VERDICT r4 next #2: the
    BASELINE config-5 v5e-8 shape, previously never executed end to
    end). Predictions must be token-identical to the unsharded engine."""
    import json
    import urllib.request

    from kubedl_tpu.serving.server import InferenceServer, ServerConfig

    cfg, params, mesh = setup
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=64))
    # .start() runs the scheduler loop — the HTTP predict path submits
    # to lanes and waits; without the loop nothing ever ticks
    eng = ContinuousBatchingEngine(cfg, params, lanes=2, max_len=64,
                                   mesh=mesh).start()
    server = InferenceServer(eng, ServerConfig(
        model_name="tp", host="127.0.0.1", port=0)).start()
    try:
        req = urllib.request.Request(
            server.url + "/v1/models/tp:predict", method="POST",
            data=json.dumps({"instances": [
                {"prompt_tokens": [5, 7, 11], "max_tokens": 6},
                {"prompt_tokens": [3], "max_tokens": 4},
            ]}).encode(), headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            preds = json.load(r)["predictions"]
        assert preds[0]["tokens"] == solo.generate([[5, 7, 11]], 6)[0]
        assert preds[1]["tokens"] == solo.generate([[3]], 4)[0]
    finally:
        server.stop()
        eng.stop()


def test_speculative_lanes_over_tp_mesh(setup):
    """Speculative decoding composes with tensor-parallel serving: the
    draft shards over the same mesh as the target and greedy outputs
    stay token-identical to the unsharded engine."""
    cfg, params, mesh = setup
    dcfg = dataclasses.replace(cfg, d_model=64, n_layers=1, d_ff=128)
    dparams = llama.init_params(dcfg, jax.random.PRNGKey(2))
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=64))
    eng = ContinuousBatchingEngine(cfg, params, lanes=2, max_len=64,
                                   mesh=mesh, draft_config=dcfg,
                                   draft_params=dparams, spec_k=2)
    try:
        reqs = [([5, 7, 11], 6), ([3], 4)]
        got = eng.run(reqs)
        assert got == [solo.generate([p], n)[0] for p, n in reqs]
        assert eng.stats.proposed > 0
    finally:
        eng.stop()


def test_mesh_rejects_quantization(setup):
    cfg, params, mesh = setup
    with pytest.raises(ValueError, match="quantization"):
        InferenceEngine(cfg, params, mesh=mesh, quantize="int8")
    with pytest.raises(ValueError, match="quantization"):
        ContinuousBatchingEngine(cfg, params, mesh=mesh, quantize="int4")
