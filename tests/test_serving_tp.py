"""Tensor-parallel serving over a local mesh: params shard by logical
specs, the KV cache by kv-heads, and outputs stay EXACTLY equal to the
unsharded engines (f32 greedy) — model-parallel serving of models too
big for one chip, on one host's mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from kubedl_tpu.models import llama
from kubedl_tpu.parallel.mesh import MeshConfig, build_mesh
from kubedl_tpu.serving.batching import ContinuousBatchingEngine
from kubedl_tpu.serving.engine import GenerateConfig, InferenceEngine

#: compile-heavy compute suite: excluded from `make test`'s fast path
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(llama.tiny(vocab=128), n_heads=4,
                              n_kv_heads=2, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, tp=2), jax.devices()[:2])
    return cfg, params, mesh


def test_static_engine_tp_exact(setup):
    cfg, params, mesh = setup
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=64))
    tp = InferenceEngine(cfg, params, GenerateConfig(max_len=64),
                         mesh=mesh)
    prompts = [[5, 7, 11], [3], [9, 2]]
    assert tp.generate(prompts, 8) == solo.generate(prompts, 8)


def test_continuous_engine_tp_exact_with_prefix(setup):
    cfg, params, mesh = setup
    solo = InferenceEngine(cfg, params, GenerateConfig(max_len=96))
    eng = ContinuousBatchingEngine(cfg, params, lanes=2, max_len=96,
                                   mesh=mesh)
    reqs = [([5, 7, 11], 6), ([3], 4), ([9, 2, 4], 5)]
    for (p, n), toks in zip(reqs, eng.run(reqs)):
        assert toks == solo.generate([p], n)[0], p
    # the prefix KV block shards and reloads correctly under tp
    eng.register_prefix([7, 7, 7, 7])
    got = eng.run([([7, 7, 7, 7, 1], 5)])[0]
    assert got == solo.generate([[7, 7, 7, 7, 1]], 5)[0]


def test_mqa_cache_replicates(setup):
    """nkv=1 does not divide tp=2: the cache must replicate its kv axis
    and still decode exactly."""
    cfg, _, mesh = setup
    mcfg = dataclasses.replace(cfg, n_kv_heads=1)
    params = llama.init_params(mcfg, jax.random.PRNGKey(1))
    solo = InferenceEngine(mcfg, params, GenerateConfig(max_len=64))
    tp = InferenceEngine(mcfg, params, GenerateConfig(max_len=64),
                         mesh=mesh)
    assert tp.generate([[4, 4, 2]], 6) == solo.generate([[4, 4, 2]], 6)


def test_mesh_rejects_quantization(setup):
    cfg, params, mesh = setup
    with pytest.raises(ValueError, match="quantization"):
        InferenceEngine(cfg, params, mesh=mesh, quantize="int8")
    with pytest.raises(ValueError, match="quantization"):
        ContinuousBatchingEngine(cfg, params, mesh=mesh, quantize="int4")
