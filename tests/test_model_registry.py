"""Model registry: ModelVersion image build + storage providers + model-path
injection (reference ``controllers/model`` + ``pkg/job_controller/job.go:471-541``)."""

import pytest

from kubedl_tpu.controllers.registry import OperatorConfig, build_operator
from kubedl_tpu.controllers.testing import set_pod_phase
from kubedl_tpu.core import meta as m
from kubedl_tpu.platform import models as pm


@pytest.fixture
def op(api):
    return build_operator(api, OperatorConfig(gang_scheduler_name=""))


def new_mv(name="mv1", storage=None, repo="registry.example.com/bert",
           tag="", model_name="bert"):
    mv = m.new_obj("model.kubedl.io/v1alpha1", "ModelVersion", name)
    mv["spec"] = {"modelName": model_name, "imageRepo": repo}
    if tag:
        mv["spec"]["imageTag"] = tag
    mv["spec"]["storage"] = storage or {
        "localStorage": {"path": "/models/bert", "nodeName": "node-1",
                         "mountPath": "/mnt/models"}}
    return mv


def test_local_storage_build_pipeline(api, op):
    api.create(new_mv())
    op.run_until_idle()

    # PV/PVC staging + dockerfile + builder pod exist
    pv = api.get("PersistentVolume", "default", "mv-pv-mv1")
    assert pv["spec"]["local"]["path"] == "/models/bert"
    affinity = m.get_in(pv, "spec", "nodeAffinity", "required",
                        "nodeSelectorTerms")[0]["matchExpressions"][0]
    assert affinity["values"] == ["node-1"]
    assert api.get("PersistentVolumeClaim", "default", "mv-pvc-mv1")
    assert "busybox" in api.get("ConfigMap", "default", "dockerfile")["data"]["dockerfile"]
    pod = api.get("Pod", "default", "image-build-mv1")
    args = pod["spec"]["containers"][0]["args"]
    assert "--context=dir:///workspace/" in args
    mv = api.get("ModelVersion", "default", "mv1")
    assert mv["status"]["imageBuildPhase"] == pm.IMAGE_BUILDING
    # tag defaults to the first 5 uid chars (modelversion_types.go:54)
    expected_image = f"registry.example.com/bert:{m.uid(mv)[:5]}"
    assert f"--destination={expected_image}" in args

    # parent Model auto-created and owns the version
    model = api.get("Model", "default", "bert")
    mv = api.get("ModelVersion", "default", "mv1")
    assert m.get_controller_ref(mv)["name"] == "bert"

    # builder success -> status flips, Model.latestVersion updated
    set_pod_phase(api, pod, "Succeeded", exit_code=0)
    op.run_until_idle()
    mv = api.get("ModelVersion", "default", "mv1")
    assert mv["status"]["imageBuildPhase"] == pm.IMAGE_BUILD_SUCCEEDED
    assert mv["status"]["image"] == expected_image
    assert mv["status"]["finishTime"]
    model = api.get("Model", "default", "bert")
    assert model["status"]["latestVersion"] == {
        "modelVersion": "mv1", "imageName": expected_image}


def test_gcs_storage_builds_straight_from_bucket(api, op):
    api.create(new_mv(storage={"gcs": {"bucket": "ckpts", "path": "bert/v1"}},
                      tag="v1"))
    op.run_until_idle()
    pod = api.get("Pod", "default", "image-build-mv1")
    args = pod["spec"]["containers"][0]["args"]
    # the bucket is fuse-mounted at /workspace/build so the shared
    # "COPY build/" dockerfile works; context stays a local dir
    assert "--context=dir:///workspace/" in args
    assert "--destination=registry.example.com/bert:v1" in args
    src = next(v for v in pod["spec"]["volumes"] if v["name"] == "build-source")
    assert src["csi"]["driver"] == "gcsfuse.csi.storage.gke.io"
    assert src["csi"]["volumeAttributes"]["bucketName"] == "ckpts"
    assert "only-dir=bert/v1" in src["csi"]["volumeAttributes"]["mountOptions"]
    assert pod["metadata"]["annotations"]["gke-gcsfuse/volumes"] == "true"
    # no PVC staging hop for GCS
    assert api.try_get("PersistentVolumeClaim", "default", "mv-pvc-mv1") is None
    assert not any(v.get("persistentVolumeClaim")
                   for v in pod["spec"]["volumes"])


def test_build_failure_reported(api, op):
    api.create(new_mv())
    op.run_until_idle()
    pod = api.get("Pod", "default", "image-build-mv1")
    set_pod_phase(api, pod, "Failed", exit_code=1)
    op.run_until_idle()
    mv = api.get("ModelVersion", "default", "mv1")
    assert mv["status"]["imageBuildPhase"] == pm.IMAGE_BUILD_FAILED


def test_missing_storage_fails_fast(api, op):
    mv = new_mv()
    mv["spec"].pop("storage")
    api.create(mv)
    op.run_until_idle()
    mv = api.get("ModelVersion", "default", "mv1")
    assert mv["status"]["imageBuildPhase"] == pm.IMAGE_BUILD_FAILED
    assert "storage" in mv["status"]["message"]
    # validation happens before any side objects: no junk Model left behind
    assert api.try_get("Model", "default", "bert") is None


def test_modelname_written_back_for_job_created_versions(api, op):
    """A job-created version omitting modelName must not leave the Model's
    latestVersion erasable by the ModelReconciler's filter."""
    mv = new_mv("mv-j1-abcde", model_name="")
    mv["spec"].pop("modelName")
    api.create(mv)
    op.run_until_idle()
    mv = api.get("ModelVersion", "default", "mv-j1-abcde")
    assert mv["spec"]["modelName"] == "mv-j1-abcde"
    set_pod_phase(api, api.get("Pod", "default", "image-build-mv-j1-abcde"),
                  "Succeeded", exit_code=0)
    op.run_until_idle()
    model = api.get("Model", "default", "mv-j1-abcde")
    assert model["status"]["latestVersion"]["modelVersion"] == "mv-j1-abcde"


def test_local_storage_node_resolved_from_output_pod(api, op):
    """localStorage without nodeName resolves to the master pod's node
    (reference job.go:525-529 GetNodeForModelOutput)."""
    from kubedl_tpu.platform.models import build_model_version_spec
    job = m.new_obj("training.kubedl.io/v1alpha1", "XGBoostJob", "j2")
    pods = [
        {"metadata": {"labels": {"replica-type": "worker", "replica-index": "0"}},
         "spec": {"nodeName": "host-b"}},
        {"metadata": {"labels": {"replica-type": "master", "replica-index": "0"}},
         "spec": {"nodeName": "host-a"}},
    ]
    spec = build_model_version_spec(
        job, {"imageRepo": "r/x",
              "storage": {"localStorage": {"path": "/m"}}}, pods)
    assert spec["storage"]["localStorage"]["nodeName"] == "host-a"
    assert spec["modelName"] == "j2"


def test_model_tracks_newest_version(api, op, clock):
    api.create(new_mv("mv1", tag="a"))
    op.run_until_idle()
    set_pod_phase(api, api.get("Pod", "default", "image-build-mv1"),
                  "Succeeded", exit_code=0)
    op.run_until_idle()
    clock.advance(60)
    api.create(new_mv("mv2", tag="b"))
    op.run_until_idle()
    set_pod_phase(api, api.get("Pod", "default", "image-build-mv2"),
                  "Succeeded", exit_code=0)
    op.run_until_idle()
    model = api.get("Model", "default", "bert")
    assert model["status"]["latestVersion"]["modelVersion"] == "mv2"
    # deleting the newest version heals latestVersion back to mv1
    api.delete("ModelVersion", "default", "mv2")
    op.run_until_idle()
    model = api.get("Model", "default", "bert")
    assert model["status"]["latestVersion"]["modelVersion"] == "mv1"


def test_model_path_env_injected_into_job(api, op):
    """Jobs with spec.modelVersion get KUBEDL_MODEL_PATH + the artifact
    volume in every replica (reference job.go:471-498)."""
    job = m.new_obj("training.kubedl.io/v1alpha1", "XGBoostJob", "j1")
    job["spec"] = {
        "modelVersion": {
            "modelName": "bert", "imageRepo": "r/bert",
            "storage": {"localStorage": {"path": "/models",
                                         "mountPath": "/mnt/out",
                                         "nodeName": "n1"}}},
        "xgbReplicaSpecs": {
            "Master": {"replicas": 1, "template": {"spec": {"containers": [
                {"name": "xgboost", "image": "xgb"}]}}},
            "Worker": {"replicas": 1, "template": {"spec": {"containers": [
                {"name": "xgboost", "image": "xgb"}]}}},
        },
    }
    api.create(job)
    op.run_until_idle()
    for pod_name in ("j1-master-0", "j1-worker-0"):
        pod = api.get("Pod", "default", pod_name)
        container = pod["spec"]["containers"][0]
        envs = {e["name"]: e.get("value") for e in container["env"]}
        assert envs[pm.MODEL_PATH_ENV] == "/mnt/out"
        assert any(vm["mountPath"] == "/mnt/out"
                   for vm in container["volumeMounts"])
        assert any(v.get("hostPath", {}).get("path") == "/models"
                   for v in pod["spec"]["volumes"])


def test_gcs_volume_uses_gcsfuse_csi(api):
    template = {"spec": {"containers": [{"name": "main", "image": "i"}]}}
    storage = {"gcs": {"bucket": "b", "mountPath": "/gcs"}}
    pm.provider_for(storage).add_model_volume(template, storage)
    vol = template["spec"]["volumes"][0]
    assert vol["csi"]["driver"] == "gcsfuse.csi.storage.gke.io"
    assert vol["csi"]["volumeAttributes"]["bucketName"] == "b"
    assert template["metadata"]["annotations"]["gke-gcsfuse/volumes"] == "true"
