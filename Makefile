# kubedl-tpu developer entry points (reference Makefile:17-80 analog).

PY ?= python

.PHONY: test test-fast bench dryrun crds run-standalone lint

# full suite on the 8-device virtual CPU mesh (conftest pins the platform)
test:
	$(PY) -m pytest tests/ -q

# operator-only tests (skips the slow compute/jit suites)
test-fast:
	$(PY) -m pytest tests/ -q --ignore=tests/test_llama.py \
	    --ignore=tests/test_ring.py --ignore=tests/test_attention.py \
	    --ignore=tests/test_checkpoint.py --ignore=tests/test_model_zoo.py \
	    --ignore=tests/test_inference.py --ignore=tests/test_dryrun.py

# one-line JSON training benchmark (TPU when reachable, cpu smoke otherwise)
bench:
	$(PY) bench.py

# multi-chip sharding compile+execute proof on a virtual mesh
dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# regenerate config/crd/bases from the API types
crds:
	$(PY) hack/gen_crds.py

# standalone control plane with console + sqlite persistence
run-standalone:
	$(PY) -m kubedl_tpu --workloads PyTorchJob,TFJob,JAXJob \
	    --object-storage sqlite:///tmp/kubedl.db \
	    --event-storage sqlite:///tmp/kubedl.db \
	    --console-port 9090

lint:
	$(PY) -m compileall -q kubedl_tpu tests bench.py __graft_entry__.py
