# kubedl-tpu developer entry points (reference Makefile:17-80 analog).

PY ?= python

.PHONY: test test-all test-fast test-chaos test-campaign test-scheduler test-trace test-replay test-telemetry test-slo test-durability test-forensics test-replication test-elastic test-serving-fleet test-federation test-rl test-multimodel bench bench-controlplane bench-scheduler bench-serving-paged bench-serving-fleet bench-federation bench-rl bench-multimodel bench-trace bench-cluster bench-cluster-adversarial bench-elastic postmortem dryrun crds run-standalone lint native

# fast path (<3 min): everything except the compile-heavy compute suites
# (those carry `pytestmark = pytest.mark.slow`). Chaos tests are fast and
# deterministic, so they ride in this tier by default.
test:
	$(PY) -m pytest tests/ -q -m "not slow"

# just the fault-injection suite; set KUBEDL_CHAOS_SEED=<n> to replay a
# failing seed (every chaos test prints the seed it ran with)
test-chaos:
	$(PY) -m pytest tests/ -q -m chaos

# chaos-campaign suite (correlated fault primitives, latency injection,
# scenario scripts, SLO-survival e2e; docs/chaos.md)
test-campaign:
	$(PY) -m pytest tests/ -q -m campaign

# full suite on the 8-device virtual CPU mesh (conftest pins the platform);
# -n auto spreads the compute compiles over workers when pytest-xdist is
# present (pip install .[test]) and falls back to serial when not. The
# dryrun wall-clock bound self-scales by PYTEST_XDIST_WORKER_COUNT.
XDIST := $(shell $(PY) -c "import xdist" 2>/dev/null && echo "-n auto")
test-all:
	$(PY) -m pytest tests/ -q $(XDIST)

test-fast: test

# one-line JSON training benchmark (TPU when reachable, cpu smoke otherwise)
bench:
	$(PY) bench.py

# control-plane settle throughput -> BENCH_CONTROLPLANE.json: the legacy
# 200x8 index-vs-scan leg plus the fleet-scale 10k jobs x 16 replicas
# gate-on legs (durable control plane, shards=1 vs shards=4, bookmark
# resume cycles; docs/durability.md) plus the replication leg (leader
# SIGKILLed mid-10k-job storm with WAL followers; docs/replication.md).
# Gates: >=2x sharded settle at no-worse reconcile p99, zero full
# relists, ZERO acknowledged writes lost across failover, promotion
# inside one lease term, read throughput scaling with follower count;
# FAILS on regression vs the committed artifact. Fast tier-1 guards:
# tests/test_controlplane_perf.py + make test-durability +
# make test-replication. Use --quick for a 1/10th-scale smoke.
bench-controlplane:
	JAX_PLATFORMS=cpu $(PY) bench_controlplane.py

# slice-scheduler policy suite (queues/quota/preemption/backfill)
test-scheduler:
	$(PY) -m pytest tests/ -q -m scheduler

# slice-scheduler policy value on deterministic synthetic traces: FCFS
# head-of-line baseline vs queues+quota+backfill, plus the heterogeneous
# placement leg (unscored vs scored pool choice with a spot outage) ->
# BENCH_SCHEDULER.json (docs/scheduling.md). Gates: >=1.3x slice
# utilization at no worse makespan, >=1.25x normalized throughput with
# >=90% ICI-packed multislice gangs; FAILS on regression vs the
# committed artifact (per-metric tolerances, like bench-cluster)
bench-scheduler:
	JAX_PLATFORMS=cpu $(PY) bench_scheduler.py

# serving capacity at a fixed KV-memory budget: paged block pool vs the
# dense per-lane slab on a mixed-length workload -> BENCH_SERVING_PAGED.json
# (docs/serving.md "Paged KV cache"); gate: >= 2x peak concurrency
bench-serving-paged:
	JAX_PLATFORMS=cpu $(PY) bench_serving_paged.py

# end-to-end tracing suite (span recorder, lifecycle spans, exporters,
# console endpoints; docs/tracing.md)
test-trace:
	$(PY) -m pytest tests/ -q -m trace

# tracer overhead microbench: disabled vs enabled span cost in ns/op ->
# BENCH_TRACE.json (docs/tracing.md); the tier-1 guard is the
# `perf`-marker op-budget test in tests/test_trace.py
bench-trace:
	JAX_PLATFORMS=cpu $(PY) bench_trace.py

# cluster-scale trace-replay suite (workload generator, smoke replay
# through the real stack, scorecard gates; docs/benchmarks.md)
test-replay:
	$(PY) -m pytest tests/ -q -m replay

# fleet goodput & straggler telemetry suite (goodput accounting,
# throughput profiles, SlowSlice detection, pending-job explainer;
# docs/telemetry.md)
test-telemetry:
	$(PY) -m pytest tests/ -q -m telemetry

# SLO engine suite (objective grammar, error budgets, multi-window
# burn-rate alerting, console endpoints; docs/slo.md)
test-slo:
	$(PY) -m pytest tests/ -q -m slo

# durable control-plane suite (journal/snapshot recovery, watch
# bookmarks, sharded ownership, crash-mid-storm chaos e2e;
# docs/durability.md)
test-durability:
	$(PY) -m pytest tests/ -q -m durability

# forensics suite (WAL time-travel WorldLine, rv-reconstruction parity
# vs a live store, incident timeline + causal page->fault linking,
# postmortem determinism, console endpoints; docs/forensics.md)
test-forensics:
	$(PY) -m pytest tests/ -q -m forensics

# replicated control-plane suite (WAL shipping at the group-commit
# fsync boundary, follower apply idempotence, SIGKILL failover +
# promotion inside one lease term, leader-kill campaign e2e;
# docs/replication.md)
test-replication:
	$(PY) -m pytest tests/ -q -m replication

# concurrency-elastic training suite (min..max gang admission,
# shrink-in-place, restart-free reconfiguration via the 2-phase
# checkpoint protocol, checkpoint-tier upload contract, the
# shrink-vs-evict e2e; docs/elastic.md)
test-elastic:
	$(PY) -m pytest tests/ -q -m elastic

# concurrency-elastic shrink/regrow bench -> BENCH_ELASTIC.json
# (docs/elastic.md): the spot-shrink control-plane comparison (elastic
# shrink-in-place vs whole-gang eviction, 2 seeds) plus a real sharded
# trainer shrinking 8 -> 4 -> 8 devices through async multi-tier
# checkpoints with loss-curve continuity. Gates: zero restart rounds
# and zero Running-exits on the elastic leg, goodput strictly better
# and median recovery a fraction of the full-restart baseline's, async
# saves blocking < 1 step each; FAILS on regression vs the committed
# artifact. The tier-1 guard is tests/test_elastic_slices.py.
bench-elastic:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) bench_elastic.py

# SLO-driven serving-fleet suite (disaggregated prefill/decode
# block-table handoff, prefix LRU eviction, prefix-aware routing with
# tenant fairness, burn-rate autoscaling + drain semantics, gate-off
# contract; docs/serving_fleet.md)
test-serving-fleet:
	$(PY) -m pytest tests/ -q -m serving_fleet

# serving-fleet comparison bench -> BENCH_SERVING_FLEET.json
# (docs/serving_fleet.md): prefix-aware vs random routing (>= 1.5x
# prefix-hit rate), disaggregated vs combined prefill/decode on a
# long-prompt mix (>= 1.3x p99 TTFT at no decode-throughput loss), and
# the flash-crowd autoscaler leg (pages, scales, recovers without
# budget exhaustion, drains with zero dropped streams); FAILS on
# regression vs the committed artifact. The tier-1 guard is
# tests/test_serving_fleet.py.
bench-serving-fleet:
	JAX_PLATFORMS=cpu $(PY) bench_serving_fleet.py

test-federation:
	$(PY) -m pytest tests/ -q -m federation

# multi-region federation bench -> BENCH_FEDERATION.json
# (docs/federation.md): the federation profile's day across three
# regions with a mid-day region-evacuation; gates: zero acknowledged
# writes lost, zero dropped non-evacuated streams, every job completes,
# pages fire/clear/link, and the whole day bit-identical across two
# in-process runs; FAILS on regression vs the committed artifact. The
# tier-1 guard is tests/test_federation.py.
bench-federation:
	JAX_PLATFORMS=cpu $(PY) bench_federation.py

# RL post-training flywheel suite (GRPO math, rollout-tenant generation
# through the fleet router, weight publishing without dropped streams,
# version-pinned rollouts, RLJob controller, gate-off contract;
# docs/rl.md)
test-rl:
	$(PY) -m pytest tests/ -q -m rl

# RL flywheel bench -> BENCH_RL.json (docs/rl.md): the routing day with
# an RLJob riding the fleet as a low-priority rollout tenant vs the
# same day without it. Gates: user p99 TTFT within tolerance of the
# no-RL baseline, rollout throughput >= the declared floor, >= 2 weight
# publishes with zero dropped streams (user AND rollout), loss-curve
# continuity across one elastic learner resize (bit-identical restore),
# and the whole leg bit-identical across two in-process runs; FAILS on
# regression vs the committed artifact. The tier-1 guard is
# tests/test_rl.py.
bench-rl:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) bench_rl.py

# multi-model serving suite (adapter catalog + paged residency
# lifecycle, model-scoped prefix cache, adapter-affine routing,
# per-model SLOs, gate-off contract; docs/multimodel.md)
test-multimodel:
	$(PY) -m pytest tests/ -q -m multimodel

# multi-model bench -> BENCH_MULTIMODEL.json (docs/multimodel.md):
# the 30-adapter Zipf day, adapter-aware vs adapter-blind routing on
# identical traffic. Gates: affinity beats blind on adapter-fault rate
# AND model-request p99 TTFT, every model's SLO compliance column
# reported, adapter pages within the fleet HBM budget, zero dropped
# streams, and the whole leg bit-identical across two in-process runs;
# FAILS on regression vs the committed artifact. The tier-1 guard is
# tests/test_multimodel.py.
bench-multimodel:
	JAX_PLATFORMS=cpu $(PY) bench_multimodel.py

# render the committed adversarial campaign's forensics blocks as
# markdown postmortems (docs/forensics.md; regenerate the blocks with
# make bench-cluster-adversarial)
postmortem:
	$(PY) -m kubedl_tpu.forensics.report BENCH_CLUSTER_ADVERSARIAL.json

# THE fleet scorecard: a production-shaped day (thousands of jobs, tens
# of thousands of serving requests, chaos faults) through the real
# control plane + scheduler + serving engine on a sim clock ->
# BENCH_CLUSTER.json (docs/benchmarks.md). Bit-for-bit reproducible for
# a fixed seed; FAILS on absolute-gate misses AND on regression vs the
# committed scorecard. The tier-1 guard is the `perf`-marked smoke
# replay in tests/test_replay.py.
bench-cluster:
	JAX_PLATFORMS=cpu $(PY) bench_cluster.py --profile day

# the adversarial chaos-campaign gate (docs/chaos.md): for each seed,
# the declarative 'adversarial' scenario (domain outage, spot-dry
# capacity sweep, rolling drains, watch storms, hot-looping shard, slow
# WAL fsync) runs through the real stack TWICE (bit-for-bit determinism
# proven in-run) plus a fault-free reference of the same workload ->
# BENCH_CLUSTER_ADVERSARIAL.json. Gates on SLO survival: >= 1 page
# fires AND clears, no error budget exhausts, zero stranded
# alerts/conditions, object-level parity with the reference world;
# FAILS on regression vs the committed artifact (shared tolerance
# engine). The tier-1 guard is the e2e in tests/test_campaign.py.
bench-cluster-adversarial:
	JAX_PLATFORMS=cpu $(PY) bench_cluster.py --profile adversarial

# multi-chip sharding compile+execute proof on a virtual mesh
dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# regenerate config/crd/bases from the API types
crds:
	$(PY) hack/gen_crds.py

# standalone control plane with console + sqlite persistence
run-standalone:
	$(PY) -m kubedl_tpu --workloads PyTorchJob,TFJob,JAXJob \
	    --object-storage sqlite:///tmp/kubedl.db \
	    --event-storage sqlite:///tmp/kubedl.db \
	    --console-port 9090

lint:
	$(PY) -m compileall -q kubedl_tpu tests bench.py __graft_entry__.py

# native runtime components (C++ data packer; auto-built on first use too)
native:
	$(PY) -c "from kubedl_tpu.native import ensure_built; print(ensure_built() or 'no compiler')"
