"""Serving-fleet bench: routing, disaggregation, autoscaling — one JSON.

Three comparison legs through the REAL fleet stack (engines + router +
autoscaler + headless SLO engine) on a sim clock (docs/serving_fleet.md):

* **routing** — prefix-cache-aware placement vs seeded-random placement
  on the identical tenant-labelled Zipf-prefix day; gate: the aware
  router's prefix-hit rate (requests landing on a replica ALREADY
  holding their shared prefix blocks) is >= 1.5x the random baseline's.
* **disagg** — disaggregated prefill/decode lanes (block-table handoff
  through the shared pool) vs the combined engine on a
  long-prompt-heavy mix; gates: p99 TTFT improves >= 1.3x at no
  decode-throughput loss.
* **autoscaler** — a flash crowd against a one-replica fleet: the TTFT
  objective PAGES, replicas scale up (the page verdict is a scale
  reason), the burn clears without exhausting the error budget, and the
  post-crowd quiet drains the fleet back down with zero dropped
  streams.

The document is bit-for-bit reproducible for a fixed ``--seed`` (no
wall clocks; workload fingerprints committed). When a committed
``BENCH_SERVING_FLEET.json`` exists at ``--out``, the fresh run is
checked against it and the bench FAILS on regression — the shared
tolerance engine, like every other bench.

Usage::

    python bench_serving_fleet.py [--seed 0] [--out FILE] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

#: absolute gates over the scorecard (path, op, threshold)
GATES = (
    ("routing.hit_rate_ratio", ">=", 1.5),
    ("routing.prefix_aware.completed_fraction", ">=", 1.0),
    ("routing.random.completed_fraction", ">=", 1.0),
    ("routing.prefix_aware.errors", "<=", 0),
    ("routing.random.errors", "<=", 0),
    ("disagg.ttft_p99_ratio", ">=", 1.3),
    ("disagg.decode_tokens_ratio", ">=", 1.0),
    ("disagg.disaggregated.handoffs", ">=", 1),
    ("disagg.disaggregated.completed_fraction", ">=", 1.0),
    ("disagg.combined.completed_fraction", ">=", 1.0),
    ("autoscaler.completed_fraction", ">=", 1.0),
    ("autoscaler.pages_fired", ">=", 1),
    ("autoscaler.stranded_alerts", "<=", 0),
    ("autoscaler.min_budget_remaining", ">=", 0.0),
    ("autoscaler.fleet.scale_ups", ">=", 1),
    ("autoscaler.fleet.drains", ">=", 1),
    ("autoscaler.fleet.reaped_count", ">=", 1),
    ("autoscaler.dropped_streams", "<=", 0),
    ("autoscaler.requests_unfinished", "<=", 0),
)

#: regression tolerances vs the committed artifact (shared engine)
REGRESSION = (
    ("routing.hit_rate_ratio", "higher_better", 0.05, 0.02),
    ("routing.prefix_aware.prefix_hit_rate", "higher_better", 0.05, 0.02),
    ("disagg.ttft_p99_ratio", "higher_better", 0.10, 0.05),
    ("disagg.decode_tokens_ratio", "higher_better", 0.02, 0.01),
    ("disagg.disaggregated.ttft_s.p99", "lower_better", 0.12, 0.05),
    ("autoscaler.min_budget_remaining", "higher_better", 0.10, 0.05),
    ("autoscaler.ttft_s.p99", "lower_better", 0.15, 0.5),
)


def evaluate_gates(scorecard: dict) -> dict:
    from kubedl_tpu.replay.scorecard import _get
    results, ok = [], True
    for path, op, threshold in GATES:
        value = _get(scorecard, path)
        passed = (value is not None
                  and (value >= threshold if op == ">=" else
                       value <= threshold))
        ok = ok and passed
        results.append({"metric": path, "op": op, "threshold": threshold,
                        "value": value, "passed": passed})
    return {"checks": results, "passed": ok}


def check_regression(new: dict, old: dict) -> list:
    from kubedl_tpu.replay.scorecard import check_tolerances
    if old.get("seed") != new.get("seed"):
        return []
    problems = check_tolerances(new, old, REGRESSION)
    for path in ("autoscaler.dropped_streams",
                 "autoscaler.stranded_alerts"):
        from kubedl_tpu.replay.scorecard import _get
        if _get(new, path):
            problems.append(f"{path} must stay 0")
    return problems


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="BENCH_SERVING_FLEET.json")
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()

    from dataclasses import asdict

    from kubedl_tpu.replay.fleet import (FLEET_PROFILES,
                                         run_autoscaler_leg,
                                         run_disagg_comparison,
                                         run_routing_comparison)

    t0 = time.perf_counter()
    routing = run_routing_comparison(args.seed)
    t1 = time.perf_counter()
    print(f"routing leg in {t1 - t0:.1f}s wall: hit-rate ratio "
          f"{routing['hit_rate_ratio']} (aware "
          f"{routing['prefix_aware']['prefix_hit_rate']} vs random "
          f"{routing['random']['prefix_hit_rate']})", file=sys.stderr)
    disagg = run_disagg_comparison(args.seed)
    t2 = time.perf_counter()
    print(f"disagg leg in {t2 - t1:.1f}s wall: p99 TTFT ratio "
          f"{disagg['ttft_p99_ratio']}, decode tokens ratio "
          f"{disagg['decode_tokens_ratio']}, "
          f"{disagg['disaggregated']['handoffs']} handoffs",
          file=sys.stderr)
    autoscaler = run_autoscaler_leg(args.seed)
    print(f"autoscaler leg in {time.perf_counter() - t2:.1f}s wall: "
          f"{autoscaler['pages_fired']} page(s), "
          f"{autoscaler['fleet']['scale_ups']} scale-ups, "
          f"{autoscaler['fleet']['drains']} drains, min budget "
          f"{autoscaler['min_budget_remaining']}", file=sys.stderr)

    scorecard = {
        "benchmark": "serving_fleet",
        "seed": args.seed,
        "profiles": {name: asdict(p)
                     for name, p in sorted(FLEET_PROFILES.items())},
        "routing": routing,
        "disagg": disagg,
        "autoscaler": autoscaler,
    }
    scorecard["gates"] = evaluate_gates(scorecard)

    problems = []
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_regression(scorecard, committed)

    print(json.dumps(scorecard))
    if not scorecard["gates"]["passed"]:
        failed = [c for c in scorecard["gates"]["checks"]
                  if not c["passed"]]
        raise SystemExit(f"GATE FAILED: {failed}")
    if problems:
        raise SystemExit("REGRESSION vs committed scorecard:\n  "
                         + "\n  ".join(problems))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(scorecard, f, indent=2, sort_keys=True)
            f.write("\n")
    return scorecard


if __name__ == "__main__":
    main()
