"""Tracer overhead microbench (`make bench-trace` -> BENCH_TRACE.json).

Measures the per-call cost of the span recorder in its three states:

* **disabled** — the production-off hot path (`tracer.span(...)` with
  ``enabled=False``): must be nanoseconds, because every reconcile /
  scheduling pass / decode tick pays it once tracing ships everywhere;
* **enabled (with)** — the context-manager path components use for
  in-line measurement;
* **enabled (record)** — the explicit-timestamp path the scheduler and
  lifecycle tracer use.

The wall-clock-free tier-1 guard is the ``perf``-marked op-budget test
in ``tests/test_trace.py``; this script puts real numbers on the same
path for the record. Gate: the disabled path must cost at most
``DISABLED_MAX_FRACTION`` of the enabled path — if disabling tracing
doesn't make it (much) cheaper, the gate is broken.
"""

from __future__ import annotations

import json
import time

from kubedl_tpu.trace import Tracer

N = 200_000
DISABLED_MAX_FRACTION = 0.5


def _bench(fn, n: int = N) -> float:
    # warmup, then best-of-3 (ns per op)
    for _ in range(1000):
        fn()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        best = min(best, time.perf_counter() - t0)
    return best / n * 1e9


def main() -> int:
    disabled = Tracer(enabled=False)
    enabled = Tracer(enabled=True, capacity=4096)

    def span_disabled():
        with disabled.span("x", component="bench"):
            pass

    def span_enabled():
        with enabled.span("x", component="bench"):
            pass

    def record_enabled():
        enabled.record("x", 0.0, 1.0, component="bench")

    out = {
        "n": N,
        "disabled_span_ns": round(_bench(span_disabled), 1),
        "enabled_span_ns": round(_bench(span_enabled), 1),
        "enabled_record_ns": round(_bench(record_enabled), 1),
        "ring_capacity": enabled.capacity,
        "gate": {"disabled_max_fraction_of_enabled": DISABLED_MAX_FRACTION},
    }
    out["disabled_fraction_of_enabled"] = round(
        out["disabled_span_ns"] / max(out["enabled_span_ns"], 1e-9), 4)
    out["gate_ok"] = (out["disabled_fraction_of_enabled"]
                      <= DISABLED_MAX_FRACTION)
    with open("BENCH_TRACE.json", "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0 if out["gate_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
