"""Multi-model serving bench: adapter-aware vs adapter-blind — one JSON.

One comparison leg through the REAL multi-model stack (engines with a
shared AdapterCatalog paging weight pages through their refcounted
pools + the adapter-affine PrefixAwareRouter + per-model SLO
objectives) on a sim clock (docs/multimodel.md):

* **multimodel** — the 30-adapter Zipf day, placed twice on identical
  traffic: adapter-AWARE routing (prefer resident replicas; cold
  models get consistent-hash homes, so the fleet partitions the
  catalog) vs adapter-BLIND routing (the model rides to the engine but
  placement ignores residency — every replica churns through the whole
  catalog and the per-replica residency cap binds). Gates: affinity
  beats blind on adapter-fault rate AND model-request p99 TTFT, every
  model's SLO compliance column reported, adapter pages within the
  fleet HBM page cap, zero errors / dropped streams / unfinished
  requests on both arms, and the aware arm bit-identical across two
  in-process runs.

The document is bit-for-bit reproducible for a fixed ``--seed`` (no
wall clocks; the workload fingerprint is committed). When a committed
``BENCH_MULTIMODEL.json`` exists at ``--out``, the fresh run is
checked against it and the bench FAILS on regression — the shared
tolerance engine, like every other bench.

Usage::

    python bench_multimodel.py [--seed 0] [--out FILE] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

#: absolute gates over the scorecard (path, op, threshold)
GATES = (
    ("fault_rate_ratio", ">=", 2.0),
    ("model_ttft_p99_ratio", ">=", 1.05),
    ("adapter_aware.completed_fraction", ">=", 1.0),
    ("adapter_blind.completed_fraction", ">=", 1.0),
    ("adapter_aware.errors", "<=", 0),
    ("adapter_blind.errors", "<=", 0),
    ("adapter_aware.dropped_streams", "<=", 0),
    ("adapter_blind.dropped_streams", "<=", 0),
    ("adapter_aware.requests_unfinished", "<=", 0),
    ("adapter_blind.requests_unfinished", "<=", 0),
    ("adapter_aware.multi_model.models_reported", ">=", 30),
    ("adapter_blind.multi_model.models_reported", ">=", 30),
    ("adapter_aware.multi_model.hbm.within_cap", ">=", 1),
    ("adapter_blind.multi_model.hbm.within_cap", ">=", 1),
    ("adapter_aware.multi_model.adapter_faults", ">=", 1),
    ("deterministic", ">=", 1),
)

#: regression tolerances vs the committed artifact (shared engine)
REGRESSION = (
    ("fault_rate_ratio", "higher_better", 0.15, 0.5),
    ("model_ttft_p99_ratio", "higher_better", 0.10, 0.05),
    ("adapter_aware.multi_model.fault_rate", "lower_better", 0.15, 0.01),
    ("adapter_aware.ttft_s.p99", "lower_better", 0.15, 0.05),
    ("adapter_aware.multi_model.model_ttft_s.p99", "lower_better",
     0.15, 0.05),
)


def evaluate_gates(scorecard: dict) -> dict:
    from kubedl_tpu.replay.scorecard import _get
    results, ok = [], True
    for path, op, threshold in GATES:
        value = _get(scorecard, path)
        passed = (value is not None
                  and (value >= threshold if op == ">=" else
                       value <= threshold))
        ok = ok and passed
        results.append({"metric": path, "op": op, "threshold": threshold,
                        "value": value, "passed": passed})
    return {"checks": results, "passed": ok}


def check_regression(new: dict, old: dict) -> list:
    from kubedl_tpu.replay.scorecard import _get, check_tolerances
    if old.get("seed") != new.get("seed"):
        return []
    problems = check_tolerances(new, old, REGRESSION)
    for path in ("adapter_aware.dropped_streams",
                 "adapter_blind.dropped_streams",
                 "adapter_aware.requests_unfinished",
                 "adapter_blind.requests_unfinished"):
        if _get(new, path):
            problems.append(f"{path} must stay 0")
    return problems


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="BENCH_MULTIMODEL.json")
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()

    from dataclasses import asdict

    from kubedl_tpu.replay.multimodel import (MULTIMODEL_PROFILES,
                                              MultiModelReplay, _mm_leg,
                                              generate_multimodel,
                                              run_multimodel_comparison)

    t0 = time.perf_counter()
    comparison = run_multimodel_comparison(args.seed)
    t1 = time.perf_counter()
    aware = comparison["adapter_aware"]["multi_model"]
    blind = comparison["adapter_blind"]["multi_model"]
    print(f"comparison in {t1 - t0:.1f}s wall: fault-rate ratio "
          f"{comparison['fault_rate_ratio']} (aware {aware['fault_rate']}"
          f" vs blind {blind['fault_rate']}), model p99 TTFT ratio "
          f"{comparison['model_ttft_p99_ratio']}, "
          f"{aware['models_reported']}/{aware['models']} models "
          "reported", file=sys.stderr)

    # determinism: the aware arm replayed in-process must reproduce the
    # comparison's aware leg bit for bit (sim clock only — no wall
    # time, no process-global state leaks between runs)
    rerun = _mm_leg(MultiModelReplay(
        generate_multimodel("multimodel", args.seed),
        adapter_affinity=True).run())
    deterministic = int(
        json.dumps(rerun, sort_keys=True)
        == json.dumps(comparison["adapter_aware"], sort_keys=True))
    print(f"determinism leg in {time.perf_counter() - t1:.1f}s wall: "
          f"{'bit-identical' if deterministic else 'DIVERGED'}",
          file=sys.stderr)

    scorecard = {
        "benchmark": "multimodel",
        "seed": args.seed,
        "profiles": {name: asdict(p)
                     for name, p in sorted(MULTIMODEL_PROFILES.items())},
        "deterministic": deterministic,
        **comparison,
    }
    scorecard["gates"] = evaluate_gates(scorecard)

    problems = []
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_regression(scorecard, committed)

    print(json.dumps(scorecard))
    if not scorecard["gates"]["passed"]:
        failed = [c for c in scorecard["gates"]["checks"]
                  if not c["passed"]]
        raise SystemExit(f"GATE FAILED: {failed}")
    if problems:
        raise SystemExit("REGRESSION vs committed scorecard:\n  "
                         + "\n  ".join(problems))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(scorecard, f, indent=2, sort_keys=True)
            f.write("\n")
    return scorecard


if __name__ == "__main__":
    main()
