"""Cluster-scale trace-replay bench: one fleet scorecard, one JSON.

Replays a production-shaped day (``--profile day``: thousands of jobs
with bursty arrivals and chaos faults, tens of thousands of serving
requests with Zipf-shared prefixes) through the REAL control plane +
slice scheduler + paged-KV serving engine on a simulated clock, with
tracing enabled, and emits ``BENCH_CLUSTER.json`` — settle throughput,
queue-delay p50/p99, slice utilization, TTFT p99, restart MTTR,
preemption/backfill counts — derived entirely from the system's own
traces and metrics (docs/benchmarks.md has the schema).

The scorecard is bit-for-bit reproducible for a fixed ``--seed``: no
wall clocks enter the document (the run's wall time goes to stderr).
When a committed scorecard exists at ``--out``, the fresh run is also
checked against it and the bench FAILS on regression — one number every
future PR must move, never backslide.

Usage::

    python bench_cluster.py [--profile smoke|day] [--seed 0]
                            [--out BENCH_CLUSTER.json] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--profile", choices=("smoke", "day"), default="day")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="BENCH_CLUSTER.json")
    ap.add_argument("--no-check", action="store_true",
                    help="skip the regression check against the "
                         "committed scorecard at --out")
    ap.add_argument("--skip-serving", action="store_true",
                    help="job day only (debugging aid; gates involving "
                         "serving will fail)")
    args = ap.parse_args()

    from kubedl_tpu.replay import (ClusterReplay, ServingReplay,
                                   build_scorecard, check_regression,
                                   evaluate_gates, generate)

    workload = generate(args.profile, args.seed)
    print(f"workload: {len(workload.jobs)} jobs, "
          f"{len(workload.serving)} serving requests, "
          f"fingerprint {workload.fingerprint()[:16]}", file=sys.stderr)

    t0 = time.perf_counter()
    cluster = ClusterReplay(workload).run()
    t1 = time.perf_counter()
    print(f"job day replayed in {t1 - t0:.1f}s wall "
          f"({cluster['rounds']} rounds, "
          f"{cluster['controlplane']['reconciles']} reconciles)",
          file=sys.stderr)
    if args.skip_serving:
        serving = {"requests_submitted": 0, "requests_completed": 0,
                   "requests_unfinished": 0, "errors": 0,
                   "resumed_admissions": 0, "shared_prefix_admissions": 0,
                   "tokens_generated": 0, "engine_ticks": 0,
                   "sim_span_s": 0.0, "slo": {},
                   "queue_waits_s": [], "ttfts_s": [],
                   "kv": {}}
    else:
        serving = ServingReplay(workload).run()
        print(f"serving day replayed in {time.perf_counter() - t1:.1f}s "
              f"wall ({serving['engine_ticks']} ticks, "
              f"{serving['tokens_generated']} tokens)", file=sys.stderr)

    scorecard = build_scorecard(workload, cluster, serving)
    scorecard["gates"] = evaluate_gates(scorecard)

    problems = []
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_regression(scorecard, committed)

    print(json.dumps(scorecard))
    if not scorecard["gates"]["passed"]:
        failed = [c for c in scorecard["gates"]["checks"]
                  if not c["passed"]]
        raise SystemExit(f"GATE FAILED: {failed}")
    if problems:
        # keep the committed baseline intact on regression
        raise SystemExit("REGRESSION vs committed scorecard:\n  "
                         + "\n  ".join(problems))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(scorecard, f, indent=2, sort_keys=True)
            f.write("\n")
    return scorecard


if __name__ == "__main__":
    main()
