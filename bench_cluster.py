"""Cluster-scale trace-replay bench: one fleet scorecard, one JSON.

Replays a production-shaped day (``--profile day``: thousands of jobs
with bursty arrivals and chaos faults, tens of thousands of serving
requests with Zipf-shared prefixes) through the REAL control plane +
slice scheduler + paged-KV serving engine on a simulated clock, with
tracing enabled, and emits ``BENCH_CLUSTER.json`` — settle throughput,
queue-delay p50/p99, slice utilization, TTFT p99, restart MTTR,
preemption/backfill counts — derived entirely from the system's own
traces and metrics (docs/benchmarks.md has the schema).

The scorecard is bit-for-bit reproducible for a fixed ``--seed``: no
wall clocks enter the document (the run's wall time goes to stderr).
When a committed scorecard exists at ``--out``, the fresh run is also
checked against it and the bench FAILS on regression — one number every
future PR must move, never backslide.

The ``--profile adversarial`` leg is the chaos-campaign gate
(docs/chaos.md): for each ``--seeds`` seed it compiles the declarative
``adversarial`` scenario (correlated domain outage, spot-dry sweep,
rolling drains, watch storms, hot-looping shard, slow WAL fsync), drives
the job day through the REAL stack with the campaign firing, re-runs it
to prove bit-for-bit determinism, replays a fault-free reference of the
same workload, and commits ``BENCH_CLUSTER_ADVERSARIAL.json`` gated on
SLO survival: at least one page fires AND clears, no error budget
exhausts, zero stranded alerts/conditions, and the post-campaign control
plane reaches object-level parity with the reference world. Each seed
block also carries a ``forensics`` postmortem (docs/forensics.md) —
every fired page causally linked to the injected fault window(s) that
caused it — rendered to markdown by ``make postmortem``.

Usage::

    python bench_cluster.py [--profile smoke|day|adversarial] [--seed 0]
                            [--seeds 0,1] [--out FILE] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def run_adversarial(args) -> dict:
    from kubedl_tpu.chaos import build_campaign
    from kubedl_tpu.replay import (ClusterReplay,
                                   build_campaign_scorecard,
                                   check_campaign_regression,
                                   evaluate_campaign_gates, generate)

    if args.seeds is not None:
        seeds = [int(s) for s in str(args.seeds).split(",")
                 if s.strip() != ""]
    elif args.seed is not None:
        seeds = [args.seed]          # replaying one failed campaign
    else:
        seeds = [0, 1]               # the committed-artifact default
    if not seeds:
        raise SystemExit("--seeds must name at least one seed "
                         "(e.g. --seeds 0,1)")
    legs = []
    for seed in seeds:
        workload = generate("adversarial", seed)
        campaign = build_campaign(args.scenario, seed, workload.profile)
        print(f"seed {seed}: {len(workload.jobs)} jobs, campaign "
              f"{args.scenario} with {len(campaign.actions)} actions, "
              f"fingerprint {campaign.fingerprint()[:16]}",
              file=sys.stderr)

        def one_run():
            wl = generate("adversarial", seed)
            camp = build_campaign(args.scenario, seed, wl.profile)
            with tempfile.TemporaryDirectory() as jdir:
                replay = ClusterReplay(wl, shards=4, campaign=camp,
                                       journal_dir=jdir)
                res = replay.run()
                return res, replay.control_plane_state()

        t0 = time.perf_counter()
        result, state = one_run()
        repeat, repeat_state = one_run()
        deterministic = (
            json.dumps(result, sort_keys=True)
            == json.dumps(repeat, sort_keys=True)
            and state == repeat_state)
        reference = ClusterReplay(generate("adversarial", seed))
        ref_result = reference.run()
        ref_state = reference.control_plane_state()
        fsum = result["forensics"]["summary"]
        print(f"seed {seed}: campaign x2 + reference replayed in "
              f"{time.perf_counter() - t0:.1f}s wall "
              f"(deterministic={deterministic}, "
              f"pages={result['slo_health']['pages_fired']}, "
              f"min budget "
              f"{result['slo_health']['min_budget_remaining']}, "
              f"forensics: {fsum['pages_linked']}/{fsum['pages']} pages "
              f"linked via {fsum['links_total']} links)",
              file=sys.stderr)
        legs.append({"workload": workload, "result": result,
                     "state": state, "reference": ref_result,
                     "reference_state": ref_state,
                     "deterministic": deterministic})

    scorecard = build_campaign_scorecard(args.scenario, legs)
    scorecard["gates"] = evaluate_campaign_gates(scorecard)

    problems = []
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_campaign_regression(scorecard, committed)

    print(json.dumps(scorecard))
    if not scorecard["gates"]["passed"]:
        failed = [c for c in scorecard["gates"]["checks"]
                  if not c["passed"]]
        raise SystemExit(f"GATE FAILED: {failed}")
    if problems:
        raise SystemExit("REGRESSION vs committed scorecard:\n  "
                         + "\n  ".join(problems))
    # a narrowed debug replay (--seed N / --seeds / --scenario) must not
    # silently rewrite the committed two-seed artifact with a subset:
    # check_campaign_regression only compares seeds present in BOTH
    # artifacts, so the lost baseline would never be flagged. Write the
    # defaulted path only for the committed shape; a debug run needs an
    # explicit --out.
    committed_shape = (seeds == [0, 1]
                       and args.scenario == "adversarial")
    if args.out and (getattr(args, "out_explicit", True)
                     or committed_shape):
        with open(args.out, "w") as f:
            json.dump(scorecard, f, indent=2, sort_keys=True)
            f.write("\n")
    elif args.out:
        print(f"not writing {args.out}: narrowed debug run "
              f"(seeds={seeds}, scenario={args.scenario!r}) would "
              f"replace the committed artifact with a "
              f"{len(seeds)}-seed subset; pass --out explicitly to "
              f"write it",
              file=sys.stderr)
    return scorecard


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--profile", choices=("smoke", "day", "adversarial"),
                    default="day")
    ap.add_argument("--seed", type=int, default=None,
                    help="workload seed (default 0); for --profile "
                         "adversarial a bare --seed N runs that one "
                         "campaign seed")
    ap.add_argument("--seeds", default=None,
                    help="adversarial profile: comma-separated campaign "
                         "seeds (each is a full run set; default 0,1 — "
                         "the committed artifact)")
    ap.add_argument("--scenario", default="adversarial",
                    help="adversarial profile: scenario name from "
                         "kubedl_tpu.chaos.SCENARIOS")
    ap.add_argument("--out", default=None,
                    help="scorecard path (default BENCH_CLUSTER.json, "
                         "or BENCH_CLUSTER_ADVERSARIAL.json for "
                         "--profile adversarial)")
    ap.add_argument("--no-check", action="store_true",
                    help="skip the regression check against the "
                         "committed scorecard at --out")
    ap.add_argument("--skip-serving", action="store_true",
                    help="job day only (debugging aid; gates involving "
                         "serving will fail)")
    ap.add_argument("--skip-elastic", action="store_true",
                    help="skip the concurrency-elastic shrink-vs-evict "
                         "leg (debugging aid; the day profile's "
                         "jobs.elastic gates will fail)")
    ap.add_argument("--skip-fleet", action="store_true",
                    help="skip the multi-replica serving-fleet leg "
                         "(debugging aid; the day profile's "
                         "serving.fleet gates will fail)")
    args = ap.parse_args()
    args.out_explicit = args.out is not None
    if args.out is None:
        args.out = ("BENCH_CLUSTER_ADVERSARIAL.json"
                    if args.profile == "adversarial"
                    else "BENCH_CLUSTER.json")
    if args.profile == "adversarial":
        return run_adversarial(args)
    if args.seed is None:
        args.seed = 0

    from kubedl_tpu.replay import (ClusterReplay, ServingReplay,
                                   build_scorecard, check_regression,
                                   evaluate_gates, generate)

    workload = generate(args.profile, args.seed)
    print(f"workload: {len(workload.jobs)} jobs, "
          f"{len(workload.serving)} serving requests, "
          f"fingerprint {workload.fingerprint()[:16]}", file=sys.stderr)

    t0 = time.perf_counter()
    cluster = ClusterReplay(workload).run()
    t1 = time.perf_counter()
    print(f"job day replayed in {t1 - t0:.1f}s wall "
          f"({cluster['rounds']} rounds, "
          f"{cluster['controlplane']['reconciles']} reconciles)",
          file=sys.stderr)
    if args.skip_serving:
        serving = {"requests_submitted": 0, "requests_completed": 0,
                   "requests_unfinished": 0, "errors": 0,
                   "resumed_admissions": 0, "shared_prefix_admissions": 0,
                   "tokens_generated": 0, "engine_ticks": 0,
                   "sim_span_s": 0.0, "slo": {},
                   "queue_waits_s": [], "ttfts_s": [],
                   "kv": {}}
    else:
        serving = ServingReplay(workload).run()
        print(f"serving day replayed in {time.perf_counter() - t1:.1f}s "
              f"wall ({serving['engine_ticks']} ticks, "
              f"{serving['tokens_generated']} tokens)", file=sys.stderr)

    if args.profile == "day" and not args.skip_serving \
            and not args.skip_fleet:
        # the multi-replica serving-fleet leg (docs/serving_fleet.md):
        # routing / disaggregation / autoscaling comparisons committed
        # as the additive serving.fleet block — the single-engine
        # serving day above is untouched, so every prior metric stays
        # byte-identical
        from kubedl_tpu.replay import run_fleet_comparison
        tf = time.perf_counter()
        serving["fleet"] = run_fleet_comparison(args.seed)
        fl = serving["fleet"]
        print(f"serving-fleet leg replayed in "
              f"{time.perf_counter() - tf:.1f}s wall (hit-rate ratio "
              f"{fl['routing']['hit_rate_ratio']}, ttft p99 ratio "
              f"{fl['disagg']['ttft_p99_ratio']}, "
              f"{fl['autoscaler']['pages_fired']} page(s))",
              file=sys.stderr)

    if args.profile == "day" and not args.skip_elastic:
        # the concurrency-elastic leg (docs/elastic.md): shrink-vs-evict
        # through a spot-shrink window, committed as the additive
        # jobs.elastic block — the day leg above is untouched, so every
        # prior metric stays byte-identical
        from kubedl_tpu.replay import run_elastic_comparison
        t2 = time.perf_counter()
        cluster["elastic"] = run_elastic_comparison(args.seed)
        eb = cluster["elastic"]
        print(f"elastic leg replayed in {time.perf_counter() - t2:.1f}s "
              f"wall (goodput gain "
              f"{eb['gains']['goodput_gain']}, recovery p50 ratio "
              f"{eb['gains']['recovery_p50_ratio']})", file=sys.stderr)

    scorecard = build_scorecard(workload, cluster, serving)
    scorecard["gates"] = evaluate_gates(scorecard)

    problems = []
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_regression(scorecard, committed)

    print(json.dumps(scorecard))
    if not scorecard["gates"]["passed"]:
        failed = [c for c in scorecard["gates"]["checks"]
                  if not c["passed"]]
        raise SystemExit(f"GATE FAILED: {failed}")
    if problems:
        # keep the committed baseline intact on regression
        raise SystemExit("REGRESSION vs committed scorecard:\n  "
                         + "\n  ".join(problems))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(scorecard, f, indent=2, sort_keys=True)
            f.write("\n")
    return scorecard


if __name__ == "__main__":
    main()
