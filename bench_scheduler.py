"""Slice-scheduler policy benchmark: one deterministic synthetic trace,
two admission policies, one JSON line.

``bench_controlplane.py`` measures how fast the operator settles jobs;
this one measures how well the *scheduler* uses finite slice inventory.
A fixed trace of mixed single-/multislice gangs across 3 tenant queues is
replayed twice on identical capacity:

* **fcfs** — the pre-scheduler world: one global FIFO, no quota, no
  backfill; a gang that does not fit blocks everything behind it (which
  is what "whoever the kube-scheduler binds first" degenerates to under
  contention, with head-of-line blocking across unrelated pools);
* **scheduler** — the real ``SliceScheduler`` driven over the in-memory
  API server with a simulated clock: per-queue FIFO, elastic quota,
  priority ordering, and reservation backfill.

Both runs report makespan, slice utilization (busy slice-seconds over
capacity x makespan), and p50/p99 queueing delay. Gate (the ISSUE 4
acceptance): scheduler utilization >= 1.3x FCFS at no worse makespan.

The trace is the classic head-of-line pathology: a large multislice job
blocks the FIFO while a different pool sits idle. Everything is seeded /
literal — no wall clock, no RNG — so the JSON is reproducible bit-for-bit.

Usage::

    python bench_scheduler.py [--out BENCH_SCHEDULER.json]
"""

from __future__ import annotations

import argparse
import heapq
import json
import time

from kubedl_tpu.api import common as c
from kubedl_tpu.api.queue import new_queue
from kubedl_tpu.core import meta as m
from kubedl_tpu.core.apiserver import APIServer
from kubedl_tpu.core.clock import SimClock
from kubedl_tpu.core.manager import Manager
from kubedl_tpu.metrics.registry import SchedulerMetrics
from kubedl_tpu.scheduling.gang import is_gang_admitted
from kubedl_tpu.scheduling.inventory import SliceInventory
from kubedl_tpu.scheduling.scheduler import SliceScheduler
from kubedl_tpu.utils.stats import percentile

POOL_A = "tpu-v5p-slice/2x2x4"        # 3D torus training pool
POOL_B = "tpu-v5-lite-podslice/4x4"   # 2D inference/finetune pool
CAPACITY = {POOL_A: 8, POOL_B: 8}

QUEUES = (
    {"name": "prod", "min": 4, "max": None, "priority": 100},
    {"name": "batch", "min": 2, "max": None, "priority": 10},
    {"name": "best", "min": 0, "max": None, "priority": 0},
)


def build_trace() -> list:
    """(arrival_s, job, queue, pool, slices, duration_s) — deterministic.

    Two long multislice pool-A jobs saturate pool A immediately; 64 short
    single-slice pool-B jobs arrive right behind them. FCFS blocks every
    pool-B job behind the second pool-A gang for its whole wait; the
    scheduler lets pool B run concurrently (per-queue FIFO + backfill)."""
    trace = [
        (0.0, "batch-warm", "batch", POOL_A, 8, 300.0),
        (1.0, "batch-big", "batch", POOL_A, 6, 300.0),
    ]
    # first wave (t=2) lands in batch, BEHIND the blocked batch-big head:
    # those admissions are true backfills (different pool, cannot delay it)
    queues = ("batch", "prod", "best", "prod")
    for i in range(64):
        trace.append((2.0 + (i % 8), f"ft-{i:03d}", queues[i % 4],
                      POOL_B, 1, 100.0))
    # a late second wave of pool-A work keeps pool A busy after the warm
    # job drains (both policies run it; it anchors the pool-A critical path)
    trace.append((320.0, "batch-tail", "batch", POOL_A, 4, 200.0))
    return sorted(trace, key=lambda t: (t[0], t[1]))


def _stats(records: dict, capacity: dict, arrivals: dict) -> dict:
    """makespan / utilization / queue-delay percentiles from
    job -> (admit_t, end_t, slices, duration)."""
    t0 = min(arrivals.values())
    end = max(r[1] for r in records.values())
    makespan = end - t0
    busy = sum(r[2] * r[3] for r in records.values())
    total = sum(capacity.values())
    delays = [r[0] - arrivals[j] for j, r in records.items()]

    return {
        "makespan_s": round(makespan, 1),
        "slice_utilization": round(busy / (total * makespan), 4),
        "queue_delay_p50_s": round(percentile(delays, 0.50), 1),
        "queue_delay_p99_s": round(percentile(delays, 0.99), 1),
        "jobs": len(records),
    }


# ---------------------------------------------------------------------------
# baseline: global FIFO, no quota, head-of-line blocking
# ---------------------------------------------------------------------------


def run_fcfs(trace: list) -> dict:
    free = dict(CAPACITY)
    waiting = list(trace)  # already arrival-sorted: THE global FIFO
    completions: list = []  # (end_t, job, pool, slices)
    records, arrivals = {}, {t[1]: t[0] for t in trace}
    t = 0.0
    while waiting or completions:
        # admit strictly from the head; the first non-fitting gang blocks
        while waiting:
            arr, job, _q, pool, slices, dur = waiting[0]
            if arr > t or free[pool] < slices:
                break
            waiting.pop(0)
            free[pool] -= slices
            records[job] = (t, t + dur, slices, dur)
            heapq.heappush(completions, (t + dur, job, pool, slices))
        # advance to the next event: an arrival or a completion
        nxt = []
        if waiting and waiting[0][0] > t:
            nxt.append(waiting[0][0])
        if completions:
            nxt.append(completions[0][0])
        if not nxt:
            if waiting:  # head blocked with no completion coming: stuck
                raise RuntimeError("FCFS wedged (trace exceeds capacity)")
            break
        t = min(nxt)
        while completions and completions[0][0] <= t:
            _, _job, pool, slices = heapq.heappop(completions)
            free[pool] += slices
    return _stats(records, CAPACITY, arrivals)


# ---------------------------------------------------------------------------
# the real scheduler over the in-memory control plane
# ---------------------------------------------------------------------------


def make_pgs(api, job, queue, pool, slices, priority=0):
    names = []
    for sid in range(slices):
        name = job if slices == 1 else f"{job}-slice-{sid}"
        pg = m.new_obj("scheduling.sigs.k8s.io/v1alpha1", "PodGroup", name,
                       labels={c.LABEL_GANG_JOB_NAME: job},
                       annotations={
                           c.ANNOTATION_SCHED_POOL: pool,
                           c.ANNOTATION_SCHED_QUEUE: queue,
                           c.ANNOTATION_SCHED_NUM_SLICES: str(slices),
                           c.ANNOTATION_SCHED_PRIORITY: str(priority),
                       })
        pg["spec"] = {"minMember": 1}
        api.create(pg)
        names.append(name)
    return names


def run_scheduler(trace: list) -> dict:
    clock = SimClock()
    api = APIServer(clock=clock)
    manager = Manager(api, clock=clock)
    sched = SliceScheduler(
        api, inventory=SliceInventory(api, static_capacity=CAPACITY),
        metrics=SchedulerMetrics())
    manager.register(sched)
    for q in QUEUES:
        api.create(new_queue(**q))

    arrivals = {t[1]: t[0] for t in trace}
    meta = {t[1]: t for t in trace}
    pg_names: dict[str, list] = {}
    pending_arrivals = list(trace)
    completions: list = []  # (sim_end_t, job, admit_t token)
    records: dict[str, tuple] = {}
    admitted: set = set()
    finished: set = set()
    preemptions = 0

    from kubedl_tpu.core.apiserver import NotFound

    def drop_gang(job):
        for name in pg_names[job]:
            try:
                api.delete("PodGroup", "default", name)
            except NotFound:
                pass

    while len(finished) < len(trace):
        # next simulation event
        nxt = []
        if pending_arrivals:
            nxt.append(pending_arrivals[0][0])
        if completions:
            nxt.append(completions[0][0])
        if not nxt:
            raise RuntimeError(
                "scheduler run wedged: no events but "
                f"{len(trace) - len(finished)} job(s) unfinished")
        sim_t = min(nxt)
        clock.advance_to(sim_t)
        while pending_arrivals and pending_arrivals[0][0] <= sim_t:
            _, job, queue, pool, slices, _dur = pending_arrivals.pop(0)
            pg_names[job] = make_pgs(api, job, queue, pool, slices)
        while completions and completions[0][0] <= sim_t:
            _, job, token = heapq.heappop(completions)
            if job in finished or job not in admitted \
                    or records.get(job, (None,))[0] != token:
                continue  # stale entry from a run that was preempted
            drop_gang(job)
            finished.add(job)
        manager.run_until_idle(max_iterations=1_000_000)
        # reclaim victims (podless gangs get their PodGroups deleted):
        # the job re-enters its queue exactly like the engine's
        # readmit_slice path recreates a job's gangs from scratch
        for job in sorted(admitted - finished):
            if any(not is_gang_admitted(pg) if (pg := api.try_get(
                    "PodGroup", "default", n)) is not None else True
                    for n in pg_names[job]):
                admitted.discard(job)
                records.pop(job, None)
                drop_gang(job)
                _, _, queue, pool, slices, _dur = meta[job]
                pg_names[job] = make_pgs(api, job, queue, pool, slices)
                preemptions += 1
        manager.run_until_idle(max_iterations=1_000_000)
        # collect fresh admissions (a gang runs once fully admitted)
        for pg in api.list("PodGroup"):
            job = m.get_labels(pg).get(c.LABEL_GANG_JOB_NAME, m.name(pg))
            if job in admitted or job in finished:
                continue
            if all((g := api.try_get("PodGroup", "default", n)) is not None
                   and is_gang_admitted(g) for n in pg_names[job]):
                admitted.add(job)
                _, _, _, _, slices, dur = meta[job]
                records[job] = (sim_t, sim_t + dur, slices, dur)
                heapq.heappush(completions, (sim_t + dur, job, sim_t))
    out = _stats(records, CAPACITY, arrivals)
    out["scheduling_passes"] = sched.passes
    out["preemptions"] = preemptions
    out["backfills"] = sum(
        sched.metrics.backfills.value(queue=q["name"]) for q in QUEUES)
    return out


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="BENCH_SCHEDULER.json")
    args = ap.parse_args()

    trace = build_trace()
    t0 = time.perf_counter()
    fcfs = run_fcfs(trace)
    sched = run_scheduler(trace)
    wall = time.perf_counter() - t0

    ratio = round(sched["slice_utilization"]
                  / max(fcfs["slice_utilization"], 1e-9), 2)
    result = {
        "benchmark": "slice_scheduler_trace",
        "capacity_slices": CAPACITY,
        "queues": [q["name"] for q in QUEUES],
        "trace_jobs": len(trace),
        "fcfs": fcfs,
        "scheduler": sched,
        "utilization_ratio": ratio,
        "makespan_ratio": round(fcfs["makespan_s"]
                                / max(sched["makespan_s"], 1e-9), 2),
        "bench_wall_seconds": round(wall, 2),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        # the acceptance gate: >=1.3x utilization at no worse makespan
        "gate_utilization_min": 1.3,
        "gate_passed": bool(ratio >= 1.3
                            and sched["makespan_s"]
                            <= fcfs["makespan_s"] + 1e-6),
    }
    print(json.dumps(result))
    if not result["gate_passed"]:
        raise SystemExit(
            f"GATE FAILED: utilization ratio {ratio} (need >= 1.3) or "
            f"makespan regressed ({sched['makespan_s']} vs "
            f"{fcfs['makespan_s']})")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    return result


if __name__ == "__main__":
    main()
