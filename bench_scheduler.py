"""Slice-scheduler policy benchmark: deterministic synthetic traces,
three admission policies, one JSON line.

``bench_controlplane.py`` measures how fast the operator settles jobs;
this one measures how well the *scheduler* uses finite slice inventory.
A fixed trace of mixed single-/multislice gangs across 3 tenant queues is
replayed twice on identical capacity:

* **fcfs** — the pre-scheduler world: one global FIFO, no quota, no
  backfill; a gang that does not fit blocks everything behind it (which
  is what "whoever the kube-scheduler binds first" degenerates to under
  contention, with head-of-line blocking across unrelated pools);
* **scheduler** — the real ``SliceScheduler`` driven over the in-memory
  API server with a simulated clock: per-queue FIFO, elastic quota,
  priority ordering, and reservation backfill.

Both runs report makespan, slice utilization (busy slice-seconds over
capacity x makespan), and p50/p99 queueing delay. Gate (the ISSUE 4
acceptance): scheduler utilization >= 1.3x FCFS at no worse makespan.

A second, **heterogeneous** trace (ISSUE 9) replays a mixed fleet —
per-(kind, pool) tokens/s spread >= 2x, a premium on-demand v5p pool vs
a cheap spot v5e pool, multi-slice gangs, and a scripted mid-day spot
outage — twice through the same scheduler: once unscored (jobs pinned
to their routed pool) and once with ``--enable-placement-scoring``
semantics (pool-eligibility sets + the throughput/contention/cost
score, seeded from measured rates). Job durations are honest:
``tokens / rate(kind, chosen pool)``, so a bad placement costs real
simulated time. Gate: scored placement >= 1.25x aggregate normalized
throughput at no worse makespan, with >= 90% of multi-slice gangs
ICI-domain-packed.

The JSON also self-checks against the committed artifact at ``--out``
(per-metric tolerances, exactly like the cluster scorecard) and exits
non-zero on regression.

Everything is seeded / literal — no wall clock, no RNG — so the JSON is
reproducible bit-for-bit (the ``timestamp``/wall fields aside).

Usage::

    python bench_scheduler.py [--out BENCH_SCHEDULER.json] [--no-check]
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import sys
import time

from kubedl_tpu.api import common as c
from kubedl_tpu.api.queue import new_queue
from kubedl_tpu.core import meta as m
from kubedl_tpu.core.apiserver import APIServer
from kubedl_tpu.core.clock import SimClock
from kubedl_tpu.core.manager import Manager
from kubedl_tpu.metrics.registry import SchedulerMetrics
from kubedl_tpu.scheduling.gang import is_gang_admitted
from kubedl_tpu.scheduling.inventory import SliceInventory
from kubedl_tpu.scheduling.scheduler import SliceScheduler
from kubedl_tpu.utils.stats import percentile

POOL_A = "tpu-v5p-slice/2x2x4"        # 3D torus training pool
POOL_B = "tpu-v5-lite-podslice/4x4"   # 2D inference/finetune pool
CAPACITY = {POOL_A: 8, POOL_B: 8}

QUEUES = (
    {"name": "prod", "min": 4, "max": None, "priority": 100},
    {"name": "batch", "min": 2, "max": None, "priority": 10},
    {"name": "best", "min": 0, "max": None, "priority": 0},
)


def build_trace() -> list:
    """(arrival_s, job, queue, pool, slices, duration_s) — deterministic.

    Two long multislice pool-A jobs saturate pool A immediately; 64 short
    single-slice pool-B jobs arrive right behind them. FCFS blocks every
    pool-B job behind the second pool-A gang for its whole wait; the
    scheduler lets pool B run concurrently (per-queue FIFO + backfill)."""
    trace = [
        (0.0, "batch-warm", "batch", POOL_A, 8, 300.0),
        (1.0, "batch-big", "batch", POOL_A, 6, 300.0),
    ]
    # first wave (t=2) lands in batch, BEHIND the blocked batch-big head:
    # those admissions are true backfills (different pool, cannot delay it)
    queues = ("batch", "prod", "best", "prod")
    for i in range(64):
        trace.append((2.0 + (i % 8), f"ft-{i:03d}", queues[i % 4],
                      POOL_B, 1, 100.0))
    # a late second wave of pool-A work keeps pool A busy after the warm
    # job drains (both policies run it; it anchors the pool-A critical path)
    trace.append((320.0, "batch-tail", "batch", POOL_A, 4, 200.0))
    return sorted(trace, key=lambda t: (t[0], t[1]))


def _stats(records: dict, capacity: dict, arrivals: dict) -> dict:
    """makespan / utilization / queue-delay percentiles from
    job -> (admit_t, end_t, slices, duration)."""
    t0 = min(arrivals.values())
    end = max(r[1] for r in records.values())
    makespan = end - t0
    busy = sum(r[2] * r[3] for r in records.values())
    total = sum(capacity.values())
    delays = [r[0] - arrivals[j] for j, r in records.items()]

    return {
        "makespan_s": round(makespan, 1),
        "slice_utilization": round(busy / (total * makespan), 4),
        "queue_delay_p50_s": round(percentile(delays, 0.50), 1),
        "queue_delay_p99_s": round(percentile(delays, 0.99), 1),
        "jobs": len(records),
    }


# ---------------------------------------------------------------------------
# baseline: global FIFO, no quota, head-of-line blocking
# ---------------------------------------------------------------------------


def run_fcfs(trace: list) -> dict:
    free = dict(CAPACITY)
    waiting = list(trace)  # already arrival-sorted: THE global FIFO
    completions: list = []  # (end_t, job, pool, slices)
    records, arrivals = {}, {t[1]: t[0] for t in trace}
    t = 0.0
    while waiting or completions:
        # admit strictly from the head; the first non-fitting gang blocks
        while waiting:
            arr, job, _q, pool, slices, dur = waiting[0]
            if arr > t or free[pool] < slices:
                break
            waiting.pop(0)
            free[pool] -= slices
            records[job] = (t, t + dur, slices, dur)
            heapq.heappush(completions, (t + dur, job, pool, slices))
        # advance to the next event: an arrival or a completion
        nxt = []
        if waiting and waiting[0][0] > t:
            nxt.append(waiting[0][0])
        if completions:
            nxt.append(completions[0][0])
        if not nxt:
            if waiting:  # head blocked with no completion coming: stuck
                raise RuntimeError("FCFS wedged (trace exceeds capacity)")
            break
        t = min(nxt)
        while completions and completions[0][0] <= t:
            _, _job, pool, slices = heapq.heappop(completions)
            free[pool] += slices
    return _stats(records, CAPACITY, arrivals)


# ---------------------------------------------------------------------------
# the real scheduler over the in-memory control plane
# ---------------------------------------------------------------------------


def make_pgs(api, job, queue, pool, slices, priority=0):
    names = []
    for sid in range(slices):
        name = job if slices == 1 else f"{job}-slice-{sid}"
        pg = m.new_obj("scheduling.sigs.k8s.io/v1alpha1", "PodGroup", name,
                       labels={c.LABEL_GANG_JOB_NAME: job},
                       annotations={
                           c.ANNOTATION_SCHED_POOL: pool,
                           c.ANNOTATION_SCHED_QUEUE: queue,
                           c.ANNOTATION_SCHED_NUM_SLICES: str(slices),
                           c.ANNOTATION_SCHED_PRIORITY: str(priority),
                       })
        pg["spec"] = {"minMember": 1}
        api.create(pg)
        names.append(name)
    return names


def run_scheduler(trace: list) -> dict:
    clock = SimClock()
    api = APIServer(clock=clock)
    manager = Manager(api, clock=clock)
    sched = SliceScheduler(
        api, inventory=SliceInventory(api, static_capacity=CAPACITY),
        metrics=SchedulerMetrics())
    manager.register(sched)
    for q in QUEUES:
        api.create(new_queue(**q))

    arrivals = {t[1]: t[0] for t in trace}
    meta = {t[1]: t for t in trace}
    pg_names: dict[str, list] = {}
    pending_arrivals = list(trace)
    completions: list = []  # (sim_end_t, job, admit_t token)
    records: dict[str, tuple] = {}
    admitted: set = set()
    finished: set = set()
    preemptions = 0

    from kubedl_tpu.core.apiserver import NotFound

    def drop_gang(job):
        for name in pg_names[job]:
            try:
                api.delete("PodGroup", "default", name)
            except NotFound:
                pass

    while len(finished) < len(trace):
        # next simulation event
        nxt = []
        if pending_arrivals:
            nxt.append(pending_arrivals[0][0])
        if completions:
            nxt.append(completions[0][0])
        if not nxt:
            raise RuntimeError(
                "scheduler run wedged: no events but "
                f"{len(trace) - len(finished)} job(s) unfinished")
        sim_t = min(nxt)
        clock.advance_to(sim_t)
        while pending_arrivals and pending_arrivals[0][0] <= sim_t:
            _, job, queue, pool, slices, _dur = pending_arrivals.pop(0)
            pg_names[job] = make_pgs(api, job, queue, pool, slices)
        while completions and completions[0][0] <= sim_t:
            _, job, token = heapq.heappop(completions)
            if job in finished or job not in admitted \
                    or records.get(job, (None,))[0] != token:
                continue  # stale entry from a run that was preempted
            drop_gang(job)
            finished.add(job)
        manager.run_until_idle(max_iterations=1_000_000)
        # reclaim victims (podless gangs get their PodGroups deleted):
        # the job re-enters its queue exactly like the engine's
        # readmit_slice path recreates a job's gangs from scratch
        for job in sorted(admitted - finished):
            if any(not is_gang_admitted(pg) if (pg := api.try_get(
                    "PodGroup", "default", n)) is not None else True
                    for n in pg_names[job]):
                admitted.discard(job)
                records.pop(job, None)
                drop_gang(job)
                _, _, queue, pool, slices, _dur = meta[job]
                pg_names[job] = make_pgs(api, job, queue, pool, slices)
                preemptions += 1
        manager.run_until_idle(max_iterations=1_000_000)
        # collect fresh admissions (a gang runs once fully admitted)
        for pg in api.list("PodGroup"):
            job = m.get_labels(pg).get(c.LABEL_GANG_JOB_NAME, m.name(pg))
            if job in admitted or job in finished:
                continue
            if all((g := api.try_get("PodGroup", "default", n)) is not None
                   and is_gang_admitted(g) for n in pg_names[job]):
                admitted.add(job)
                _, _, _, _, slices, dur = meta[job]
                records[job] = (sim_t, sim_t + dur, slices, dur)
                heapq.heappush(completions, (sim_t + dur, job, sim_t))
    out = _stats(records, CAPACITY, arrivals)
    out["scheduling_passes"] = sched.passes
    out["preemptions"] = preemptions
    out["backfills"] = sum(
        sched.metrics.backfills.value(queue=q["name"]) for q in QUEUES)
    return out


# ---------------------------------------------------------------------------
# the heterogeneous placement leg (ISSUE 9): unscored vs scored placement
# ---------------------------------------------------------------------------

PLACEMENT_CAPACITY = {POOL_A: 8, POOL_B: 8}
#: measured tokens/s per slice, per (kind, pool) — the BENCH_r0*-style
#: seed the ThroughputProfileStore is primed with (>= 2x spread for
#: train, near-parity for the others so cost decides them)
PLACEMENT_RATES = {
    "train":    {POOL_A: 4000.0, POOL_B: 800.0},
    "finetune": {POOL_A: 1500.0, POOL_B: 1400.0},
    "serve":    {POOL_A: 1000.0, POOL_B: 1100.0},
}
#: $/chip-hour: premium on-demand v5p vs cheap spot v5e
PLACEMENT_COSTS = {POOL_A: (3.0, False), POOL_B: (1.0, True)}
#: the scripted spot outage: every admitted POOL_B gang is evicted at
#: t=OUT and the pool stays dry until t=BACK (evictions ride the same
#: delete-and-readmit path scheduler preemptions use)
SPOT_OUTAGE = (700.0, 1500.0)


def build_placement_trace() -> list:
    """(arrival_s, job, kind, primary_pool, slices, tokens) —
    deterministic. The primary pool is the legacy routing (whatever pool
    the job kind historically ran on): heavy train jobs land on the
    cheap-but-5x-slower spot pool, light finetune/serve jobs hog the
    premium pool — exactly the misrouting throughput-aware scoring is
    supposed to fix."""
    trace = []
    for i in range(10):
        # big multislice pretrain gangs, legacy-routed to the SLOW pool
        trace.append((10.0 * i, f"tr-{i:02d}", "train", POOL_B, 2,
                      1_200_000.0))
    for i in range(16):
        # light finetunes, legacy-routed to the premium pool
        trace.append((5.0 + 10.0 * i, f"ft-{i:02d}", "finetune", POOL_A,
                      1, 450_000.0))
    for i in range(12):
        # serving bake-offs: near-parity throughput, cost should decide
        trace.append((8.0 + 15.0 * i, f"sv-{i:02d}", "serve", POOL_A, 1,
                      300_000.0))
    return sorted(trace, key=lambda t: (t[0], t[1]))


def _placement_pgs(api, job, kind, pool, slices):
    names = []
    for sid in range(slices):
        name = job if slices == 1 else f"{job}-slice-{sid}"
        pg = m.new_obj("scheduling.sigs.k8s.io/v1alpha1", "PodGroup", name,
                       labels={c.LABEL_GANG_JOB_NAME: job},
                       annotations={
                           c.ANNOTATION_SCHED_POOL: pool,
                           c.ANNOTATION_SCHED_QUEUE: "default",
                           c.ANNOTATION_SCHED_NUM_SLICES: str(slices),
                           c.ANNOTATION_SCHED_PRIORITY: "0",
                           c.ANNOTATION_SCHED_POOLS:
                               f"{POOL_A},{POOL_B}",
                           c.ANNOTATION_SCHED_PROFILE: kind,
                       })
        pg["spec"] = {"minMember": 1}
        api.create(pg)
        names.append(name)
    return names


def run_placement(trace: list, scored: bool) -> dict:
    """Replay the heterogeneous trace through the real scheduler; with
    ``scored`` the scheduler carries a PlacementScorer primed from
    PLACEMENT_RATES (the measured-seed path), without it jobs stay on
    their routed primary pool. Durations are tokens / rate(kind, chosen
    pool); a spot outage mid-day evicts every POOL_B gang."""
    from kubedl_tpu.core.apiserver import NotFound
    from kubedl_tpu.scheduling.inventory import PoolEconomics
    from kubedl_tpu.scheduling.scoring import PlacementScorer
    from kubedl_tpu.telemetry.profiles import ThroughputProfileStore

    clock = SimClock()
    api = APIServer(clock=clock)
    manager = Manager(api, clock=clock)
    inv = SliceInventory(
        api, static_capacity=dict(PLACEMENT_CAPACITY),
        economics={p: PoolEconomics(cost, spot=spot)
                   for p, (cost, spot) in PLACEMENT_COSTS.items()})
    scorer = None
    if scored:
        store = ThroughputProfileStore(clock=clock)
        for kind, rates in sorted(PLACEMENT_RATES.items()):
            for pool, rate in sorted(rates.items()):
                store.observe_rate(kind, pool, rate)
        scorer = PlacementScorer(inv, profiles=store)
    sched = SliceScheduler(api, inventory=inv,
                           metrics=SchedulerMetrics(), scorer=scorer)
    manager.register(sched)

    meta = {t[1]: t for t in trace}
    pg_names: dict[str, list] = {}
    tokens_left = {t[1]: t[5] for t in trace}
    admit_info: dict[str, tuple] = {}    # job -> (admit_t, rate, pool)
    pending_arrivals = list(trace)
    completions: list = []               # (end_t, job, admit_t token)
    finished: set = set()
    records: dict[str, tuple] = {}       # job -> (first_admit_t, end_t)
    arrivals = {t[1]: t[0] for t in trace}
    ms_observed = ms_packed = 0
    spot_evictions = 0
    outage_events = [(SPOT_OUTAGE[0], "out"), (SPOT_OUTAGE[1], "back")]
    cost_dollars = 0.0
    norm_weighted = norm_weight = 0.0

    def drop_gang(job):
        for name in pg_names[job]:
            try:
                api.delete("PodGroup", "default", name)
            except NotFound:
                pass

    def settle(job, now):
        """Bank a running job's progress up to ``now`` and clear it.
        Normalized-throughput weights accrue here over the seconds the
        job ACTUALLY ran on its pool — weighting planned durations at
        admission would double-count the never-run tail of every
        evicted gang, and differently per leg."""
        nonlocal cost_dollars, norm_weighted, norm_weight
        t_adm, rate, pool = admit_info.pop(job)
        ran = max(now - t_adm, 0.0)
        tokens_left[job] = max(tokens_left[job] - rate * ran, 0.0)
        _, _, kind, _pp, slices, _tok = meta[job]
        cost, _spot = PLACEMENT_COSTS[pool]
        cost_dollars += slices * 16 * cost * ran / 3600.0
        best = max(PLACEMENT_RATES[kind].values())
        norm_weighted += (rate / best) * slices * ran
        norm_weight += slices * ran

    while len(finished) < len(trace):
        nxt = []
        if pending_arrivals:
            nxt.append(pending_arrivals[0][0])
        if completions:
            nxt.append(completions[0][0])
        if outage_events:
            nxt.append(outage_events[0][0])
        if not nxt:
            raise RuntimeError("placement leg wedged")
        sim_t = min(nxt)
        clock.advance_to(sim_t)
        while pending_arrivals and pending_arrivals[0][0] <= sim_t:
            _, job, kind, pool, slices, _tok = pending_arrivals.pop(0)
            pg_names[job] = _placement_pgs(api, job, kind, pool, slices)
        while completions and completions[0][0] <= sim_t:
            _, job, token = heapq.heappop(completions)
            if job in finished or admit_info.get(job, (None,))[0] != token:
                continue                 # stale (evicted meanwhile)
            settle(job, sim_t)
            records[job] = (records[job][0], sim_t)
            drop_gang(job)
            finished.add(job)
        while outage_events and outage_events[0][0] <= sim_t:
            _, what = outage_events.pop(0)
            if what == "out":
                inv.static_capacity[POOL_B] = 0
                for job in sorted(admit_info):
                    if admit_info[job][2] == POOL_B:
                        settle(job, sim_t)
                        drop_gang(job)
                        spot_evictions += 1
                        _, _, kind, pool, slices, _tok = meta[job]
                        pg_names[job] = _placement_pgs(
                            api, job, kind, pool, slices)
            else:
                inv.static_capacity[POOL_B] = PLACEMENT_CAPACITY[POOL_B]
            sched.schedule_pass()
        manager.run_until_idle(max_iterations=1_000_000)
        # collect fresh admissions; duration derives from the CHOSEN pool
        for job in sorted(pg_names):
            if job in finished or job in admit_info:
                continue
            pgs = [api.try_get("PodGroup", "default", n)
                   for n in pg_names[job]]
            if not all(p is not None and is_gang_admitted(p)
                       for p in pgs):
                continue
            pool = m.get_annotations(pgs[0])[c.ANNOTATION_SCHED_POOL]
            _, _, kind, _pp, slices, _tok = meta[job]
            rate = PLACEMENT_RATES[kind][pool]
            dur = tokens_left[job] / rate
            admit_info[job] = (sim_t, rate, pool)
            records.setdefault(job, (sim_t, sim_t))
            heapq.heappush(completions, (sim_t + dur, job, sim_t))
            if slices > 1:
                spans = inv.gang_domains("default", job, pool)
                if spans is not None:
                    ms_observed += 1
                    ms_packed += 1 if spans <= 1 else 0

    makespan = max(r[1] for r in records.values()) - min(
        arrivals.values())
    total_tokens = sum(t[5] for t in trace)
    out = {
        "jobs": len(trace),
        "makespan_s": round(makespan, 1),
        "tokens_per_s": round(total_tokens / makespan, 1),
        "normalized_throughput": round(
            norm_weighted / norm_weight, 4) if norm_weight else 0.0,
        "ici_packed_fraction": round(ms_packed / ms_observed, 4)
        if ms_observed else 1.0,
        "multi_slice_gangs": ms_observed,
        "spot_evictions": spot_evictions,
        "spot_evictions_survived": spot_evictions,  # all jobs complete
        "cost_dollars": round(cost_dollars, 2),
        "scheduling_passes": sched.passes,
    }
    if scored:
        out["scored_placements"] = sum(
            sched.metrics.scored_placements.value(pool=p)
            for p in PLACEMENT_CAPACITY)
    return out


# ---------------------------------------------------------------------------
# regression check vs the committed artifact (satellite of ISSUE 9 —
# the scheduler bench gets the same teeth the cluster scorecard has)
# ---------------------------------------------------------------------------

#: (path, direction, relative slack, absolute grace)
REGRESSION_RULES = (
    ("utilization_ratio", "higher_better", 0.03, 0.02),
    ("scheduler.slice_utilization", "higher_better", 0.03, 0.01),
    ("scheduler.makespan_s", "lower_better", 0.05, 5.0),
    ("scheduler.queue_delay_p50_s", "lower_better", 0.10, 5.0),
    ("scheduler.scheduling_passes", "lower_better", 0.20, 20.0),
    ("placement.throughput_ratio", "higher_better", 0.03, 0.02),
    ("placement.normalized_throughput_ratio", "higher_better",
     0.03, 0.02),
    ("placement.scored.ici_packed_fraction", "higher_better",
     0.03, 0.02),
    ("placement.scored.cost_dollars", "lower_better", 0.10, 5.0),
)


def check_regression(new: dict, old: dict) -> list:
    """Per-metric tolerance comparison against the committed
    BENCH_SCHEDULER.json — the cluster scorecard's shared tolerance
    engine with this bench's rule table. Metrics absent from either
    side are skipped, so a first run against an older artifact only
    checks what both know."""
    from kubedl_tpu.replay.scorecard import check_tolerances
    return check_tolerances(new, old, REGRESSION_RULES)


def main() -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="BENCH_SCHEDULER.json")
    ap.add_argument("--no-check", action="store_true",
                    help="skip the regression check against the "
                         "committed artifact at --out")
    args = ap.parse_args()

    trace = build_trace()
    t0 = time.perf_counter()
    fcfs = run_fcfs(trace)
    sched = run_scheduler(trace)

    # the heterogeneous placement leg: same scheduler, unscored vs scored
    ptrace = build_placement_trace()
    unscored = run_placement(ptrace, scored=False)
    scored = run_placement(ptrace, scored=True)
    wall = time.perf_counter() - t0

    ratio = round(sched["slice_utilization"]
                  / max(fcfs["slice_utilization"], 1e-9), 2)
    tokens_ratio = round(scored["tokens_per_s"]
                         / max(unscored["tokens_per_s"], 1e-9), 2)
    norm_ratio = round(scored["normalized_throughput"]
                       / max(unscored["normalized_throughput"], 1e-9), 2)
    placement_gate = bool(
        norm_ratio >= 1.25
        and scored["makespan_s"] <= unscored["makespan_s"] + 1e-6
        and scored["ici_packed_fraction"] >= 0.9)
    result = {
        "benchmark": "slice_scheduler_trace",
        "capacity_slices": CAPACITY,
        "queues": [q["name"] for q in QUEUES],
        "trace_jobs": len(trace),
        "fcfs": fcfs,
        "scheduler": sched,
        "utilization_ratio": ratio,
        "makespan_ratio": round(fcfs["makespan_s"]
                                / max(sched["makespan_s"], 1e-9), 2),
        "placement": {
            "capacity_slices": PLACEMENT_CAPACITY,
            "rates_tokens_per_s": PLACEMENT_RATES,
            "cost_per_chip_hour": {p: c for p, (c, _s)
                                   in PLACEMENT_COSTS.items()},
            "spot_pools": [p for p, (_c, s)
                           in PLACEMENT_COSTS.items() if s],
            "spot_outage_s": list(SPOT_OUTAGE),
            "trace_jobs": len(ptrace),
            "unscored": unscored,
            "scored": scored,
            "throughput_ratio": tokens_ratio,
            "normalized_throughput_ratio": norm_ratio,
            "gate_normalized_min": 1.25,
            "gate_packed_min": 0.9,
            "gate_passed": placement_gate,
        },
        "bench_wall_seconds": round(wall, 2),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        # the acceptance gate: >=1.3x utilization at no worse makespan
        "gate_utilization_min": 1.3,
        "gate_passed": bool(ratio >= 1.3
                            and sched["makespan_s"]
                            <= fcfs["makespan_s"] + 1e-6),
    }
    print(json.dumps(result))
    if not result["gate_passed"]:
        raise SystemExit(
            f"GATE FAILED: utilization ratio {ratio} (need >= 1.3) or "
            f"makespan regressed ({sched['makespan_s']} vs "
            f"{fcfs['makespan_s']})")
    if not placement_gate:
        raise SystemExit(
            f"PLACEMENT GATE FAILED: normalized-throughput ratio "
            f"{norm_ratio} (need >= 1.25) at makespan "
            f"{scored['makespan_s']} vs {unscored['makespan_s']}, "
            f"packed fraction {scored['ici_packed_fraction']} "
            f"(need >= 0.9)")
    if not args.no_check and args.out and os.path.exists(args.out):
        try:
            with open(args.out) as f:
                committed = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: cannot read committed {args.out}: {e}",
                  file=sys.stderr)
            committed = {}
        problems = check_regression(result, committed)
        if problems:
            # keep the committed baseline intact on regression
            raise SystemExit("REGRESSION vs committed scheduler bench:"
                             "\n  " + "\n  ".join(problems))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    return result


if __name__ == "__main__":
    main()
