"""Data pipelines: synthetic LM batches + sharded host loading.

The operator-side dataset story (CacheBackend CRD → host-disk cache) mounts
data into the container; this module is the in-container loader. For
benchmarks and CI the synthetic stream generates deterministic token
batches; ``shard_batch`` places a host-local batch onto the mesh with the
canonical (dp×fsdp, cp) sharding.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib


def synthetic_lm_batches(batch_size: int, seq_len: int, vocab_size: int,
                         seed: int = 0) -> Iterator[dict]:
    """Deterministic stream of {tokens, targets} next-token batches."""
    rng = np.random.default_rng(seed)
    while True:
        toks = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                            dtype=np.int32)
        yield {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Rank-aware batch sharding: the leading axis shards over the data
    axes, a rank-2 [b, s] leaf additionally shards its sequence axis over
    cp (ring attention), and higher-rank leaves (images) shard the batch
    axis only."""
    full = mesh_lib.batch_spec()  # P((dp, fsdp), cp)

    def put(x):
        x = jnp.asarray(x)
        if x.ndim == 0:
            s = P()
        elif x.ndim == 2 and jnp.issubdtype(x.dtype, jnp.integer):
            # integer [b, s] = token ids/targets/segments: sequence axis
            # shards over cp. Float rank-2 leaves (feature matrices) only
            # shard the batch axis — cp is a sequence axis, and a feature
            # dim need not divide it.
            s = full
        else:
            s = P(full[0], *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(put, batch)


def sharded_synthetic_stream(batch_size: int, seq_len: int, vocab_size: int,
                             mesh: Mesh, seed: int = 0) -> Iterator[dict]:
    for batch in synthetic_lm_batches(batch_size, seq_len, vocab_size, seed):
        yield shard_batch(batch, mesh)


def prefetch_to_device(batches: Iterator[dict], mesh: Optional[Mesh] = None,
                       size: int = 2) -> Iterator[dict]:
    """Keep ``size`` device batches in flight ahead of the consumer.

    ``jax.device_put`` is asynchronous: issuing the transfer for batch
    N+1 while the step for batch N is still executing hides the
    host→device copy behind compute — the standard TPU input-pipeline
    overlap (without it, every step starts with a synchronous HBM fill).
    With ``mesh`` each host batch is sharded on the way in; without it
    the stream is assumed pre-sharded and only the lookahead window is
    added. Host memory holds at most ``size`` extra batches."""
    import collections

    put = (lambda b: shard_batch(b, mesh)) if mesh is not None \
        else (lambda b: b)
    size = max(size, 1)  # size<=0 would silently drop the whole stream
    queue = collections.deque()
    try:
        for _ in range(size):
            queue.append(put(next(batches)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(batches)))
        except StopIteration:
            pass
        yield out


class TokenFileDataset:
    """Pre-tokenized corpus on disk: a flat int32 (or int16/uint16) token
    array, memory-mapped — the layout GCS-FUSE/persistent-disk dataset
    caches serve (CacheBackend CRD mounts it; this reads it).

    Each host reads only its own contiguous shard of the file
    (``process_index``/``process_count``), so a multi-host job streams
    disjoint data with zero coordination.
    """

    def __init__(self, path: str, seq_len: int, batch_size: int,
                 dtype=np.int32, process_index: int = 0,
                 process_count: int = 1, seed: int = 0):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.batch_size = batch_size
        n = len(self.tokens) // (seq_len + 1)
        lo = n * process_index // process_count
        hi = n * (process_index + 1) // process_count
        if hi - lo < batch_size:
            # an undersized shard would make batches() spin forever
            # yielding nothing — fail loudly at construction instead
            raise ValueError(
                f"token file too small: {n} sequences across "
                f"{process_count} hosts leaves host {process_index} with "
                f"{hi - lo} (< batch_size {batch_size})")
        self._indices = np.arange(lo, hi)
        self._rng = np.random.default_rng(seed + process_index)

    def __len__(self) -> int:
        return len(self._indices)

    def batches(self) -> Iterator[dict]:
        """Infinite shuffled stream of {tokens, targets} (epoch reshuffle)."""
        sl = self.seq_len
        while True:
            order = self._rng.permutation(self._indices)
            for start in range(0, len(order) - self.batch_size + 1,
                               self.batch_size):
                rows = [self.tokens[i * (sl + 1):(i + 1) * (sl + 1)]
                        for i in order[start:start + self.batch_size]]
                block = np.asarray(rows, dtype=np.int32)  # single host copy
                yield {"tokens": block[:, :-1], "targets": block[:, 1:]}
