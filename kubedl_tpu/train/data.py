"""Data pipelines: synthetic LM batches + sharded host loading.

The operator-side dataset story (CacheBackend CRD → host-disk cache) mounts
data into the container; this module is the in-container loader. For
benchmarks and CI the synthetic stream generates deterministic token
batches; ``shard_batch`` places a host-local batch onto the mesh with the
canonical (dp×fsdp, cp) sharding.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib


def synthetic_lm_batches(batch_size: int, seq_len: int, vocab_size: int,
                         seed: int = 0, skip: int = 0) -> Iterator[dict]:
    """Deterministic stream of {tokens, targets} next-token batches.
    ``skip`` fast-forwards the stream by that many batches (resume): the
    rng advances through identical draws, so batch ``skip`` here is
    bit-identical to batch ``skip`` of an unskipped stream."""
    rng = np.random.default_rng(seed)
    for _ in range(skip):
        rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                     dtype=np.int32)
    while True:
        toks = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                            dtype=np.int32)
        yield {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


class CountingIterator:
    """Wraps a batch iterator and counts consumed batches — the host-side
    data cursor the checkpoint layer persists (VERDICT r4 next #1: a
    resumed run must not replay the corpus head). ``consumed`` starts at
    the skip offset the underlying stream was fast-forwarded by, so it is
    always the absolute position in the logical stream."""

    def __init__(self, it: Iterator[dict], consumed: int = 0):
        self._it = iter(it)
        self.consumed = consumed

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        batch = next(self._it)
        self.consumed += 1
        return batch


def skip_batches(stream: Iterator[dict], n: int) -> Iterator[dict]:
    """Generic fast-forward: draw and discard ``n`` batches. Host-side
    numpy work only (used for streams with no cheaper skip path — packed
    text); datasets with index-level skip implement their own."""
    for _ in range(n):
        next(stream)
    return stream


def skip_epochs(skip: int, per_epoch: int, draw_epoch) -> int:
    """Resume fast path shared by the epoch-shuffled datasets: burn every
    whole skipped epoch by replaying the SAME rng draw an unskipped
    stream made (``draw_epoch``), returning the remaining within-epoch
    offset in batches. Keeps the batches-per-epoch invariant in one
    place — the callers' epoch loops must yield exactly ``per_epoch``
    batches per permutation."""
    while skip >= per_epoch:
        draw_epoch()
        skip -= per_epoch
    return skip


def pack_documents(docs, seq_len: int, batch_size: int,
                   pad_id: int = 0) -> Iterator[dict]:
    """Greedy first-fit packing of variable-length token documents into
    fixed [batch, seq_len] training batches — the standard fine-tuning
    data shape for the flash kernels' segment-ids path.

    Yields {tokens, targets, segment_ids, positions, mask}:

    * documents are packed back to back per row; a doc longer than
      ``seq_len + 1`` is split into chunks (each chunk its own segment);
    * ``segment_ids`` are unique per document within a row (pads get -1),
      so attention never crosses documents;
    * ``positions`` restart at 0 per document — RoPE sees every doc at
      its natural offsets, exactly as if it were alone in the batch;
    * ``mask`` zeroes loss terms whose (input, target) pair crosses a
      document boundary or touches padding.

    Leftover documents that don't fill a final batch are dropped (the
    streaming contract: every yielded batch is full).

    A finite ``docs`` list routes through the C++ packer
    (``kubedl_tpu.native``, bit-identical output pinned by
    tests/test_native.py) — packing is per-step host byte shuffling,
    exactly what starves a TPU input pipeline in Python at scale.
    Generators/streams and environments without the native lib use the
    pure-Python path below."""
    if isinstance(docs, (list, tuple)) and \
            all(hasattr(d, "__len__") for d in docs):
        # lists of generators keep the Python path (it list()s each doc)
        from .. import native
        packed = native.pack_rows_native(docs, seq_len, pad_id)
        if packed is not None:
            toks, segs, pos = packed
            for i in range(0, len(toks) - batch_size + 1, batch_size):
                yield _packed_arrays(toks[i:i + batch_size],
                                     segs[i:i + batch_size],
                                     pos[i:i + batch_size])
            return
    seq1 = seq_len + 1     # pack seq_len+1 then shift for (tokens, targets)
    rows, row, seg_row, pos_row, seg_id = [], [], [], [], 0

    def flush_row():
        nonlocal row, seg_row, pos_row, seg_id
        pad = seq1 - len(row)
        rows.append((row + [pad_id] * pad,
                     seg_row + [-1] * pad,
                     pos_row + [0] * pad))
        row, seg_row, pos_row, seg_id = [], [], [], 0

    for doc in docs:
        doc = list(doc)
        for start in range(0, len(doc), seq1):
            chunk = doc[start:start + seq1]
            if len(chunk) < 2:
                continue           # a 1-token chunk has no (input, target)
            if len(row) + len(chunk) > seq1:
                flush_row()
            row.extend(chunk)
            seg_row.extend([seg_id] * len(chunk))
            pos_row.extend(range(len(chunk)))
            seg_id += 1
            if len(row) == seq1:
                flush_row()
            while len(rows) >= batch_size:
                batch, rows = rows[:batch_size], rows[batch_size:]
                yield _packed_batch(batch)
    if row:
        flush_row()
    while len(rows) >= batch_size:
        batch, rows = rows[:batch_size], rows[batch_size:]
        yield _packed_batch(batch)


def _packed_batch(rows) -> dict:
    return _packed_arrays(np.asarray([r[0] for r in rows], np.int32),
                          np.asarray([r[1] for r in rows], np.int32),
                          np.asarray([r[2] for r in rows], np.int32))


def _packed_arrays(toks, seg, pos) -> dict:
    # toks/seg/pos: [b, seq+1] int32
    mask = (seg[:, :-1] == seg[:, 1:]) & (seg[:, :-1] >= 0)
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:],
            "segment_ids": seg[:, :-1], "positions": pos[:, :-1],
            "mask": mask}


def sft_batches(examples, seq_len: int, batch_size: int,
                pad_id: int = 0, seed: int = 0,
                skip: int = 0) -> Iterator[dict]:
    """Infinite supervised fine-tuning stream from ``(ids, prompt_len)``
    examples: each row is one example padded to ``seq_len``, loss masked
    to the RESPONSE tokens only (the standard instruction-tuning rule —
    the model is never trained to reproduce the prompt).

    The loss element at column ``j`` scores predicting token ``j+1``:
    it is kept iff ``j + 1 >= prompt_len`` (target is a response token)
    and ``j + 1 < len(ids)`` (target is real, not padding). Examples
    longer than ``seq_len + 1`` are truncated from the right; an example
    whose prompt alone fills the window contributes no loss and is
    rejected up front rather than silently training on nothing.
    """
    exs = []
    for ids, plen in examples:
        ids = list(ids)[:seq_len + 1]
        if plen >= len(ids):
            raise ValueError(
                f"example with prompt_len {plen} leaves no response "
                f"tokens inside seq_len {seq_len} — raise seq or trim "
                "the prompt")
        exs.append((ids, plen))
    if len(exs) < batch_size:
        raise ValueError(f"{len(exs)} examples < batch {batch_size}")
    rng = np.random.default_rng(seed)
    seq1 = seq_len + 1
    # resume fast path: skipped epochs advance the rng through identical
    # permutation draws; the within-epoch offset is index math only
    skip = skip_epochs(skip, len(exs) // batch_size,
                       lambda: rng.permutation(len(exs)))
    while True:
        order = rng.permutation(len(exs))
        start0 = skip * batch_size
        skip = 0
        for start in range(start0, len(order) - batch_size + 1, batch_size):
            toks = np.full((batch_size, seq1), pad_id, np.int32)
            mask = np.zeros((batch_size, seq_len), bool)
            for r, idx in enumerate(order[start:start + batch_size]):
                ids, plen = exs[idx]
                toks[r, :len(ids)] = ids
                mask[r, max(plen - 1, 0):len(ids) - 1] = True
            yield {"tokens": toks[:, :-1], "targets": toks[:, 1:],
                   "mask": mask}


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Rank-aware batch sharding: the leading axis shards over the data
    axes, a rank-2 [b, s] leaf additionally shards its sequence axis over
    cp (ring attention), and higher-rank leaves (images) shard the batch
    axis only."""
    full = mesh_lib.batch_spec()  # P((dp, fsdp), cp)

    def put(x):
        x = jnp.asarray(x)
        if x.ndim == 0:
            s = P()
        elif x.ndim == 2 and jnp.issubdtype(x.dtype, jnp.integer):
            # integer [b, s] = token ids/targets/segments: sequence axis
            # shards over cp. Float rank-2 leaves (feature matrices) only
            # shard the batch axis — cp is a sequence axis, and a feature
            # dim need not divide it.
            s = full
        else:
            s = P(full[0], *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(put, batch)


def sharded_synthetic_stream(batch_size: int, seq_len: int, vocab_size: int,
                             mesh: Mesh, seed: int = 0) -> Iterator[dict]:
    for batch in synthetic_lm_batches(batch_size, seq_len, vocab_size, seed):
        yield shard_batch(batch, mesh)


def prefetch_to_device(batches: Iterator[dict], mesh: Optional[Mesh] = None,
                       size: int = 2) -> Iterator[dict]:
    """Keep ``size`` device batches in flight ahead of the consumer.

    ``jax.device_put`` is asynchronous: issuing the transfer for batch
    N+1 while the step for batch N is still executing hides the
    host→device copy behind compute — the standard TPU input-pipeline
    overlap (without it, every step starts with a synchronous HBM fill).
    With ``mesh`` each host batch is sharded on the way in; without it
    the stream is assumed pre-sharded and only the lookahead window is
    added. Host memory holds at most ``size`` extra batches."""
    import collections

    put = (lambda b: shard_batch(b, mesh)) if mesh is not None \
        else (lambda b: b)
    size = max(size, 1)  # size<=0 would silently drop the whole stream
    queue = collections.deque()
    try:
        for _ in range(size):
            queue.append(put(next(batches)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(batches)))
        except StopIteration:
            pass
        yield out


class TokenFileDataset:
    """Pre-tokenized corpus on disk: a flat int32 (or int16/uint16) token
    array, memory-mapped — the layout GCS-FUSE/persistent-disk dataset
    caches serve (CacheBackend CRD mounts it; this reads it).

    Each host reads only its own contiguous shard of the file
    (``process_index``/``process_count``), so a multi-host job streams
    disjoint data with zero coordination.
    """

    def __init__(self, path: str, seq_len: int, batch_size: int,
                 dtype=np.int32, process_index: int = 0,
                 process_count: int = 1, seed: int = 0):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.batch_size = batch_size
        n = len(self.tokens) // (seq_len + 1)
        lo = n * process_index // process_count
        hi = n * (process_index + 1) // process_count
        if hi - lo < batch_size:
            # an undersized shard would make batches() spin forever
            # yielding nothing — fail loudly at construction instead
            raise ValueError(
                f"token file too small: {n} sequences across "
                f"{process_count} hosts leaves host {process_index} with "
                f"{hi - lo} (< batch_size {batch_size})")
        self._indices = np.arange(lo, hi)
        self._rng = np.random.default_rng(seed + process_index)

    def __len__(self) -> int:
        return len(self._indices)

    def batches(self, skip: int = 0) -> Iterator[dict]:
        """Infinite shuffled stream of {tokens, targets} (epoch reshuffle).

        ``skip`` fast-forwards by that many batches WITHOUT touching the
        memmap: whole skipped epochs advance the rng through the same
        permutation draws, and the within-epoch offset is pure index
        math — so resuming at batch N is O(epochs) cheap and batch N is
        bit-identical to batch N of an unskipped stream."""
        sl = self.seq_len
        skip = skip_epochs(skip, len(self._indices) // self.batch_size,
                           lambda: self._rng.permutation(self._indices))
        while True:
            order = self._rng.permutation(self._indices)
            start0 = skip * self.batch_size
            skip = 0
            for start in range(start0, len(order) - self.batch_size + 1,
                               self.batch_size):
                rows = [self.tokens[i * (sl + 1):(i + 1) * (sl + 1)]
                        for i in order[start:start + self.batch_size]]
                block = np.asarray(rows, dtype=np.int32)  # single host copy
                yield {"tokens": block[:, :-1], "targets": block[:, 1:]}
