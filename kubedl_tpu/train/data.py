"""Data pipelines: synthetic LM batches + sharded host loading.

The operator-side dataset story (CacheBackend CRD → host-disk cache) mounts
data into the container; this module is the in-container loader. For
benchmarks and CI the synthetic stream generates deterministic token
batches; ``shard_batch`` places a host-local batch onto the mesh with the
canonical (dp×fsdp, cp) sharding.
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib


def synthetic_lm_batches(batch_size: int, seq_len: int, vocab_size: int,
                         seed: int = 0) -> Iterator[dict]:
    """Deterministic stream of {tokens, targets} next-token batches."""
    rng = np.random.default_rng(seed)
    while True:
        toks = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                            dtype=np.int32)
        yield {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Rank-aware batch sharding: the leading axis shards over the data
    axes, a rank-2 [b, s] leaf additionally shards its sequence axis over
    cp (ring attention), and higher-rank leaves (images) shard the batch
    axis only."""
    full = mesh_lib.batch_spec()  # P((dp, fsdp), cp)

    def put(x):
        x = jnp.asarray(x)
        if x.ndim == 0:
            s = P()
        elif x.ndim == 2 and jnp.issubdtype(x.dtype, jnp.integer):
            # integer [b, s] = token ids/targets/segments: sequence axis
            # shards over cp. Float rank-2 leaves (feature matrices) only
            # shard the batch axis — cp is a sequence axis, and a feature
            # dim need not divide it.
            s = full
        else:
            s = P(full[0], *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(put, batch)


def sharded_synthetic_stream(batch_size: int, seq_len: int, vocab_size: int,
                             mesh: Mesh, seed: int = 0) -> Iterator[dict]:
    for batch in synthetic_lm_batches(batch_size, seq_len, vocab_size, seed):
        yield shard_batch(batch, mesh)
