"""Direct Preference Optimization (DPO) on the shared transformer core.

Preference fine-tuning for every model family in ``models/`` (Llama,
Gemma/-2, Mistral, Qwen2, the MoE stack): given (prompt, chosen,
rejected) pairs, push the policy's implied reward
``beta * (logp_policy - logp_ref)`` to rank chosen above rejected
(Rafailov et al. 2023).

TPU-first shape choices:

* per-sequence log-probabilities come from ``forward_hidden`` + the
  chunked LM-head scan (``ops.loss.chunked_token_nll``) — the
  [b, s, vocab] logits tensor is never materialized, the same HBM
  discipline as pre-training (``llama.lm_loss``);
* chosen and rejected rows ride ONE forward pass, concatenated on the
  batch axis ([2b, s]) so the MXU sees one large matmul stream and the
  dp-axis sharding of ``Trainer`` applies unchanged;
* the frozen reference model is optional at step time: pass
  ``ref_chosen_logps``/``ref_rejected_logps`` in the batch (precomputed
  once, offline — halves step FLOPs and HBM) or let the step compute
  them under ``stop_gradient`` from a second param tree.

No reference-repo analog: the reference (mental2008/kubedl) is an
operator with no training stack (SURVEY.md §2 note); this module is
beyond-parity compute for the in-tree TPU path. It composes with LoRA
(``ops/lora.py``) — wrap the policy params, leave the frozen base as the
DPO reference — the standard adapter-DPO recipe without a second full
model in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..models import llama
from ..ops.loss import chunked_token_nll
from .scoring import hidden_and_head, render_rows  # noqa: F401 — re-exported


@dataclass(frozen=True)
class DPOConfig:
    #: inverse-temperature of the implied reward
    beta: float = 0.1
    #: conservative-DPO label smoothing: probability the preference
    #: label is flipped (0 = trust labels fully)
    label_smoothing: float = 0.0
    #: "sigmoid" (DPO) or "ipo" (IPO's squared hinge — bounded, no
    #: winner-takes-all saturation)
    loss_type: str = "sigmoid"

    def __post_init__(self):
        if self.loss_type not in ("sigmoid", "ipo"):
            raise ValueError(f"unknown DPO loss_type {self.loss_type!r}")
        if not 0.0 <= self.label_smoothing < 0.5:
            raise ValueError(
                f"label_smoothing must be in [0, 0.5), got "
                f"{self.label_smoothing}")
        if self.loss_type == "ipo" and self.label_smoothing:
            raise ValueError(
                "IPO has no label-smoothing term; it would be silently "
                "ignored — use loss_type='sigmoid' for cDPO")


def sequence_logprobs(config, params, tokens, targets, mask=None,
                      mesh=None, chunk: int = 512, with_aux: bool = False):
    """Summed log P(targets | tokens) per row: [b, s] -> [b] float32.

    ``mask`` selects the completion positions (prompt tokens contribute
    nothing). Uses the chunked LM-head scan, so peak logits HBM is
    b*chunk*V regardless of sequence length. ``with_aux=True`` also
    returns the MoE load-balancing aux loss (0 for dense families)."""
    x, head, aux = hidden_and_head(config, params, tokens, mesh)
    lp = -chunked_token_nll(x, head, targets, mask=mask, chunk=chunk,
                            logit_softcap=config.logit_softcap)
    return (lp, aux) if with_aux else lp


def dpo_loss(policy_chosen, policy_rejected, ref_chosen, ref_rejected,
             cfg: DPOConfig = DPOConfig()):
    """Scalar loss + metrics from per-sequence logps (all [b] float32).

    Returns ``(loss, metrics)`` where metrics carries the implied
    rewards, their margin, and ranking accuracy."""
    chosen_reward = cfg.beta * (policy_chosen - ref_chosen)
    rejected_reward = cfg.beta * (policy_rejected - ref_rejected)
    logits = chosen_reward - rejected_reward
    if cfg.loss_type == "ipo":
        # IPO regresses the RAW log-ratio margin (logits / beta) to
        # 1/(2 beta); no label smoothing term
        loss = jnp.mean(
            (logits / cfg.beta - 1.0 / (2.0 * cfg.beta)) ** 2)
    else:
        ls = cfg.label_smoothing
        loss = jnp.mean(
            -(1.0 - ls) * jax.nn.log_sigmoid(logits)
            - ls * jax.nn.log_sigmoid(-logits))
    metrics = {
        "reward_chosen": jnp.mean(chosen_reward),
        "reward_rejected": jnp.mean(rejected_reward),
        "reward_margin": jnp.mean(logits),
        "accuracy": jnp.mean((logits > 0).astype(jnp.float32)),
    }
    return loss, metrics


def _pair_logprobs(config, params, batch, mesh, chunk,
                   with_aux: bool = False):
    """One concatenated forward over chosen+rejected rows -> ([b], [b])."""
    tokens = jnp.concatenate([batch["chosen_tokens"],
                              batch["rejected_tokens"]])
    targets = jnp.concatenate([batch["chosen_targets"],
                               batch["rejected_targets"]])
    mask = None
    if "chosen_mask" in batch:
        mask = jnp.concatenate([batch["chosen_mask"],
                                batch["rejected_mask"]])
    lp, aux = sequence_logprobs(config, params, tokens, targets,
                                mask=mask, mesh=mesh, chunk=chunk,
                                with_aux=True)
    b = batch["chosen_tokens"].shape[0]
    if with_aux:
        return lp[:b], lp[b:], aux
    return lp[:b], lp[b:]


def make_dpo_loss_fn(config, dpo: DPOConfig = DPOConfig(),
                     ref_params=None, mesh=None, chunk: int = 512):
    """Build ``loss_fn(params, batch) -> scalar`` for ``train.Trainer``.

    Batch keys: ``{chosen,rejected}_{tokens,targets}`` (+ optional
    ``_mask``), and either ``ref_{chosen,rejected}_logps`` (precomputed —
    preferred) or nothing, in which case ``ref_params`` must be given and
    the frozen reference runs inside the step under ``stop_gradient``."""

    def loss_fn(params, batch):
        pol_c, pol_r, aux = _pair_logprobs(config, params, batch, mesh,
                                           chunk, with_aux=True)
        if "ref_chosen_logps" in batch:
            ref_c = batch["ref_chosen_logps"].astype(jnp.float32)
            ref_r = batch["ref_rejected_logps"].astype(jnp.float32)
        elif ref_params is not None:
            ref_c, ref_r = _pair_logprobs(
                config, jax.tree.map(jax.lax.stop_gradient, ref_params),
                batch, mesh, chunk)
            ref_c = jax.lax.stop_gradient(ref_c)
            ref_r = jax.lax.stop_gradient(ref_r)
        else:
            raise ValueError(
                "DPO needs ref_{chosen,rejected}_logps in the batch or "
                "ref_params at build time")
        loss, _ = dpo_loss(pol_c, pol_r, ref_c, ref_r, dpo)
        # MoE: keep the router balanced through preference tuning too
        aux_w = getattr(config, "aux_loss_weight", 0.0)
        return loss + aux_w * aux

    return loss_fn


def reference_logps_fn(config, ref_params, mesh=None, chunk: int = 512):
    """Jitted ``batch -> (ref_chosen_logps, ref_rejected_logps)`` for the
    precompute-once data-prep pass. ``ref_params`` ride as a real jit
    argument (device buffers), not baked-in constants."""
    jitted = jax.jit(partial(_pair_logprobs, config, mesh=mesh,
                             chunk=chunk))
    return lambda batch: jitted(ref_params, batch=batch)


def preference_batch(prompt_and_chosen, prompt_and_rejected,
                     prompt_lens, pad_id: int = 0):
    """Assemble a DPO batch from already-tokenized rows.

    Args:
      prompt_and_chosen / prompt_and_rejected: list of int lists, each
        the full prompt+completion token sequence.
      prompt_lens: per-pair prompt length (masked out of the loss).

    Both sides render through the shared ``render_rows`` layout (right
    pad to one 128-aligned length, shifted targets, completion-only
    mask)."""
    n = len(prompt_and_chosen)
    if not (n == len(prompt_and_rejected) == len(prompt_lens)):
        raise ValueError("pair lists must have equal length")
    longest = max(len(r) for r in prompt_and_chosen + prompt_and_rejected)
    s = -(-longest // 128) * 128
    c = render_rows(prompt_and_chosen, prompt_lens, pad_id, pad_to=s)
    r = render_rows(prompt_and_rejected, prompt_lens, pad_id, pad_to=s)
    return {"chosen_tokens": c["tokens"], "chosen_targets": c["targets"],
            "chosen_mask": c["mask"], "rejected_tokens": r["tokens"],
            "rejected_targets": r["targets"], "rejected_mask": r["mask"]}
