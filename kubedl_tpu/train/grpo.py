"""GRPO — group-relative policy optimization (RL from verifiable rewards).

The critic-free PPO variant used for reasoning post-training (Shao et
al. 2024, DeepSeekMath): sample a GROUP of completions per prompt, score
them with a programmatic reward, normalize rewards within each group
into advantages (no value network), and update with a token-level
clipped importance-ratio objective plus a KL penalty to a frozen
reference.

TPU-first shape choices, matching the rest of ``train/``:

* per-token log-probabilities come from ``forward_hidden`` + the chunked
  LM-head scan (``ops.loss.chunked_token_logps``): [b, s] floats are
  cheap, the [b, s, V] logits never materialize;
* rollouts come from the in-tree serving engine
  (``serving.engine.InferenceEngine.generate(return_logprobs=True)``),
  whose sampled-token logprobs ARE the behavior-policy term — no second
  scoring pass over the rollout batch;
* the update is a plain ``Trainer`` loss function: the same sharded,
  jitted, donated step as pre-training, DPO, and LoRA.

No reference-repo analog (the reference operator has no training stack,
SURVEY.md §2); beyond-parity compute for the in-tree TPU path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.loss import chunked_token_logps
from .scoring import hidden_and_head, render_rows


@dataclass(frozen=True)
class GRPOConfig:
    #: completions sampled per prompt (the "group")
    group_size: int = 8
    #: PPO clip width for the token importance ratio
    clip_eps: float = 0.2
    #: weight of the k3 KL penalty to the frozen reference
    kl_coef: float = 0.04
    #: divide group-centered rewards by the group std (classic GRPO);
    #: False = center only (the "Dr. GRPO" debiasing)
    normalize_std: bool = True

    def __post_init__(self):
        if self.group_size < 2:
            raise ValueError("group_size must be >= 2 (advantages are "
                             "relative within a group)")
        if self.clip_eps <= 0:
            raise ValueError("clip_eps must be > 0")
        if self.kl_coef < 0:
            raise ValueError("kl_coef must be >= 0")


def group_advantages(rewards, cfg: GRPOConfig = GRPOConfig()):
    """[n_groups, group_size] rewards -> same-shape advantages.

    Center within each group; optionally scale by the group std. A
    group whose rewards are all equal gets exactly zero advantage
    (epsilon guard, no NaN)."""
    r = jnp.asarray(rewards, jnp.float32)
    if r.ndim != 2:
        raise ValueError(f"rewards must be [n_groups, group_size], got "
                         f"shape {r.shape}")
    centered = r - jnp.mean(r, axis=1, keepdims=True)
    if cfg.normalize_std:
        centered = centered / (jnp.std(r, axis=1, keepdims=True) + 1e-6)
    return centered


def token_logps(config, params, tokens, targets, mesh=None,
                chunk: int = 512, with_aux: bool = False):
    """Per-token log P(targets | tokens): [b, s] float32 (any family).
    ``with_aux=True`` also returns the MoE router aux loss (0 dense)."""
    x, head, aux = hidden_and_head(config, params, tokens, mesh)
    lp = chunked_token_logps(x, head, targets, chunk=chunk,
                             logit_softcap=config.logit_softcap)
    return (lp, aux) if with_aux else lp


def grpo_loss(logps, old_logps, ref_logps, advantages, mask,
              cfg: GRPOConfig = GRPOConfig()):
    """Token-level clipped surrogate + KL penalty.

    Args: logps/old_logps/ref_logps [b, s] (policy, behavior, frozen
    reference); advantages [b] (one per completion); mask [b, s] over
    completion tokens. Returns (loss, metrics)."""
    adv = advantages[:, None].astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)

    log_ratio = logps - jax.lax.stop_gradient(old_logps)
    ratio = jnp.exp(log_ratio)
    clipped = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps)
    pg = -jnp.minimum(ratio * adv, clipped * adv)

    # k3 estimator: non-negative, unbiased in expectation
    ref_delta = jax.lax.stop_gradient(ref_logps) - logps
    kl = jnp.exp(ref_delta) - ref_delta - 1.0

    loss = jnp.sum((pg + cfg.kl_coef * kl) * mask) / denom
    metrics = {
        "kl": jnp.sum(kl * mask) / denom,
        "clip_frac": jnp.sum(
            (jnp.abs(ratio - 1.0) > cfg.clip_eps) * mask) / denom,
        "ratio_mean": jnp.sum(ratio * mask) / denom,
        "reward_advantage_mean": jnp.mean(advantages),
    }
    return loss, metrics


def make_grpo_loss_fn(config, grpo: GRPOConfig = GRPOConfig(),
                      mesh=None, chunk: int = 512):
    """Build ``loss_fn(params, batch) -> scalar`` for ``train.Trainer``.

    Batch keys: ``tokens``/``targets``/``mask`` [b, s],
    ``advantages`` [b], ``old_logps``/``ref_logps`` [b, s] (behavior and
    reference logps are data — precomputed, never differentiated)."""

    def loss_fn(params, batch):
        lp, aux = token_logps(config, params, batch["tokens"],
                              batch["targets"], mesh=mesh, chunk=chunk,
                              with_aux=True)
        loss, _ = grpo_loss(lp, batch["old_logps"], batch["ref_logps"],
                            batch["advantages"], batch["mask"], grpo)
        # MoE: keep the router balanced through RL too (matches DPO)
        aux_w = getattr(config, "aux_loss_weight", 0.0)
        return loss + aux_w * aux

    return loss_fn


def _generate_submit(engine, groups, max_new_tokens: int, seed: int):
    """Rollouts through the paged/continuous path: any object with the
    fleet submit surface (``submit(prompt, max_new, logprobs=,
    temperature=, top_k=, top_p=)`` — a ``ContinuousBatchingEngine``, a
    fleet router, or the RL ``RolloutClient``). Per-request overrides
    force plain temperature-1 sampling so the engine's full-softmax
    logprobs ARE the behavior policy, whatever its own GenerateConfig
    says — the bare-``generate`` path has to refuse a greedy engine;
    this one just overrides it. ``reseed`` (when exposed) pins the
    sampling stream so a fixed (seed, policy version) reproduces the
    exact token streams."""
    reseed = getattr(engine, "reseed", None)
    if reseed is not None:
        reseed(seed)
    reqs = [engine.submit(list(p), max_new_tokens, logprobs=True,
                          temperature=1.0, top_k=0, top_p=1.0)
            for p in groups]
    step = getattr(engine, "step", None)
    if step is not None:
        while step():
            pass
    return [(r.result(), list(r.logprobs)) for r in reqs]


def rollout_batch(engine, prompts, reward_fn, max_new_tokens: int,
                  cfg: GRPOConfig = GRPOConfig(), seed: int = 0,
                  pad_id: int = 0):
    """Sample a group of completions per prompt and assemble the GRPO
    update batch.

    ``engine`` holds the CURRENT policy weights; its sampled-token
    logprobs become ``old_logps``. Two generation surfaces are accepted:
    the fleet submit surface (``submit``/``step`` — the paged,
    continuous-batching path; preferred) and the legacy bare
    ``InferenceEngine.generate`` handle. ``reward_fn(prompt_ids,
    completion_ids) -> float`` is the verifiable reward. Returns the
    batch dict (numpy, 128-aligned) WITHOUT ``ref_logps`` — score it
    with ``token_logps`` under the frozen reference, then pass to the
    trainer."""
    groups = [list(p) for p in prompts for _ in range(cfg.group_size)]
    if hasattr(engine, "submit"):
        outs = _generate_submit(engine, groups, max_new_tokens, seed)
    else:
        gen = getattr(engine, "gen", None)
        if gen is not None:
            # the engine reports FULL-softmax logprobs (token_logprobs
            # is deliberately sampling-agnostic); they equal the
            # behavior policy only under plain temperature-1 sampling.
            # Greedy would additionally make every group identical ->
            # all advantages 0.
            if gen.temperature != 1.0 or gen.top_k or gen.top_p != 1.0:
                raise ValueError(
                    "GRPO rollouts need plain sampling (temperature=1, "
                    f"no top_k/top_p) so reported logprobs ARE the "
                    f"behavior policy; engine has temperature="
                    f"{gen.temperature}, top_k={gen.top_k}, "
                    f"top_p={gen.top_p}")
        outs = engine.generate(groups, max_new_tokens, seed=seed,
                               return_logprobs=True)
    return assemble_batch(groups, outs, len(prompts), reward_fn,
                          cfg=cfg, pad_id=pad_id)


def assemble_batch(groups, outs, n_prompts: int, reward_fn,
                   cfg: GRPOConfig = GRPOConfig(), pad_id: int = 0):
    """Completed rollouts -> the GRPO update batch (the assembly half of
    :func:`rollout_batch`, shared with the RL flywheel's
    ``RolloutClient``, which gathers ``outs`` through the fleet router
    instead of one engine). ``groups`` is the flat prompt list
    (``n_prompts * cfg.group_size`` rows, group-major); ``outs`` is one
    ``(generated_ids, logprobs)`` pair per row."""
    rewards = np.asarray(
        [reward_fn(groups[i], ids) for i, (ids, _) in enumerate(outs)],
        np.float32).reshape(n_prompts, cfg.group_size)
    adv = np.asarray(group_advantages(rewards, cfg))

    rows = [p + list(ids) for p, (ids, _) in zip(groups, outs)]
    batch = render_rows(rows, [len(p) for p in groups], pad_id)
    old = np.zeros_like(batch["mask"])
    for i, (p, (ids, lps)) in enumerate(zip(groups, outs)):
        pl = len(p)
        old[i, pl - 1:pl - 1 + len(ids)] = np.asarray(lps, np.float32)
    batch.update(old_logps=old, advantages=adv.reshape(-1),
                 rewards=rewards)
    return batch
