"""Training container entrypoint: ``python -m kubedl_tpu.train``.

The training-side twin of ``python -m kubedl_tpu.serving``: a JAXJob /
PyTorchJob container can run a full config-driven training job — model
preset, data source, parallelism mesh, checkpointing, elastic protocol,
model export — without shipping its own train.py. Everything the
operator injects is honored:

* rendezvous env (``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``/
  coordinator) initializes ``jax.distributed`` via
  ``runtime.bootstrap`` (multi-host slices rendezvous exactly as the
  controller rendered them, SURVEY.md §2-P);
* ``KUBEDL_MODEL_PATH`` (the ModelVersion artifact volume the engine
  mounts on success-tracked jobs) receives the final exported model, so
  `job succeeds -> ModelVersion -> Kaniko image -> Inference predictor`
  composes end to end;
* the 2-phase elastic checkpoint protocol runs when the job coordinates
  are present (``KUBEDL_JOB_KIND/NAMESPACE/NAME`` + an in-cluster
  api-server): ``ElasticCheckpointAgent`` answers
  ``kubedl.io/ckpt-requested-version`` between steps.

Config is JSON — ``--config /path.json``, or inline in
``$KUBEDL_TRAIN_CONFIG``:

    {"model": "llama.tiny", "mode": "pretrain",
     "data": {"kind": "synthetic"},
     "batch": 8, "seq": 256, "steps": 200,
     "mesh": {"dp": 2, "fsdp": -1},
     "optimizer": {"learning_rate": 3e-4},
     "checkpoint": {"directory": "/ckpt", "save_interval_steps": 50}}

``model`` is ``family.preset`` (``llama.llama3_8b``, ``gemma.gemma_2b``,
``moe.mixtral_8x7b``, every zero-arg constructor in those modules), or
``{"model_path": dir}`` to fine-tune a saved artifact;
``model_overrides`` tweaks any config field. ``mode`` is ``pretrain``
(next-token loss; data ``synthetic``, a ``tokens`` memmap file, or
``text`` — a raw ``.jsonl``/``.txt`` corpus tokenized by
``data.tokenizer`` ("byte" or a local HuggingFace tokenizer dir,
``kubedl_tpu.tokenizer``) and document-packed into segment-isolated
batches),
``sft`` (instruction tuning from JSONL rows ``{"prompt": ...,
"response": ...}`` — text with ``data.tokenizer``, or token-id lists —
loss masked to response tokens only),
``evaluate`` (no training: corpus perplexity, or multiple-choice
accuracy from ``eval_jsonl`` rows — results to INFO and
``results_path``),
``dpo`` (preference pairs from JSONL rows
``{"chosen": [...], "rejected": [...], "prompt_len": n}``, frozen
initial weights as the DPO reference), or ``grpo`` (on-policy RL from a
verifiable reward: prompts from JSONL rows ``{"prompt": [ids]}`` or raw
text with ``data.tokenizer``, the reward a user-supplied callable named
by ``reward`` — ``"pkg.mod:fn"`` or ``"/path/rewards.py:fn"`` — called
as ``fn(prompt_ids, completion_ids) -> float``, with ``tokenizer=``
bound when the function declares that parameter (text-level rewards);
each round samples a group per prompt from an in-process serving engine
rebuilt on the current weights, then takes ``rollout.steps_per_round``
update steps).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys

log = logging.getLogger("kubedl.train")

#: model families the preset resolver may import from
_FAMILIES = ("llama", "gemma", "moe")


def load_config(argv=None) -> dict:
    p = argparse.ArgumentParser(prog="python -m kubedl_tpu.train")
    p.add_argument("--config", help="path to the JSON training config")
    args = p.parse_args(argv)
    if args.config:
        with open(args.config) as f:
            return json.load(f)
    raw = os.environ.get("KUBEDL_TRAIN_CONFIG", "")
    if not raw:
        raise SystemExit(
            "no config: pass --config FILE or set $KUBEDL_TRAIN_CONFIG")
    return json.loads(raw)


def resolve_model(cfg: dict):
    """``model`` -> (config, params-or-None). Params come back non-None
    only for ``model_path`` artifacts (fine-tuning)."""
    import importlib

    model = cfg.get("model", "llama.tiny")
    if isinstance(model, dict):
        from ..models.io import load_model
        config, params = load_model(model["model_path"])
    else:
        fam, _, preset = model.partition(".")
        if fam not in _FAMILIES or not preset:
            raise ValueError(
                f"model must be one of {_FAMILIES} as 'family.preset', "
                f"or {{'model_path': dir}}; got {model!r}")
        mod = importlib.import_module(f"kubedl_tpu.models.{fam}")
        try:
            ctor = getattr(mod, preset)
        except AttributeError:
            raise ValueError(f"unknown preset {preset!r} in "
                             f"models.{fam}") from None
        config, params = ctor(), None
    if cfg.get("model_overrides"):
        config = dataclasses.replace(config, **cfg["model_overrides"])
    if getattr(config, "loss_chunk", 0) == 0 \
            and "loss_chunk" not in cfg.get("model_overrides", {}):
        # presets default loss_chunk=0 (naive [b, s, V] logits) — at
        # real vocab sizes that is tens of GB; the entrypoint always
        # takes the chunked LM-head scan unless explicitly overridden
        config = dataclasses.replace(config, loss_chunk=512)
    return config, params


def data_stream(cfg: dict, config, mesh, batch: int, seq: int,
                skip: int = 0):
    """Pretrain batch iterator per the ``data`` section (device-placed,
    prefetched). ``skip`` fast-forwards the underlying host stream by
    that many batches (checkpoint resume) — batch ``skip`` of the
    returned iterator is bit-identical to batch ``skip`` of an
    unskipped one. The result is a :class:`~.data.CountingIterator`
    whose ``consumed`` is the absolute cursor the checkpoint layer
    persists."""
    from .data import CountingIterator, prefetch_to_device

    data = cfg.get("data", {"kind": "synthetic"})
    raw = _raw_stream(data, config, batch, seq, skip=skip)
    return CountingIterator(prefetch_to_device(raw, mesh, size=2),
                            consumed=skip)


def _raw_stream(data: dict, config, batch: int, seq: int, skip: int = 0):
    """Host-side batch stream for one ``data`` spec; ``mixture``
    composes sub-streams by weight (domain mixing: each step draws its
    batch from one source, in expectation proportional to the
    weights). ``skip`` fast-forwards: token files skip by index math,
    synthetic replays rng draws, packed text / mixtures replay host-side
    packing (no device work either way)."""
    import jax

    from .data import TokenFileDataset, skip_batches, synthetic_lm_batches

    kind = data.get("kind", "synthetic")
    if kind == "mixture":
        import numpy as np
        sources = data.get("sources") or []
        if len(sources) < 2:
            raise ValueError("mixture needs >= 2 sources")
        weights = np.asarray([float(s.get("weight", 1.0))
                              for s in sources])
        if (weights <= 0).any():
            raise ValueError("mixture weights must be > 0")
        weights = weights / weights.sum()
        # the source-selection rng must be HOST-INVARIANT: hosts drawing
        # different sources in the same step would trace different
        # programs (packed vs plain batches) and desync the SPMD
        # collectives. Per-host data divergence comes from each source's
        # own host sharding.
        rng = np.random.default_rng(data.get("seed", 0))
        # resume: replay ONLY the selection draws (one rng.choice per
        # skipped batch — identical draw sequence to the unskipped
        # stream), then hand each source its own per-source skip count so
        # token files fast-forward by index math instead of materializing
        # every skipped batch
        counts = [0] * len(sources)
        for _ in range(skip):
            counts[int(rng.choice(len(sources), p=weights))] += 1
        streams = [_raw_stream(s, config, batch, seq, skip=c)
                   for s, c in zip(sources, counts)]

        def mixed():
            while True:
                yield next(streams[rng.choice(len(streams), p=weights)])
        return mixed()
    if kind == "synthetic":
        raw = synthetic_lm_batches(batch, seq, config.vocab_size,
                                   seed=data.get("seed", 0), skip=skip)
        skip = 0
    elif kind == "tokens":
        raw = TokenFileDataset(
            data["path"], seq, batch,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            seed=data.get("seed", 0)).batches(skip=skip)
        skip = 0
    elif kind == "text":
        # raw text corpus (.jsonl {"text": ...} rows or plain lines):
        # tokenize, then document-pack into segment-isolated batches —
        # the packer's segment_ids/positions/mask flow through loss_fn
        import numpy as np

        from ..tokenizer import load_tokenizer, text_documents
        from .data import pack_documents
        tok = load_tokenizer(data.get("tokenizer", "byte"))
        if tok is None:
            raise ValueError("data.kind='text' needs data.tokenizer")
        if tok.vocab_size > config.vocab_size:
            raise ValueError(
                f"tokenizer vocab {tok.vocab_size} exceeds model vocab "
                f"{config.vocab_size} — wrong tokenizer for this model")
        # materialize once: fine-tune corpora fit host RAM, and a list
        # (not a generator) routes through the native C++ packer. Each
        # host takes a disjoint stride of the corpus.
        docs = [d for i, d in enumerate(
                    text_documents(data["path"], tok,
                                   text_key=data.get("text_key", "text")))
                if i % jax.process_count() == jax.process_index()]
        if not docs:
            raise ValueError(f"no documents in {data['path']} for host "
                             f"{jax.process_index()}")
        rng = np.random.default_rng(
            data.get("seed", 0) + jax.process_index())

        def packed_epochs():
            while True:
                order = rng.permutation(len(docs))
                n = 0
                for b in pack_documents([docs[i] for i in order], seq,
                                        batch, pad_id=tok.pad_id):
                    n += 1
                    yield b
                if n == 0:
                    # the packer only yields FULL batches; a corpus that
                    # rounds down to zero would spin here forever
                    raise ValueError(
                        f"corpus {data['path']} packs into 0 full "
                        f"batches of {batch}x{seq} — lower batch/seq or "
                        "add data")
        raw = packed_epochs()
    else:
        raise ValueError(f"unknown data kind {kind!r} for pretrain")
    return skip_batches(raw, skip)


def build_eval_fn(cfg: dict, config, mesh, batch: int, seq: int,
                  params_of=None):
    """(eval_every, eval_fn) for in-training validation: ``eval``
    section ``{"every": N, "data": {...}, "max_batches": M}`` draws a
    FIXED held-out set once (every eval point scores the same tokens,
    so the curve is comparable) and returns a closure the Trainer calls
    between steps."""
    import itertools
    import math

    ecfg = cfg.get("eval") or {}
    every = int(ecfg.get("every", 0))
    if not every:
        return 0, None
    if not ecfg.get("data"):
        raise ValueError("eval.every needs eval.data (a held-out source)")
    import jax.numpy as jnp

    from . import evaluate as ev

    n = int(ecfg.get("max_batches", 8))
    stream = data_stream({**cfg, "data": ecfg["data"]}, config, mesh,
                         batch, seq)
    ev_batches = list(itertools.islice(stream, n))
    row_nll = ev.make_row_nll_fn(config, mesh)

    def eval_fn(state):
        p = params_of(state) if params_of is not None else state.params
        total = cnt = 0.0
        for b in ev_batches:
            total += float(jnp.sum(row_nll(p, b)))
            mask = b.get("mask")
            cnt += (float(jnp.sum(mask)) if mask is not None
                    else b["tokens"].shape[0] * b["tokens"].shape[1])
        nll = total / max(cnt, 1.0)
        return {"val_nll": nll, "val_ppl": math.exp(min(nll, 80.0))}

    return every, eval_fn


def sft_stream(cfg: dict, config, mesh, batch: int, seq: int,
               skip: int = 0):
    """Instruction-tuning batches from an ``sft_jsonl`` file: rows
    ``{"prompt": ..., "response": ...}`` where each field is raw text
    (requires ``data.tokenizer``) or a token-id list. Loss covers
    response tokens only (``train.data.sft_batches``). ``skip``
    fast-forwards for checkpoint resume (epoch-permutation index math,
    no batch materialization)."""
    from ..tokenizer import load_tokenizer
    from .data import CountingIterator, prefetch_to_device, sft_batches

    data = cfg.get("data", {})
    if data.get("kind") != "sft_jsonl":
        raise ValueError("mode=sft needs data.kind='sft_jsonl'")
    tok = load_tokenizer(data.get("tokenizer", ""))
    _check_tok_vocab(tok, config)

    def ids_of(v, *, bos: bool, eos: bool):
        if isinstance(v, list):
            return [int(t) for t in v]
        if tok is None:
            raise ValueError(
                "text prompt/response rows need data.tokenizer")
        return tok.encode(v, add_bos=bos, add_eos=eos)

    examples = []
    with open(data["path"]) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            p = ids_of(row["prompt"], bos=True, eos=False)
            r = ids_of(row["response"], bos=False, eos=True)
            examples.append((p + r, len(p)))
    if not examples:
        raise ValueError(f"no rows in {data['path']}")
    stream = sft_batches(examples, seq, batch,
                         pad_id=tok.pad_id if tok is not None else 0,
                         seed=data.get("seed", 0), skip=skip)
    return CountingIterator(prefetch_to_device(stream, mesh, size=2),
                            consumed=skip)


def dpo_batches(cfg: dict, config, params, mesh, batch: int,
                skip: int = 0):
    """Infinite DPO batch stream from a pairs JSONL, reference logps
    precomputed once per batch under the FROZEN initial weights.
    ``skip`` fast-forwards the round-robin cursor by index math —
    crucially WITHOUT recomputing reference logps for skipped batches
    (they are per-batch device work)."""
    import jax.numpy as jnp

    from . import dpo
    from .data import CountingIterator, shard_batch

    data = cfg.get("data", {})
    if data.get("kind") != "dpo_jsonl":
        raise ValueError("mode=dpo needs data.kind='dpo_jsonl'")
    rows = []
    with open(data["path"]) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    if len(rows) < batch:
        raise ValueError(f"{len(rows)} pairs < batch {batch}")
    ref_fn = dpo.reference_logps_fn(config, params, mesh=mesh)

    def stream():
        i = (skip * batch) % len(rows)
        while True:
            chunk = [rows[(i + j) % len(rows)] for j in range(batch)]
            i = (i + batch) % len(rows)
            b = dpo.preference_batch(
                [r["chosen"] for r in chunk],
                [r["rejected"] for r in chunk],
                [r["prompt_len"] for r in chunk])
            b = {k: jnp.asarray(v) for k, v in b.items()}
            ref_c, ref_r = ref_fn(b)
            b["ref_chosen_logps"] = ref_c
            b["ref_rejected_logps"] = ref_r
            yield shard_batch(b, mesh)

    return CountingIterator(stream(), consumed=skip)


def build_pp_pretrain(config, mesh, num_micro: int):
    """``mesh: {"pp": n}`` with n > 1: GPipe pipeline training through
    the entrypoint (llama-family, plain/SFT batches). Layers are
    stage-stacked ``[pp, L/pp, ...]`` and flow through
    ``parallel.pipeline.pipeline_apply``; ``jax.grad`` differentiates
    straight through the ppermute ring, so EVERY param (embedding and
    head included) trains. Returns ``(loss_fn, to_pp, from_pp,
    specs_of)`` — to_pp/from_pp restack params between the flat
    checkpoint/export layout and the staged training layout.
    ``pipeline_grads_1f1b`` remains the library-level memory-bound
    scheduler. Reference analog: none (SURVEY §2-P: in-process
    parallelism is delegated to the user's framework)."""
    import jax

    from ..models import llama
    from ..parallel.pipeline import (pipeline_apply, stack_stages,
                                     stage_scan)
    from ..parallel.sharding import spec as logical_spec

    pp = mesh.shape["pp"]
    if llama.window_flags(config) is not None:
        raise ValueError(
            "pp training does not support per-layer window patterns "
            "(Gemma-2 alternating windows) yet")
    if not config.scan_layers:
        # stack_stages restacks the leading LAYER axis; per-layer dict
        # lists have no such axis and would restack d_model instead
        raise ValueError("pp training needs scan_layers=True "
                         "(stacked layer params)")
    if config.n_layers % pp:
        raise ValueError(
            f"{config.n_layers} layers not divisible by pp={pp}")

    def to_pp(params):
        out = {k: v for k, v in params.items() if k != "layers"}
        out["stages"] = stack_stages(params["layers"], pp)
        return out

    def from_pp(params):
        out = {k: v for k, v in params.items() if k != "stages"}
        out["layers"] = jax.tree.map(
            lambda p: p.reshape((config.n_layers,) + p.shape[2:]),
            params["stages"])
        return out

    def specs_of(params_pp):
        base = llama.param_specs(config)
        sp = {k: v for k, v in base.items() if k != "layers"}
        sp["stages"] = jax.tree.map(lambda _: logical_spec("stages"),
                                    params_pp["stages"])
        return sp

    def loss_fn(params, batch):
        if "segment_ids" in batch:
            # backstop — the entrypoint rejects packed data kinds before
            # any data opens
            raise ValueError(
                "pp training does not support packed (segment-id) "
                "batches yet — use data.kind tokens/synthetic/sft_jsonl")

        def apply_layers(x, cos, sin):
            def body(x, lp):
                return llama._layer_forward(config, x, lp, cos, sin,
                                            None)
            if config.remat:
                body = jax.checkpoint(
                    body,
                    policy=jax.checkpoint_policies
                    .checkpoint_dots_with_no_batch_dims)
            return pipeline_apply(mesh, stage_scan(body),
                                  params["stages"], x, num_micro)

        # prologue (embed/embed_scale/rope) and final norm are SHARED
        # with the flat forward via the apply_layers hook — the two
        # forwards cannot drift as model knobs accrue
        x = llama.forward_hidden(config, params, batch["tokens"],
                                 apply_layers=apply_layers)
        return llama.lm_loss(config, x, params, batch["targets"],
                             mask=batch.get("mask"))

    return loss_fn, to_pp, from_pp, specs_of


def _data_fingerprint(cfg: dict, mode: str, batch: int, seq: int) -> dict:
    """Identity of the data stream a checkpoint cursor belongs to. A
    restored cursor only fast-forwards when the stream it counted is the
    stream about to be built — after a config change (different corpus /
    batch / seq / mode) the offset is meaningless, so the stream restarts
    at 0 with a warning instead of silently misaligning."""
    return {"mode": mode, "batch": batch, "seq": seq,
            "data": cfg.get("data", {"kind": "synthetic"})}


def _check_tok_vocab(tok, config) -> None:
    """The ONE tokenizer-fits-model rule: ids past the embedding table
    are clamped by the TPU gather, so a mismatch would produce silently
    meaningless numbers rather than an error."""
    if tok is not None and tok.vocab_size > config.vocab_size:
        raise ValueError(
            f"tokenizer vocab {tok.vocab_size} exceeds model vocab "
            f"{config.vocab_size} — wrong tokenizer for this model")


def run_evaluate(cfg: dict, config, params, mesh) -> int:
    """``mode=evaluate``: score a model without training — corpus
    perplexity (data kinds ``synthetic``/``tokens``/``text``) or
    multiple-choice accuracy (``eval_jsonl`` rows ``{"prompt": ...,
    "options": [...], "answer": i?}``, text fields via
    ``data.tokenizer``). Results log to INFO and, with
    ``results_path``, land as one JSON file — so an eval is just a
    JAXJob with this config."""
    from ..tokenizer import load_tokenizer
    from . import evaluate as ev

    data = cfg.get("data", {})
    ecfg = cfg.get("eval", {})
    tok = load_tokenizer(data.get("tokenizer", ""))
    _check_tok_vocab(tok, config)

    if data.get("kind") == "eval_jsonl":
        def ids_of(v, *, bos: bool):
            if isinstance(v, list):
                return [int(t) for t in v]
            if tok is None:
                raise ValueError("text eval rows need data.tokenizer")
            return tok.encode(v, add_bos=bos)

        rows = []
        with open(data["path"]) as f:
            for line in f:
                if line.strip():
                    rows.append(json.loads(line))
        if not rows:
            raise ValueError(f"no rows in {data['path']}")
        questions = [{"prompt": ids_of(r["prompt"], bos=True),
                      "options": [ids_of(o, bos=False)
                                  for o in r["options"]]} for r in rows]
        ranked = ev.loglikelihood_ranks(
            config, params, questions, mesh=mesh,
            length_normalize=bool(ecfg.get("length_normalize", False)))
        results = {"kind": "loglikelihood", "questions": len(ranked),
                   "choices": [r["choice"] for r in ranked]}
        answers = [r.get("answer") for r in rows]
        if all(a is not None for a in answers):
            correct = sum(int(c == a) for c, a in
                          zip(results["choices"], answers))
            results["accuracy"] = correct / len(answers)
    else:
        batch = int(cfg.get("batch", 8))
        seq = int(cfg.get("seq", 256))
        batches = data_stream(cfg, config, mesh, batch, seq)
        results = ev.perplexity(config, params, batches, mesh=mesh,
                                max_batches=int(cfg.get("steps", 16)))
        results["kind"] = "perplexity"

    log.info("evaluate results: %s", json.dumps(results))
    out = cfg.get("results_path")
    if out:
        import jax
        if jax.process_index() == 0:
            with open(out, "w") as f:
                json.dump(results, f, indent=1)
            log.info("results written to %s", out)
    return 0


def resolve_reward(spec: str):
    """``"pkg.mod:fn"`` or ``"/path/file.py:fn"`` -> the reward callable
    ``fn(prompt_ids, completion_ids) -> float``."""
    import importlib
    import importlib.util

    mod_spec, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(
            f"reward must be 'module:function' or '/path.py:function', "
            f"got {spec!r}")
    if mod_spec.endswith(".py"):
        py_spec = importlib.util.spec_from_file_location(
            "kubedl_reward", mod_spec)
        mod = importlib.util.module_from_spec(py_spec)
        py_spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(mod_spec)
    try:
        return getattr(mod, fn_name)
    except AttributeError:
        raise ValueError(
            f"no function {fn_name!r} in {mod_spec}") from None


def run_grpo(cfg: dict, config, trainer, state, manager, ref_params,
             elastic_agent=None):
    """The on-policy RLVR loop: refresh the serving engine's weights to
    the current policy each round, sample a group per prompt, score with
    the verifiable reward, update for ``steps_per_round`` steps.

    On-policy means ``old_logps`` from the freshly refreshed engine ARE
    the current policy — the clipped ratio only engages within a round
    as the weights move. ``ref_params`` is the frozen KL reference
    (the INITIAL weights, copied before any checkpoint restore)."""
    import jax
    import jax.numpy as jnp

    from ..serving.engine import GenerateConfig, InferenceEngine
    from ..serving.engine import init_mesh_serving
    from . import grpo as grpo_mod
    from .data import shard_batch

    data = cfg.get("data", {})
    if data.get("kind") != "prompts_jsonl":
        raise ValueError("mode=grpo needs data.kind='prompts_jsonl'")
    from ..tokenizer import load_tokenizer
    tok = load_tokenizer(data.get("tokenizer", ""))
    _check_tok_vocab(tok, config)
    prompts = []
    with open(data["path"]) as f:
        for line in f:
            if line.strip():
                p = json.loads(line)["prompt"]
                if isinstance(p, str):
                    if tok is None:
                        raise ValueError(
                            "text prompts need data.tokenizer")
                    p = tok.encode(p, add_bos=True)
                prompts.append(p)
    if not prompts:
        raise ValueError(f"no prompts in {data['path']}")
    reward_fn = resolve_reward(cfg.get("reward", ""))
    import inspect
    if "tokenizer" in inspect.signature(reward_fn).parameters:
        if tok is None:
            # fail before the model loads, not at the first reward call
            # mid-rollout
            raise ValueError(
                "reward function declares a tokenizer parameter but the "
                "config sets no data.tokenizer")
        # text-level rewards: fn(prompt_ids, completion_ids,
        # tokenizer=...) decodes with the corpus tokenizer
        import functools
        reward_fn = functools.partial(reward_fn, tokenizer=tok)

    gcfg = grpo_mod.GRPOConfig(**cfg.get("grpo", {}))
    roll = cfg.get("rollout", {})
    rounds = int(roll.get("rounds", 10))
    steps_per_round = int(roll.get("steps_per_round", 4))
    if rounds < 1 or steps_per_round < 1:
        # 0 steps would roll out + score for nothing (and hit an unbound
        # `loss` in the log line) — refuse up front like GRPOConfig does
        raise ValueError("rollout.rounds and rollout.steps_per_round "
                         "must be >= 1")
    max_new = int(roll.get("max_new_tokens", 64))
    max_len = int(roll.get("max_len", 1024))
    per_round = int(roll.get("prompts_per_round", 0)) or max(
        1, 8 // gcfg.group_size)
    if jax.process_count() > 1:
        raise ValueError("mode=grpo is single-host for now: the rollout "
                         "engine runs in-process on this host's chips")

    interval = manager.config.save_interval_steps if manager else 0
    last_saved = int(state.step)
    mesh = trainer.mesh
    engine = None
    # resume: rounds advance the step by exactly steps_per_round, so the
    # restored step IS the data cursor — start at the next round instead
    # of replaying the prompt list from round 0 (a resumed GRPO run must
    # roll out the same prompt schedule an uninterrupted one would)
    start_rnd = min(int(state.step) // steps_per_round, rounds)
    if start_rnd:
        log.info("grpo resume: %d rounds already done (step %d), "
                 "starting at round %d", start_rnd, int(state.step),
                 start_rnd + 1)
    for rnd in range(start_rnd, rounds):
        # device->host->device param refresh (training shards by fsdp,
        # the engine places its own way); building the engine ONCE keeps
        # its per-instance jit cache — only the buffers change per round
        host_params = jax.device_get(state.params)
        if engine is None:
            engine = InferenceEngine(
                config, host_params,
                GenerateConfig(max_len=max_len, temperature=1.0))
        else:
            engine.params, _ = init_mesh_serving(
                config, host_params, None, engine.mesh)
        batch_prompts = [prompts[(rnd * per_round + j) % len(prompts)]
                         for j in range(per_round)]
        batch = grpo_mod.rollout_batch(
            engine, batch_prompts, reward_fn, max_new, cfg=gcfg,
            seed=int(cfg.get("seed", 0)) + rnd)
        mean_reward = float(batch["rewards"].mean())
        ref_lp = grpo_mod.token_logps(
            config, ref_params, jnp.asarray(batch["tokens"]),
            jnp.asarray(batch["targets"]))
        train = {k: jnp.asarray(v) for k, v in batch.items()
                 if k != "rewards"}
        train["ref_logps"] = ref_lp
        sb = shard_batch(train, mesh)
        for _ in range(steps_per_round):
            state, loss = trainer.step(state, sb)
        log.info("grpo round %d/%d mean_reward %.4f loss %.4f",
                 rnd + 1, rounds, mean_reward, float(loss))
        if elastic_agent is not None:
            elastic_agent.poll(state)
        # host-side cadence: rounds advance step by steps_per_round, so
        # the manager's `step % interval` periodic gate would only fire
        # at lcm(steps_per_round, interval)
        if manager is not None and interval \
                and int(state.step) - last_saved >= interval:
            manager.save(state, force=True)
            last_saved = int(state.step)
    if manager is not None:
        manager.save(state, force=True)
        manager.wait_until_finished()
    return state


def _maybe_elastic_agent(manager):
    """ElasticCheckpointAgent when the operator injected job coordinates
    and an api-server is reachable; None otherwise (standalone runs)."""
    kind = os.environ.get("KUBEDL_JOB_KIND", "")
    ns = os.environ.get("KUBEDL_JOB_NAMESPACE", "")
    name = os.environ.get("KUBEDL_JOB_NAME", "")
    if not (kind and ns and name and manager):
        return None
    if not os.environ.get("KUBERNETES_SERVICE_HOST"):
        return None
    from ..core.kubeclient import ClusterConfig, KubeAPIServer
    from .checkpoint import ElasticCheckpointAgent
    api = KubeAPIServer(ClusterConfig.in_cluster())
    return ElasticCheckpointAgent(api, kind, ns, name, manager)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    cfg = load_config(argv)

    from ..runtime import bootstrap
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        # the image may pre-initialize jax on the accelerator platform
        # (sitecustomize); an explicit JAX_PLATFORMS (cpu smoke runs)
        # must still win after that
        bootstrap.pin_platform(want)
    info = bootstrap.rendezvous_from_env()
    if info is not None and info.is_distributed:
        bootstrap.initialize_distributed(info)

    import jax

    from ..models import llama, moe
    from ..parallel.mesh import MeshConfig, build_mesh
    from .trainer import TrainConfig, Trainer

    config, loaded_params = resolve_model(cfg)
    family = moe if isinstance(config, moe.MoEConfig) else llama
    mesh = build_mesh(MeshConfig(**cfg.get("mesh", {})))
    batch = int(cfg.get("batch", 8))
    seq = int(cfg.get("seq", min(getattr(config, "max_seq_len", 1024),
                                 1024)))
    steps = int(cfg.get("steps", 100))
    log.info("model=%s params=%.2fM mesh=%s mode=%s", cfg.get("model"),
             config.num_params / 1e6, dict(mesh.shape),
             cfg.get("mode", "pretrain"))

    if loaded_params is None:
        params = jax.jit(lambda k: family.init_params(config, k))(
            jax.random.PRNGKey(int(cfg.get("seed", 0))))
    else:
        params = loaded_params

    mode = cfg.get("mode", "pretrain")
    if cfg.get("lora") and mode not in ("pretrain", "sft"):
        # before any data files open: adapter tuning only composes with
        # the plain next-token losses
        raise ValueError("lora applies to mode pretrain/sft (dpo and "
                         "grpo tune full weights)")
    ppn = int(mesh.shape.get("pp", 1))
    pp_build = None
    if ppn > 1:
        # pipeline training: validated up front, before any data opens
        if mode not in ("pretrain", "sft"):
            raise ValueError("pp training supports mode pretrain/sft")
        if cfg.get("lora"):
            raise ValueError("pp does not compose with lora adapters")
        if family is not llama:
            raise ValueError("pp training supports the dense llama "
                             "family only (MoE scales with ep instead)")
        if mesh.shape.get("cp", 1) > 1 or mesh.shape.get("tp", 1) > 1:
            # the staged loss path shards stage params on pp only and
            # runs layers without mesh-aware sharding constraints —
            # cp/tp axes would silently replicate work instead of
            # activating ring/ulysses or tensor parallelism
            raise ValueError(
                "pp training composes with dp/fsdp only; set cp=1 and "
                "tp=1 (cp/tp inside pipeline stages is not wired yet)")

        def _kinds(d):
            if d.get("kind") == "mixture":
                return [s.get("kind") for s in d.get("sources", [])]
            return [d.get("kind", "synthetic")]
        if mode == "pretrain" and \
                "text" in _kinds(cfg.get("data", {"kind": "synthetic"})):
            # rejected BEFORE the corpus is tokenized/packed, not at the
            # first trainer step after minutes of data prep
            raise ValueError(
                "pp training does not support packed text batches yet — "
                "use data.kind tokens/synthetic (or mode sft)")
        pp_num_micro = int(cfg.get("pipeline", {})
                           .get("num_micro", 0)) or max(2, ppn)
        if batch % pp_num_micro:
            raise ValueError(
                f"batch {batch} not divisible by pipeline.num_micro="
                f"{pp_num_micro}")
        data_width = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        if (batch // pp_num_micro) % data_width:
            raise ValueError(
                f"microbatch {batch // pp_num_micro} rows must divide "
                f"the dp*fsdp width {data_width}")
    if cfg.get("export_hf_path"):
        # validate up front on ALL processes: the post-training check
        # only ran on rank 0 after hours of work, leaving other hosts
        # exiting 0 while rank 0 failed (ADVICE r4)
        from ..models import moe as _moe
        if isinstance(config, _moe.MoEConfig):
            raise ValueError(
                "export_hf_path: MoE configs have no HF mapping — drop "
                "export_hf_path or use a llama-family model")
    if mode == "evaluate":
        return run_evaluate(cfg, config, params, mesh)

    # the checkpoint manager opens BEFORE the data stream is built: the
    # saved data cursor (consumed-batch count) decides how far to
    # fast-forward the stream, so a resumed run continues at the exact
    # batch boundary instead of replaying the corpus head
    manager = None
    resume_skip = 0
    fingerprint = _data_fingerprint(cfg, mode, batch, seq)
    ck = cfg.get("checkpoint")
    if ck:
        from .checkpoint import CheckpointConfig, CheckpointManager
        manager = CheckpointManager(CheckpointConfig(**ck))
        cursor = manager.latest_data_state()
        if cursor:
            if cursor.get("fingerprint") == fingerprint:
                resume_skip = int(cursor.get("consumed_batches", 0))
                log.info("data cursor: resuming stream at batch %d",
                         resume_skip)
            else:
                log.warning(
                    "data cursor fingerprint mismatch (saved %s != "
                    "current %s); stream restarts at batch 0",
                    cursor.get("fingerprint"), fingerprint)

    batches = None
    if mode in ("pretrain", "sft"):
        if ppn > 1:
            loss_fn, pp_to, pp_from, pp_specs = build_pp_pretrain(
                config, mesh, pp_num_micro)
            pp_build = (pp_to, pp_from, pp_specs)
            log.info("pipeline training: pp=%d num_micro=%d (GPipe)",
                     ppn, pp_num_micro)
        else:
            def loss_fn(p, b):
                # packed text batches carry segment/position/mask
                # planes; token/synthetic batches don't — one closure
                # serves both
                return family.loss_fn(config, p, b["tokens"],
                                      b["targets"], mask=b.get("mask"),
                                      segment_ids=b.get("segment_ids"),
                                      positions=b.get("positions"),
                                      mesh=mesh)
        batches = (sft_stream(cfg, config, mesh, batch, seq,
                              skip=resume_skip)
                   if mode == "sft"
                   else data_stream(cfg, config, mesh, batch, seq,
                                    skip=resume_skip))
    elif mode == "dpo":
        import jax.numpy as jnp

        from . import dpo as dpo_mod
        dcfg = dpo_mod.DPOConfig(**cfg.get("dpo", {}))
        loss_fn = dpo_mod.make_dpo_loss_fn(config, dcfg, mesh=mesh)
        # the frozen DPO reference is the INITIAL weights — copy them:
        # init_state/step donate the originals into the train state
        ref_params = jax.tree.map(jnp.copy, params)
        batches = dpo_batches(cfg, config, ref_params, mesh, batch,
                              skip=resume_skip)
    elif mode == "grpo":
        import jax.numpy as jnp

        from . import grpo as grpo_mod
        loss_fn = grpo_mod.make_grpo_loss_fn(
            config, grpo_mod.GRPOConfig(**cfg.get("grpo", {})),
            mesh=mesh)
        # the frozen KL reference must be the INITIAL weights: copy
        # before init_state (donation) AND before checkpoint restore
        # (a resumed run must not rebase the anchor to mid-training)
        grpo_ref_params = jax.tree.map(jnp.copy, params)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    opt = cfg.get("optimizer", {})
    lora_cfg = cfg.get("lora")
    lora_state = None
    if lora_cfg:
        # adapter-only fine-tuning: the base stays frozen (closed over),
        # the optimizer state is adapter-sized, and export folds the
        # adapters back into dense weights (ops/lora.py). Mode
        # compatibility was validated before any data files opened.
        from ..ops import lora as lora_mod
        rank = int(lora_cfg.get("rank", 8))
        alpha = float(lora_cfg.get("alpha", 16.0))
        targets = tuple(lora_cfg.get("targets")
                        or lora_mod.DEFAULT_TARGETS)
        base_params = params
        adapters = lora_mod.init_adapters(
            base_params, rank=rank, targets=targets,
            key=jax.random.PRNGKey(int(cfg.get("seed", 0)) + 1))
        inner_loss = loss_fn

        def loss_fn(ad, b):  # noqa: F811 — deliberate adapter rebind
            return inner_loss(
                lora_mod.merge_params(base_params, ad, alpha=alpha), b)

        lora_state = (lora_mod, base_params, alpha)
        trainer = Trainer(loss_fn,
                          lora_mod.adapter_specs(
                              family.param_specs(config), adapters),
                          mesh, TrainConfig(**opt))
        state = trainer.init_state(adapters)
        log.info("lora: rank=%d alpha=%.1f targets=%s (%.2fM trainable)",
                 rank, alpha, ",".join(sorted(targets)),
                 sum(x.size for x in
                     jax.tree_util.tree_leaves(state.params)) / 1e6)
    elif pp_build is not None:
        pp_to, pp_from, pp_specs = pp_build
        params = pp_to(params)
        trainer = Trainer(loss_fn, pp_specs(params), mesh,
                          TrainConfig(**opt))
        state = trainer.init_state(params)
    else:
        trainer = Trainer(loss_fn, family.param_specs(config), mesh,
                          TrainConfig(**opt))
        state = trainer.init_state(params)

    if manager is not None:
        state = manager.restore_or(trainer.abstract_state(state),
                                   lambda: state)
        if manager.latest_step():
            log.info("resumed from checkpoint step %s",
                     manager.latest_step())

    from .data import CountingIterator
    data_state_fn = None
    if manager is not None and isinstance(batches, CountingIterator):
        def data_state_fn():
            return {"consumed_batches": batches.consumed,
                    "fingerprint": fingerprint}

    if mode == "grpo":
        state = run_grpo(cfg, config, trainer, state, manager,
                         grpo_ref_params,
                         elastic_agent=_maybe_elastic_agent(manager))
    else:
        params_of = None
        if lora_state is not None:
            lmod, lbase, lalpha = lora_state
            params_of = (lambda st: lmod.merge_params(
                lbase, st.params, alpha=lalpha))
        elif pp_build is not None:
            # eval runs the flat (non-staged) forward on restacked params
            params_of = (lambda st: pp_build[1](st.params))
        ev_every, ev_fn = ((0, None) if mode == "dpo"
                           else build_eval_fn(cfg, config, mesh, batch,
                                              seq, params_of=params_of))
        agent = _maybe_elastic_agent(manager)
        if agent is not None:
            agent.data_state_fn = data_state_fn
        state = trainer.fit(state, batches, num_steps=steps,
                            log_every=int(cfg.get("log_every", 10)),
                            checkpoint_manager=manager,
                            elastic_agent=agent,
                            eval_every=ev_every, eval_fn=ev_fn,
                            data_state_fn=data_state_fn)

    export = cfg.get("export_path") or os.environ.get("KUBEDL_MODEL_PATH")
    if export:
        export_params = state.params
        if pp_build is not None:
            # restack [pp, L/pp, ...] stages to the flat [L, ...] layout
            # every other consumer (serving, HF export) reads
            export_params = pp_build[1](export_params)
        if lora_state is not None:
            # fold trained adapters into dense weights: the exported
            # artifact serves with zero adapter overhead and composes
            # with int8/int4 quantization
            lmod, lbase, lalpha = lora_state
            export_params = lmod.merge_to_dense(lbase, state.params,
                                                alpha=lalpha)
        # fsdp-sharded params span non-addressable devices on multi-host
        # runs: device_get on process 0 alone would raise. All hosts
        # join the allgather; only process 0 touches the filesystem.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            host_params = multihost_utils.process_allgather(export_params)
        else:
            host_params = jax.device_get(export_params)
        if jax.process_index() == 0:
            from ..models.io import save_model
            save_model(config, host_params, export)
            log.info("exported model to %s", export)
            hf_out = cfg.get("export_hf_path")
            if hf_out:
                # straight to HuggingFace format (only llama-family
                # cores have an HF analog; MoE configs raise)
                from ..models import moe
                if isinstance(config, moe.MoEConfig):
                    raise ValueError(
                        "export_hf_path: MoE configs have no HF mapping")
                from ..models.convert import save_hf_checkpoint
                save_hf_checkpoint(config, host_params, hf_out)
                log.info("exported HF checkpoint to %s", hf_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
