"""Training loop: sharded train step, optimizer, checkpointing, data."""

from .trainer import TrainConfig, Trainer, TrainState  # noqa: F401
