"""Checkpoint/resume: orbax-backed state persistence + the elastic
2-phase protocol's training side.

The reference operator implements checkpoint *coordination* only — the
versioned annotations ``ckpt-requested-version`` / ``ckpt-completed-version``
driven between controller and AIMaster (``controllers/pytorch/
elastic_scale.go:35-39,118-182``) — and leaves byte-level checkpointing to
the training container. This framework ships both halves:

* :class:`CheckpointManager` — orbax ``CheckpointManager`` wrapper that
  saves/restores the sharded :class:`~kubedl_tpu.train.trainer.TrainState`.
  Restore takes the *target mesh's* shardings, so a checkpoint written on
  one world size resumes on another (orbax reshards on load) — the
  mechanism elastic scaling relies on.
* :class:`ElasticCheckpointAgent` — the in-container AIMaster analog: it
  watches the job's ``ckpt-requested-version`` annotation, saves, and
  acknowledges via ``ckpt-completed-version``, closing the loop with the
  operator's elastic controller (``kubedl_tpu.controllers.workloads.pytorch``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import orbax.checkpoint as ocp

from ..api import common as c
from ..core import meta as m

log = logging.getLogger("kubedl_tpu.checkpoint")


@dataclass
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 0     # 0: only explicit save() calls
    max_to_keep: int = 3
    async_save: bool = True


class CheckpointManager:
    """Thin orbax wrapper pinned to the framework's TrainState layout."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        self._mngr = ocp.CheckpointManager(
            config.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                save_interval_steps=max(config.save_interval_steps, 1),
                enable_async_checkpointing=config.async_save,
            ))

    def save(self, state, force: bool = False, step: Optional[int] = None,
             periodic: bool = False, data_state: Optional[dict] = None) -> bool:
        """Save at ``state.step``.

        Three call shapes, disambiguated explicitly (the old force-only
        API made ``save_interval_steps=0`` silently swallow explicit
        ``save()`` calls — ADVICE r1):

        * ``periodic=True`` — the trainer's per-step call: saves only on
          interval boundaries; ``save_interval_steps=0`` disables it.
        * ``force=True`` — always saves (final/preempt checkpoints).
        * plain ``save(state)`` — an explicit request: always saves,
          regardless of the interval setting.

        Pass ``step`` (host-side counter) to skip the per-call
        ``device_get`` sync — fit() does, so non-saving steps cost one
        modulo instead of a device round-trip. A step already on disk is a
        no-op (the final forced save after an interval save of it).

        ``data_state`` is the host-side data cursor (JSON-able dict —
        consumed-batch count + source fingerprint): it rides the same
        orbax step as a ``data`` item so model state and data position
        can never diverge (VERDICT r4 next #1 — without it a resumed
        pretrain silently replays the corpus head)."""
        if periodic and not force:
            if self.config.save_interval_steps <= 0:
                return False  # interval saves disabled
            if step is None:
                step = int(jax.device_get(state.step))
            if step % self.config.save_interval_steps:
                return False  # cheap early-out before touching orbax
        if step is None:
            step = int(jax.device_get(state.step))
        if step in (self._mngr.all_steps() or []):
            return False
        items = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            items["data"] = ocp.args.JsonSave(data_state)
        # orbax applies its own interval gate to non-forced saves; explicit
        # (non-periodic) requests must bypass it or an off-interval step
        # would be silently skipped
        saved = self._mngr.save(step, args=ocp.args.Composite(**items),
                                force=force or not periodic)
        if saved:
            log.info("checkpoint saved at step %d", step)
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, abstract_state, step: Optional[int] = None):
        """Restore ``step`` (default latest) into the given abstract state
        — a pytree of ``jax.ShapeDtypeStruct`` with *target* shardings, so
        world-size changes reshard transparently."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        if "state" not in self._items(step):
            # checkpoint written by the pre-cursor layout (bare
            # StandardSave, no named items): restore it the old way
            # instead of crashing every pre-upgrade resume
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract_state))
        return self._mngr.restore(
            step, args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state))).state

    def _items(self, step: int) -> set:
        """Named items saved at ``step`` (empty set for the legacy
        single-item layout or unreadable metadata)."""
        try:
            return set(self._mngr.item_metadata(step).keys())
        except Exception:  # noqa: BLE001 — metadata shape varies by layout
            return set()

    def latest_data_state(self, step: Optional[int] = None) -> Optional[dict]:
        """The data cursor saved alongside ``step`` (default latest), or
        None when the step has no ``data`` item (pre-cursor checkpoints,
        bench runs). Cheap — reads one small JSON file, no arrays — so
        the entrypoint can learn the resume offset BEFORE it builds the
        data stream."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        if "data" not in self._items(step):
            return None
        return self._mngr.restore(
            step, args=ocp.args.Composite(data=ocp.args.JsonRestore())).data

    def restore_or(self, abstract_state, init_fn: Callable):
        """Resume from the latest checkpoint, else initialize fresh — the
        one-liner every elastic-restartable training loop needs."""
        restored = self.restore(abstract_state)
        if restored is not None:
            log.info("resumed from checkpoint step %d",
                     int(jax.device_get(restored.step)))
            return restored
        return init_fn()

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()


def abstract_state_like(state, mesh, param_specs, opt_specs, step_spec=None):
    """Build the abstract restore target for ``state`` on ``mesh``:
    ShapeDtypeStructs carrying the *target* NamedShardings."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def abstr(x, sharding):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    from .trainer import TrainState
    step_sh = NamedSharding(mesh, step_spec or P())
    return TrainState(
        step=abstr(state.step, step_sh),
        params=jax.tree.map(
            lambda x, s: abstr(x, NamedSharding(mesh, s)),
            state.params, param_specs),
        opt_state=jax.tree.map(
            lambda x, s: abstr(x, NamedSharding(mesh, s)),
            state.opt_state, opt_specs),
    )


class ElasticCheckpointAgent:
    """Training-side half of the operator's 2-phase elastic protocol.

    The controller requests a checkpoint by bumping
    ``kubedl.io/ckpt-requested-version`` on the job (the generation it
    wants to resize to); this agent saves and acknowledges by writing the
    same version into ``kubedl.io/ckpt-completed-version``, after which the
    controller deletes victims and restarts the world
    (``elastic_scale.go:136-160`` behavior contract).
    """

    def __init__(self, api, kind: str, namespace: str, name: str,
                 manager: CheckpointManager, data_state_fn=None):
        self.api = api
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.manager = manager
        #: optional () -> dict supplying the data cursor, so an elastic
        #: checkpoint resumes its stream exactly like a periodic one
        self.data_state_fn = data_state_fn
        self._acked = 0

    def poll(self, state) -> bool:
        """Check for an outstanding checkpoint request; save + ack if one
        is pending. Returns True when a checkpoint was taken."""
        job = self.api.try_get(self.kind, self.namespace, self.name)
        if job is None:
            return False
        ann = m.annotations(job)
        requested = int(ann.get(c.ANNOTATION_CKPT_REQUESTED_VERSION, 0) or 0)
        completed = int(ann.get(c.ANNOTATION_CKPT_COMPLETED_VERSION, 0) or 0)
        if requested <= max(completed, self._acked):
            return False
        self.manager.save(state, force=True,
                          data_state=(self.data_state_fn()
                                      if self.data_state_fn else None))
        self.manager.wait_until_finished()  # ack only after bytes are down
        self.api.patch_merge(self.kind, self.namespace, self.name, {
            "metadata": {"annotations": {
                c.ANNOTATION_CKPT_COMPLETED_VERSION: str(requested)}}})
        self._acked = requested
        log.info("elastic checkpoint v%d taken and acknowledged", requested)
        return True
