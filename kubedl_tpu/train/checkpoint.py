"""Checkpoint/resume: orbax-backed state persistence + the elastic
2-phase protocol's training side.

The reference operator implements checkpoint *coordination* only — the
versioned annotations ``ckpt-requested-version`` / ``ckpt-completed-version``
driven between controller and AIMaster (``controllers/pytorch/
elastic_scale.go:35-39,118-182``) — and leaves byte-level checkpointing to
the training container. This framework ships both halves:

* :class:`CheckpointManager` — orbax ``CheckpointManager`` wrapper that
  saves/restores the sharded :class:`~kubedl_tpu.train.trainer.TrainState`.
  Restore takes the *target mesh's* shardings, so a checkpoint written on
  one world size resumes on another (orbax reshards on load) — the
  mechanism elastic scaling relies on.
* :class:`ElasticCheckpointAgent` — the in-container AIMaster analog: it
  watches the job's ``ckpt-requested-version`` annotation, saves, and
  acknowledges via ``ckpt-completed-version``, closing the loop with the
  operator's elastic controller (``kubedl_tpu.controllers.workloads.pytorch``).
"""

from __future__ import annotations

import logging
import os
import queue
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import orbax.checkpoint as ocp

from ..api import common as c
from ..core import meta as m
from ..core.apiserver import ApiError

log = logging.getLogger("kubedl_tpu.checkpoint")


@dataclass
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 0     # 0: only explicit save() calls
    max_to_keep: int = 3
    async_save: bool = True


class CheckpointManager:
    """Thin orbax wrapper pinned to the framework's TrainState layout."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        self._mngr = ocp.CheckpointManager(
            config.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                save_interval_steps=max(config.save_interval_steps, 1),
                enable_async_checkpointing=config.async_save,
            ))

    def save(self, state, force: bool = False, step: Optional[int] = None,
             periodic: bool = False, data_state: Optional[dict] = None) -> bool:
        """Save at ``state.step``.

        Three call shapes, disambiguated explicitly (the old force-only
        API made ``save_interval_steps=0`` silently swallow explicit
        ``save()`` calls — ADVICE r1):

        * ``periodic=True`` — the trainer's per-step call: saves only on
          interval boundaries; ``save_interval_steps=0`` disables it.
        * ``force=True`` — always saves (final/preempt checkpoints).
        * plain ``save(state)`` — an explicit request: always saves,
          regardless of the interval setting.

        Pass ``step`` (host-side counter) to skip the per-call
        ``device_get`` sync — fit() does, so non-saving steps cost one
        modulo instead of a device round-trip. A step already on disk is a
        no-op (the final forced save after an interval save of it).

        ``data_state`` is the host-side data cursor (JSON-able dict —
        consumed-batch count + source fingerprint): it rides the same
        orbax step as a ``data`` item so model state and data position
        can never diverge (VERDICT r4 next #1 — without it a resumed
        pretrain silently replays the corpus head)."""
        if periodic and not force:
            if self.config.save_interval_steps <= 0:
                return False  # interval saves disabled
            if step is None:
                step = int(jax.device_get(state.step))
            if step % self.config.save_interval_steps:
                return False  # cheap early-out before touching orbax
        if step is None:
            step = int(jax.device_get(state.step))
        if step in (self._mngr.all_steps() or []):
            return False
        items = {"state": ocp.args.StandardSave(state)}
        if data_state is not None:
            items["data"] = ocp.args.JsonSave(data_state)
        # orbax applies its own interval gate to non-forced saves; explicit
        # (non-periodic) requests must bypass it or an off-interval step
        # would be silently skipped
        saved = self._mngr.save(step, args=ocp.args.Composite(**items),
                                force=force or not periodic)
        if saved:
            log.info("checkpoint saved at step %d", step)
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, abstract_state, step: Optional[int] = None):
        """Restore ``step`` (default latest) into the given abstract state
        — a pytree of ``jax.ShapeDtypeStruct`` with *target* shardings, so
        world-size changes reshard transparently."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        if "state" not in self._items(step):
            # checkpoint written by the pre-cursor layout (bare
            # StandardSave, no named items): restore it the old way
            # instead of crashing every pre-upgrade resume
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract_state))
        return self._mngr.restore(
            step, args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state))).state

    def _items(self, step: int) -> set:
        """Named items saved at ``step`` (empty set for the legacy
        single-item layout or unreadable metadata)."""
        try:
            return set(self._mngr.item_metadata(step).keys())
        except Exception:  # noqa: BLE001 — metadata shape varies by layout
            return set()

    def latest_data_state(self, step: Optional[int] = None) -> Optional[dict]:
        """The data cursor saved alongside ``step`` (default latest), or
        None when the step has no ``data`` item (pre-cursor checkpoints,
        bench runs). Cheap — reads one small JSON file, no arrays — so
        the entrypoint can learn the resume offset BEFORE it builds the
        data stream."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        if "data" not in self._items(step):
            return None
        return self._mngr.restore(
            step, args=ocp.args.Composite(data=ocp.args.JsonRestore())).data

    def restore_or(self, abstract_state, init_fn: Callable):
        """Resume from the latest checkpoint, else initialize fresh — the
        one-liner every elastic-restartable training loop needs."""
        restored = self.restore(abstract_state)
        if restored is not None:
            log.info("resumed from checkpoint step %d",
                     int(jax.device_get(restored.step)))
            return restored
        return init_fn()

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()


class CheckpointTiers:
    """Host-local fast tier + object-store durable tier (docs/elastic.md
    "Async multi-tier checkpointing").

    The local tier is the orbax directory the trainer saves into
    (device→host already overlapped with compute by orbax's async
    checkpointing); this class adds the host→object-store leg on a
    background worker so neither tier ever blocks a training step, and
    the *nearest*-tier read path for restore.

    Upload contract (the WAL-snapshot tmp+rename discipline): a step is
    copied into ``<object_dir>/<step>.uploading`` and atomically renamed
    to ``<object_dir>/<step>`` only when every byte is down — a torn
    upload (crash mid-copy) leaves a ``.uploading`` orphan that the read
    path NEVER serves and the next publisher sweeps. The object tier
    therefore never serves a partial checkpoint.
    """

    UPLOADING_SUFFIX = ".uploading"

    def __init__(self, local_dir: str, object_dir: str,
                 ready: Optional[Callable] = None,
                 poll_interval_s: float = 0.02,
                 ready_timeout_s: float = 120.0):
        self.local_dir = str(local_dir)
        self.object_dir = str(object_dir)
        os.makedirs(self.object_dir, exist_ok=True)
        #: ``ready(step) -> bool``: whether the local tier has finalized
        #: the step (orbax renames its tmp dir into place on finalize,
        #: so directory existence is the default readiness signal)
        self._ready = ready or self._local_finalized
        self._poll = float(poll_interval_s)
        self._ready_timeout = float(ready_timeout_s)
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        #: steps whose upload completed (observability / tests)
        self.uploaded: list = []
        #: torn ``.uploading`` orphans swept before uploads
        self.swept = 0
        #: per-step upload attempts so far (bounded retries)
        self._attempts: dict = {}
        #: steps whose upload exhausted its retries — ``flush`` raises
        #: on these instead of reporting a durable tier it never wrote
        self.failed: list = []
        self.max_attempts = 3

    # -- read side --------------------------------------------------------

    def _step_dirs(self, root: str) -> list:
        try:
            names = os.listdir(root)
        except OSError:
            return []
        out = []
        for n in names:
            if n.endswith(self.UPLOADING_SUFFIX):
                continue               # torn upload: never served
            try:
                out.append(int(n))
            except ValueError:
                continue
        return sorted(out)

    def local_steps(self) -> list:
        return [s for s in self._step_dirs(self.local_dir)
                if self._local_finalized(s)]

    def object_steps(self) -> list:
        return self._step_dirs(self.object_dir)

    def nearest_step(self) -> Optional[int]:
        """Newest step across both tiers (restore reads the nearest copy
        of it: local when present, object-store otherwise)."""
        steps = set(self.local_steps()) | set(self.object_steps())
        return max(steps) if steps else None

    def localize(self, step: int) -> bool:
        """Ensure ``step`` exists in the local tier, downloading from
        the object tier when the local copy is gone (the
        fresh-host-after-eviction path). Returns False when neither
        tier has it."""
        if step in self.local_steps():
            return True
        if step not in self.object_steps():
            return False
        src = os.path.join(self.object_dir, str(step))
        tmp = os.path.join(self.local_dir,
                           f"{step}{self.UPLOADING_SUFFIX}")
        dst = os.path.join(self.local_dir, str(step))
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(self.local_dir, exist_ok=True)
        shutil.copytree(src, tmp)
        os.replace(tmp, dst)
        log.info("checkpoint step %d localized from the object tier",
                 step)
        return True

    def localize_latest(self) -> Optional[int]:
        """Pull the newest object-tier step missing locally — run before
        opening the orbax manager so restore sees the nearest tier."""
        newest = self.nearest_step()
        if newest is not None and self.localize(newest):
            return newest
        return None

    # -- write side -------------------------------------------------------

    def _local_finalized(self, step: int) -> bool:
        """Orbax finalizes a step by renaming its tmp dir into place, so
        a plain directory named ``<step>`` IS the commit marker."""
        return os.path.isdir(os.path.join(self.local_dir, str(step)))

    def publish(self, step: int) -> None:
        """Enqueue the host→object-store upload of ``step`` on the
        background worker (never blocks the training step)."""
        self._ensure_worker()
        self._queue.put(int(step))

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="ckpt-upload", daemon=True)
                self._worker.start()

    def _drain(self) -> None:
        while True:
            step = self._queue.get()
            try:
                if step is None:
                    return
                self._upload(step)
            except Exception as e:  # noqa: BLE001 — a failed upload
                # must not kill the worker; retry bounded, and if the
                # step keeps failing record it so flush() surfaces the
                # hole instead of reporting a durable tier that was
                # never written
                n = self._attempts.get(step, 0) + 1
                self._attempts[step] = n
                if n < self.max_attempts:
                    log.warning("checkpoint upload of step %s failed "
                                "(attempt %d/%d, will retry): %s",
                                step, n, self.max_attempts, e)
                    time.sleep(self._poll)
                    self._queue.put(step)
                else:
                    log.error("checkpoint upload of step %s failed "
                              "%d times; the object tier is MISSING "
                              "this step: %s", step, n, e)
                    self.failed.append(step)
            finally:
                self._queue.task_done()

    def _upload(self, step: int) -> None:
        deadline = time.monotonic() + self._ready_timeout
        while not self._ready(step):
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"step {step} never finalized in the local tier")
            time.sleep(self._poll)
        dst = os.path.join(self.object_dir, str(step))
        if os.path.isdir(dst):
            return                      # already uploaded (idempotent)
        tmp = dst + self.UPLOADING_SUFFIX
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)          # torn upload from a prior crash
            self.swept += 1
        shutil.copytree(os.path.join(self.local_dir, str(step)), tmp)
        os.replace(tmp, dst)            # atomic: readers see all or nothing
        self.uploaded.append(step)
        log.info("checkpoint step %d published to the object tier", step)

    def flush(self, timeout_s: float = 120.0) -> None:
        """Wait until every enqueued upload has landed; raise when any
        step exhausted its retries — a clean return MEANS the object
        tier holds every published step (the contract restore-on-a-
        fresh-host depends on)."""
        deadline = time.monotonic() + timeout_s
        while not self._queue.empty() or self._queue.unfinished_tasks:
            if time.monotonic() >= deadline:
                raise TimeoutError("checkpoint uploads did not drain")
            time.sleep(self._poll)
        if self.failed:
            raise RuntimeError(
                f"object-tier upload failed permanently for step(s) "
                f"{sorted(set(self.failed))}; the durable tier is "
                f"missing them")

    def close(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(None)
            self._worker.join(timeout=10.0)


class TieredCheckpointManager(CheckpointManager):
    """:class:`CheckpointManager` + the object-store tier: every
    completed save is published to ``object_dir`` on the background
    worker, and construction pulls the newest object-tier step down
    first, so ``restore``/``latest_step`` read the nearest tier even on
    a host whose local disk started empty (the spot-eviction resume
    path, docs/elastic.md)."""

    def __init__(self, config: CheckpointConfig, object_dir: str,
                 upload: bool = True):
        self.tiers = CheckpointTiers(config.directory, object_dir)
        self.tiers.localize_latest()
        super().__init__(config)
        self._upload_enabled = bool(upload)

    def save(self, state, force: bool = False, step: Optional[int] = None,
             periodic: bool = False,
             data_state: Optional[dict] = None) -> bool:
        saved = super().save(state, force=force, step=step,
                             periodic=periodic, data_state=data_state)
        if saved and self._upload_enabled:
            if step is None:
                step = int(jax.device_get(state.step))
            self.tiers.publish(step)
        return saved

    def wait_until_finished(self) -> None:
        super().wait_until_finished()
        self.tiers.flush()

    def close(self) -> None:
        try:
            self.tiers.flush()
        except (TimeoutError, RuntimeError) as e:
            log.warning("closing with unfinished checkpoint uploads: %s",
                        e)
        self.tiers.close()
        super().close()


def abstract_state_like(state, mesh, param_specs, opt_specs, step_spec=None):
    """Build the abstract restore target for ``state`` on ``mesh``:
    ShapeDtypeStructs carrying the *target* NamedShardings."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    def abstr(x, sharding):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    from .trainer import TrainState
    step_sh = NamedSharding(mesh, step_spec or P())
    return TrainState(
        step=abstr(state.step, step_sh),
        params=jax.tree.map(
            lambda x, s: abstr(x, NamedSharding(mesh, s)),
            state.params, param_specs),
        opt_state=jax.tree.map(
            lambda x, s: abstr(x, NamedSharding(mesh, s)),
            state.opt_state, opt_specs),
    )


class ElasticCheckpointAgent:
    """Training-side half of the operator's 2-phase elastic protocol.

    The controller requests a checkpoint by bumping
    ``kubedl.io/ckpt-requested-version`` on the job (the generation it
    wants to resize to); this agent saves and acknowledges by writing the
    same version into ``kubedl.io/ckpt-completed-version``, after which the
    controller deletes victims and restarts the world
    (``elastic_scale.go:136-160`` behavior contract).
    """

    def __init__(self, api, kind: str, namespace: str, name: str,
                 manager: CheckpointManager, data_state_fn=None):
        self.api = api
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.manager = manager
        #: optional () -> dict supplying the data cursor, so an elastic
        #: checkpoint resumes its stream exactly like a periodic one
        self.data_state_fn = data_state_fn
        self._acked = 0

    def poll(self, state) -> bool:
        """Check for an outstanding checkpoint request; save + ack if one
        is pending. Returns True when a checkpoint was taken."""
        job = self.api.try_get(self.kind, self.namespace, self.name)
        if job is None:
            return False
        ann = m.annotations(job)
        requested = int(ann.get(c.ANNOTATION_CKPT_REQUESTED_VERSION, 0) or 0)
        completed = int(ann.get(c.ANNOTATION_CKPT_COMPLETED_VERSION, 0) or 0)
        if requested <= max(completed, self._acked):
            return False
        self.manager.save(state, force=True,
                          data_state=(self.data_state_fn()
                                      if self.data_state_fn else None))
        self.manager.wait_until_finished()  # ack only after bytes are down
        acked = self._ack(requested)
        if acked is None:
            # the ack write could not land this poll: leave _acked
            # untouched so the NEXT poll retries the acknowledgement
            # (the checkpoint itself is down; re-saving is a no-op)
            log.warning("elastic checkpoint v%d saved but the ack write "
                        "did not land; will retry", requested)
            return True
        self._acked = acked
        log.info("elastic checkpoint v%d taken and acknowledged", acked)
        return True

    def _ack(self, requested: int) -> Optional[int]:
        """Write ``ckpt-completed-version`` with the standard conflict
        re-read/re-apply retry (docs/elastic.md): under chaos 409s the
        bare patch raced the controller's own annotation writes — a
        dropped ack stalls the whole reconfiguration, with the
        controller waiting on an acknowledgement the agent believes it
        sent. Each retry RE-READS the job: a newer requested version
        observed mid-retry is acknowledged instead (the checkpoint just
        taken covers it — state only moves between polls)."""
        for _ in range(8):
            try:
                self.api.patch_merge(self.kind, self.namespace, self.name, {
                    "metadata": {"annotations": {
                        c.ANNOTATION_CKPT_COMPLETED_VERSION:
                            str(requested)}}})
                return requested
            except ApiError as e:   # Conflict / transient 5xx / timeout
                job = self.api.try_get(self.kind, self.namespace,
                                       self.name)
                if job is None:
                    return None
                ann = m.annotations(job)
                newer = int(
                    ann.get(c.ANNOTATION_CKPT_REQUESTED_VERSION, 0) or 0)
                requested = max(requested, newer)
                log.warning("elastic ack conflicted (%s); re-applying "
                            "as v%d", e, requested)
                continue
        return None
