"""Evaluation harness: perplexity and log-likelihood scoring.

Two primitives cover the standard LM evaluation surface:

* ``perplexity`` — mean next-token NLL (and its exp) over a batch
  stream, with one jitted eval step reused across batches. Runs the
  exact training loss path (chunked LM-head scan, family dispatch incl.
  MoE), so eval numbers are comparable to training loss by construction.
* ``loglikelihood_ranks`` — per-option summed log P(continuation |
  prompt) for multiple-choice scoring (the lm-eval-harness
  "loglikelihood" contract): render each (prompt, option) pair with
  continuation-only masking, score with the chunked per-row scan,
  argmax per question.

No reference analog: the reference operator (mental2008/kubedl) has no
compute stack (SURVEY.md §2); this is beyond-parity tooling for the
in-tree TPU training path. TPU-first: one compiled step per (rows, seq)
shape — options pad to a shared 128-aligned length so every question
reuses the same executable.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.loss import chunked_token_nll
from .scoring import hidden_and_head, render_rows


def make_row_nll_fn(config, mesh=None, chunk: int = 512):
    """Jitted ``(params, batch) -> per-row summed NLL [b]`` over
    ``{tokens, targets[, mask]}`` — the one compiled step both
    evaluators share."""

    def rows(params, batch):
        x, head, _ = hidden_and_head(config, params, batch["tokens"],
                                     mesh)
        return chunked_token_nll(x, head, batch["targets"],
                                 mask=batch.get("mask"), chunk=chunk,
                                 logit_softcap=config.logit_softcap)

    return jax.jit(rows)


def perplexity(config, params, batches: Iterable[dict], mesh=None,
               chunk: int = 512, max_batches: Optional[int] = None):
    """Corpus perplexity over ``batches`` of ``{tokens, targets[, mask]}``.

    Returns ``{nll, perplexity, tokens}`` (token count covers unmasked
    targets only). One compile per distinct batch shape."""
    import itertools

    row_nll = make_row_nll_fn(config, mesh, chunk)
    total = 0.0
    count = 0.0
    if max_batches is not None:
        # islice, not a loop-break: a break after enumerate would pull
        # (and shard, and transfer) one extra batch just to discard it
        batches = itertools.islice(batches, max_batches)
    for batch in batches:
        total += float(jnp.sum(row_nll(params, batch)))
        mask = batch.get("mask")
        count += (float(jnp.sum(mask)) if mask is not None
                  else batch["tokens"].shape[0] * batch["tokens"].shape[1])
    if count == 0:
        raise ValueError("no target tokens evaluated")
    nll = total / count
    return {"nll": nll, "perplexity": math.exp(min(nll, 80.0)),
            "tokens": int(count)}


def _render_options(prompt, options, pad_to: int, pad_id: int):
    """Each option row renders through the shared completion layout."""
    rows = [list(prompt) + list(opt) for opt in options]
    b = render_rows(rows, [len(prompt)] * len(options), pad_id,
                    pad_to=pad_to)
    return {k: jnp.asarray(v) for k, v in b.items()}


def loglikelihood_ranks(config, params, questions: Sequence[dict],
                        mesh=None, chunk: int = 512, pad_id: int = 0,
                        length_normalize: bool = False):
    """Score multiple-choice questions by continuation log-likelihood.

    ``questions``: each ``{"prompt": [ids], "options": [[ids], ...]}``
    (prompt and every option non-empty). Returns per question
    ``{"logps": [...], "choice": argmax}``; ``length_normalize`` divides
    each option's logp by its token count (lm-eval-harness "acc_norm").
    Questions with the same option count share one executable."""
    if not questions:
        return []
    for q in questions:
        if len(q["prompt"]) < 1:
            raise ValueError("prompt must include at least one token")
        if any(len(o) < 1 for o in q["options"]):
            raise ValueError("options must be non-empty")
    longest = max(len(q["prompt"]) + len(o)
                  for q in questions for o in q["options"])
    pad_to = -(-longest // 128) * 128
    row_nll = make_row_nll_fn(config, mesh, chunk)

    out = []
    for q in questions:
        batch = _render_options(q["prompt"], q["options"], pad_to, pad_id)
        logps = -np.asarray(row_nll(params, batch), np.float32)
        if length_normalize:
            logps = logps / np.array([len(o) for o in q["options"]],
                                     np.float32)
        out.append({"logps": [float(v) for v in logps],
                    "choice": int(np.argmax(logps))})
    return out
