"""Shared scoring primitives for sequence-level objectives.

Every post-training objective (DPO pairs, GRPO rollouts, eval options)
needs the same two pieces; they live here — neutral ground — so
eval-only or RL-only users don't transitively depend on the DPO module:

* :func:`hidden_and_head` — family-dispatched forward to final hidden
  states + densified LM head (+ MoE router aux loss), the front half of
  every chunked logprob scan;
* :func:`render_rows` — the one prompt/completion batch layout
  (right-padded 128-aligned tokens, left-shifted targets,
  completion-only mask) with the pl-1 mask arithmetic validated once
  for all callers.
"""

from __future__ import annotations

from typing import Optional

from ..models import llama


def _hidden(config, params, tokens, mesh):
    """Family dispatch: final hidden states + router aux loss (0 for
    dense families; MoEConfig subclasses LlamaConfig so isinstance picks
    the sparse path)."""
    from ..models import moe
    if isinstance(config, moe.MoEConfig):
        return moe.forward_hidden(config, params, tokens, mesh=mesh)
    return llama.forward_hidden(config, params, tokens, mesh=mesh), 0.0


def hidden_and_head(config, params, tokens, mesh=None):
    """Final hidden states, densified LM head, and the MoE router aux
    loss (0 for dense families)."""
    from ..ops.quant import to_dense
    x, aux = _hidden(config, params, tokens, mesh)
    head = to_dense(llama._lm_head(config, params), config.dtype)
    return x, head, aux


def render_rows(rows, prompt_lens, pad_id: int = 0,
                pad_to: Optional[int] = None):
    """Render tokenized prompt+completion rows into the one batch layout
    every sequence-level objective shares: right-padded ``tokens``
    (128-aligned), left-shifted ``targets``, and a ``mask`` covering
    completion targets only (target index ``pl-1`` predicts the first
    completion token).

    The pl-1 arithmetic silently zeroes the mask when a prompt is empty
    (wraps to -1) or a completion is empty — both rejected here, once,
    for all callers (DPO pairs, GRPO rollouts, eval options)."""
    import numpy as np

    n = len(rows)
    if len(prompt_lens) != n:
        raise ValueError("rows and prompt_lens must have equal length")
    if any(pl < 1 for pl in prompt_lens):
        raise ValueError("prompt_lens must be >= 1 (include BOS)")
    if any(pl >= len(r) for pl, r in zip(prompt_lens, rows)):
        raise ValueError("every row needs completion tokens past its "
                         "prompt_len")
    longest = max(len(r) for r in rows)
    s = pad_to or -(-longest // 128) * 128
    if longest > s:
        raise ValueError(f"pad_to={s} shorter than longest row {longest}")
    toks = np.full((n, s), pad_id, np.int32)
    tgts = np.full((n, s), pad_id, np.int32)
    mask = np.zeros((n, s), np.float32)
    for i, (row, pl) in enumerate(zip(rows, prompt_lens)):
        row = np.asarray(row, np.int32)
        toks[i, :len(row)] = row
        tgts[i, :len(row) - 1] = row[1:]
        mask[i, pl - 1:len(row) - 1] = 1.0
    return {"tokens": toks, "targets": tgts, "mask": mask}
