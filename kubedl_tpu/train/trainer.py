"""Sharded training loop for the model zoo.

The TPU-native training payload of a PyTorchJob/JAXJob slice (BASELINE
config 3: Llama SPMD fine-tune on v5p-32). Design:

* one jitted ``train_step`` with donated state: params/optimizer sharded by
  the model's logical specs over the (dp, fsdp, cp, tp) mesh, batch sharded
  over (dp×fsdp, cp); XLA/GSPMD inserts all collectives;
* optimizer state in float32 (master copy) while live weights stay bf16 —
  update applies in fp32 then casts, the standard mixed-precision recipe;
* gradient accumulation via an inner ``lax.scan`` over microbatches;
* checkpoint/restore via Orbax when available (GCS-ready), with a
  numpy-on-disk fallback so the loop has zero hard deps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..parallel.sharding import tree_shardings


@dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    decay_steps: int = 10_000
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    accum_steps: int = 1
    seed: int = 0
    #: XProf trace directory ("" = off). Traces land under
    #: <profile_dir>/plugins/profile, which the TensorBoard subsystem
    #: (platform/tensorboard.py) serves straight from the job's logdir —
    #: the operator-level profiling convention from SURVEY §5.
    profile_dir: str = ""
    #: trace window: [profile_start_step, profile_start_step+profile_steps)
    profile_start_step: int = 10   # skip compile + warmup steps
    profile_steps: int = 3


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=max(config.decay_steps, config.warmup_steps + 1),
        end_value=config.learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(config.grad_clip),
        optax.scale_by_adam(b1=config.beta1, b2=config.beta2,
                            mu_dtype=jnp.float32),
        optax.add_decayed_weights(config.weight_decay),
        optax.scale_by_learning_rate(schedule),
    )


class Trainer:
    """Wires a loss function + param specs into a sharded, jitted step.

    ``loss_fn(params, batch) -> scalar`` must be pure; ``param_specs`` is a
    PartitionSpec pytree congruent with params.
    """

    def __init__(self, loss_fn: Callable, param_specs, mesh: Mesh,
                 config: Optional[TrainConfig] = None,
                 batch_spec: Optional[P] = None):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.config = config or TrainConfig()
        self.optimizer = make_optimizer(self.config)
        self.param_specs = param_specs
        self._batch_spec = batch_spec
        self._step_fn = None

    # -- state ------------------------------------------------------------

    def init_state(self, params) -> TrainState:
        """Shard params by their specs and build the (sharded) optimizer
        state; fp32 Adam moments come from optax (``mu_dtype=float32``)."""
        self._shapes_cache = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
        p_shard = tree_shardings(self.mesh, self.param_specs)
        params = jax.tree.map(jax.device_put, params, p_shard)

        @partial(jax.jit,
                 out_shardings=tree_shardings(self.mesh, self._opt_specs()))
        def _init_opt(p):
            return self.optimizer.init(p)

        opt_state = _init_opt(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    def _opt_specs(self):
        """Specs for the optimizer-state pytree: any leaf whose shape
        matches a param gets that param's spec (Adam moments mirror params);
        everything else (counts, scalars) replicates."""
        shapes = jax.eval_shape(self.optimizer.init, self._shapes_cache)
        param_leaves = jax.tree_util.tree_leaves(self._shapes_cache)
        spec_leaves = jax.tree_util.tree_leaves(
            self.param_specs, is_leaf=lambda x: isinstance(x, P))
        by_shape = {}
        for shp, sp in zip(param_leaves, spec_leaves):
            by_shape.setdefault(tuple(shp.shape), sp)

        def leaf_spec(leaf):
            return by_shape.get(tuple(leaf.shape), P())
        return jax.tree.map(leaf_spec, shapes)

    # -- step -------------------------------------------------------------

    def _build_step(self):
        cfg = self.config
        p_shard = tree_shardings(self.mesh, self.param_specs)
        opt_shard = tree_shardings(self.mesh, self._opt_specs())
        # explicit batch_spec pins every leaf; the default defers to the
        # shardings shard_batch() placed (rank-aware: [b] labels, [b, s]
        # tokens, [b, h, w, c] images all shard differently)
        b_shard = (NamedSharding(self.mesh, self._batch_spec)
                   if self._batch_spec is not None else None)
        state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()), params=p_shard,
            opt_state=opt_shard)

        def one_grad(params, micro):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, micro)
            return loss, grads

        def step_fn(state: TrainState, batch):
            params = state.params
            if cfg.accum_steps > 1:
                micro = jax.tree.map(
                    lambda x: x.reshape((cfg.accum_steps,
                                         x.shape[0] // cfg.accum_steps)
                                        + x.shape[1:]), batch)

                def accum(carry, mb):
                    loss_acc, grad_acc = carry
                    loss, grads = one_grad(params, mb)
                    return (loss_acc + loss,
                            jax.tree.map(jnp.add, grad_acc, grads)), None

                zeros = jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), params)
                (loss, grads), _ = jax.lax.scan(
                    accum, (jnp.zeros((), jnp.float32), zeros), micro)
                loss = loss / cfg.accum_steps
                grads = jax.tree.map(lambda g: g / cfg.accum_steps, grads)
            else:
                loss, grads = one_grad(params, batch)

            updates, new_opt = self.optimizer.update(
                grads, state.opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_params = jax.tree.map(
                lambda new, old: new.astype(old.dtype), new_params, params)
            return TrainState(step=state.step + 1, params=new_params,
                              opt_state=new_opt), loss

        return jax.jit(step_fn,
                       in_shardings=(state_shardings, b_shard),
                       out_shardings=(state_shardings, NamedSharding(self.mesh, P())),
                       donate_argnums=(0,))

    @property
    def step(self):
        if self._step_fn is None:
            self._step_fn = self._build_step()
        return self._step_fn

    def remesh(self, mesh: Mesh) -> None:
        """Adopt a new device mesh after an elastic world change
        (docs/elastic.md): the jitted step and its shardings rebuild
        lazily against the new topology. The caller restores state via
        :func:`~kubedl_tpu.train.checkpoint.abstract_state_like` on the
        new mesh (``abstract_state`` already targets ``self.mesh``), so
        a shrink/regrow never re-initializes — the step counter and the
        loss curve continue where the checkpoint left them."""
        self.mesh = mesh
        self._step_fn = None

    # -- loop -------------------------------------------------------------

    def fit(self, state: TrainState, batches, num_steps: int,
            log_every: int = 10, on_step=None, checkpoint_manager=None,
            elastic_agent=None, eval_every: int = 0, eval_fn=None,
            data_state_fn=None, tracer=None):
        """Training loop. ``checkpoint_manager`` saves on its configured
        interval plus a final save; ``elastic_agent`` is polled each step so
        operator-requested elastic checkpoints are taken between steps
        (the AIMaster contract, ``kubedl_tpu.train.checkpoint``).
        ``eval_fn(state) -> dict`` runs every ``eval_every`` steps (and
        once after the last step) on the CURRENT state — held-out
        validation without leaving the loop. ``data_state_fn() -> dict``
        supplies the data cursor stored with every checkpoint, so a
        restore resumes the stream at the exact batch boundary.
        ``tracer`` (``kubedl_tpu.trace.Tracer``, enabled) records one
        ``train.step`` span per step and ``train.checkpoint`` spans,
        attached to the owning job's trace when the operator injected
        ``$KUBEDL_TRACEPARENT`` (docs/tracing.md)."""
        tr = tracer if tracer is not None and tracer.enabled else None
        trace_id = parent_id = None
        replica = ""
        if tr is not None:
            import os
            from ..trace import ENV_TRACEPARENT, parse_traceparent
            ctx = parse_traceparent(os.environ.get(ENV_TRACEPARENT, ""))
            if ctx is not None:
                trace_id, parent_id = ctx
            else:
                trace_id = tr.new_trace_id()
            # which slice worker this is: the operator injects
            # TPU_WORKER_ID (tpu/placement.py); the telemetry layer's
            # straggler detector compares step-time skew across replicas
            from ..tpu.placement import ENV_TPU_WORKER_ID
            replica = (os.environ.get(ENV_TPU_WORKER_ID)
                       or os.environ.get("HOSTNAME", ""))
        t0 = time.time()
        tokens = 0
        step0 = int(jax.device_get(state.step))  # one sync, then host-side
        cfg = self.config
        tracing = False
        # clamp the window into the actual run so a short fit still
        # produces a trace instead of silently skipping it
        profile_at = -1
        if cfg.profile_dir and cfg.profile_steps > 0:
            profile_at = min(cfg.profile_start_step, max(num_steps - 1, 0))
        try:
            for i in range(num_steps):
                if i == profile_at:
                    jax.profiler.start_trace(cfg.profile_dir)
                    tracing = True
                batch = next(batches)
                step_tokens = _batch_tokens(batch)
                tokens += step_tokens
                t_step = time.time() if tr is not None else 0.0
                state, loss = self.step(state, batch)
                if tr is not None:
                    # tokens + replica make the span throughput-derivable:
                    # the telemetry layer builds per-(model, pool)
                    # profiles and cross-replica skew detection from
                    # exactly these attributes (docs/telemetry.md)
                    tr.record("train.step", t_step, time.time(),
                              trace_id=trace_id, parent_id=parent_id,
                              component="train",
                              attributes={"step": step0 + i + 1,
                                          "tokens": step_tokens,
                                          "replica": replica})
                if tracing and i + 1 >= profile_at + cfg.profile_steps:
                    jax.block_until_ready(loss)  # close open device events
                    jax.profiler.stop_trace()
                    tracing = False
                if on_step is not None:
                    on_step(int(state.step), float(loss))
                if elastic_agent is not None:
                    elastic_agent.poll(state)
                if checkpoint_manager is not None:
                    t_ck = time.time() if tr is not None else 0.0
                    checkpoint_manager.save(
                        state, step=step0 + i + 1, periodic=True,
                        data_state=(data_state_fn() if data_state_fn
                                    else None))
                    if tr is not None:
                        tr.record("train.checkpoint", t_ck, time.time(),
                                  trace_id=trace_id, parent_id=parent_id,
                                  component="train",
                                  attributes={"step": step0 + i + 1,
                                              "periodic": True})
                if log_every and (i + 1) % log_every == 0:
                    dt = time.time() - t0
                    print(f"step {int(state.step)} loss {float(loss):.4f} "
                          f"{tokens / dt:.0f} tok/s")
                if eval_fn is not None and eval_every and \
                        ((i + 1) % eval_every == 0 or i + 1 == num_steps):
                    res = eval_fn(state)
                    print(f"step {int(state.step)} eval "
                          + " ".join(f"{k} {v:.4f}" if isinstance(v, float)
                                     else f"{k} {v}"
                                     for k, v in res.items()))
        finally:
            if tracing:
                jax.profiler.stop_trace()
        if checkpoint_manager is not None:
            t_ck = time.time() if tr is not None else 0.0
            checkpoint_manager.save(
                state, force=True,
                data_state=(data_state_fn() if data_state_fn else None))
            checkpoint_manager.wait_until_finished()
            if tr is not None:
                tr.record("train.checkpoint", t_ck, time.time(),
                          trace_id=trace_id, parent_id=parent_id,
                          component="train",
                          attributes={"step": int(jax.device_get(state.step)),
                                      "periodic": False})
        return state

    def abstract_state(self, state: TrainState):
        """Restore target for this trainer's shardings (see
        ``checkpoint.abstract_state_like``)."""
        from .checkpoint import abstract_state_like
        return abstract_state_like(state, self.mesh, self.param_specs,
                                   self._opt_specs())


def _batch_tokens(batch) -> int:
    leaf = jax.tree_util.tree_leaves(batch)[0]
    return int(leaf.shape[0] * (leaf.shape[1] if leaf.ndim > 1 else 1))
