"""Native runtime components (C++, loaded via ctypes).

The compute path is jax/XLA/pallas; the host-side runtime around it uses
native code where the per-step work is byte shuffling that would starve
the input pipeline in Python (the reference ships its data path as
compiled Go for the same reason). Components degrade transparently: when
the shared library is absent and no compiler is available, callers use
their pure-Python fallbacks.

``ensure_built()`` compiles ``packer.cc`` on first use with g++ (cached
next to the source); ``make native`` does the same ahead of time.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_DIR = Path(__file__).resolve().parent
_SO = _DIR / "libkubedl_native.so"
_SRC = _DIR / "packer.cc"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def ensure_built() -> Optional[Path]:
    """Build the shared library if missing and a compiler exists.
    Returns the .so path or None. Never raises."""
    if not _SRC.is_file():
        return _SO if _SO.is_file() else None  # wheel without sources
    if _SO.is_file() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    import logging
    import shutil
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return None
    # build to a per-pid temp path and os.replace: a killed or concurrent
    # build (xdist workers; multi-process hosts — the lock is per-process)
    # must never leave a truncated .so that caches as up-to-date forever
    tmp = _SO.with_suffix(f".{os.getpid()}.tmp")
    try:
        subprocess.run([cxx, "-O2", "-shared", "-fPIC", "-std=c++17",
                        "-o", str(tmp), str(_SRC)],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except subprocess.CalledProcessError as e:
        logging.getLogger("kubedl_tpu.native").warning(
            "native build failed; using the Python fallback:\n%s",
            (e.stderr or b"").decode(errors="replace")[-2000:])
        return None
    except Exception:  # noqa: BLE001 — fall back to Python packing
        return None
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None when native
    code is unavailable or disabled (``KUBEDL_NATIVE=0``)."""
    global _lib, _tried
    if os.environ.get("KUBEDL_NATIVE", "1") == "0":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = ensure_built()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))
            lib.kubedl_pack_rows.restype = ctypes.c_long
            lib.kubedl_pack_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
                ctypes.c_long, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_long,
            ]
        except (OSError, AttributeError):
            # unloadable, or a stale/foreign .so without our symbol:
            # degrade to the Python fallback, never crash the pipeline
            return None
        _lib = lib
        return _lib


def pack_rows_native(docs, seq_len: int, pad_id: int = 0):
    """Pack a finite list of token documents into (tokens, segs, pos)
    int32 arrays of shape [rows, seq_len+1] via the C++ packer. Returns
    None when the native path is unavailable (caller falls back)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    seq1 = seq_len + 1
    lens = np.asarray([len(d) for d in docs], np.int64)
    if len(lens) == 0 or int(lens.sum()) == 0:
        return (np.zeros((0, seq1), np.int32),) * 3
    flat = np.concatenate([np.asarray(d, np.int32) for d in docs]) \
        if len(docs) > 1 else np.asarray(docs[0], np.int32)
    flat = np.ascontiguousarray(flat, np.int32)
    # every chunk opens at most one new row, +1 for the trailing flush
    max_rows = int(np.ceil(lens / seq1).sum()) + 1
    toks = np.empty((max_rows, seq1), np.int32)
    segs = np.empty((max_rows, seq1), np.int32)
    pos = np.empty((max_rows, seq1), np.int32)
    n = lib.kubedl_pack_rows(
        flat.ctypes.data, lens.ctypes.data, len(lens),
        seq_len, pad_id,
        toks.ctypes.data, segs.ctypes.data, pos.ctypes.data, max_rows)
    if n < 0:  # capacity bound violated: fall back rather than trust it
        return None
    return toks[:n], segs[:n], pos[:n]
