// Native document packer — the data-loader hot path.
//
// Greedy first-fit packing of variable-length token documents into fixed
// [rows, seq_len+1] training rows (tokens / segment ids / positions),
// bit-identical to the Python reference in
// kubedl_tpu/train/data.py:pack_documents (the Python path remains the
// fallback and the spec; tests/test_native.py pins equality). Packing is
// pure byte shuffling over int32 streams — exactly the kind of per-step
// host work that starves a TPU input pipeline when the tokenizer output
// is large, so it runs as native code the way the reference's data
// loaders do.
//
// Build: make native   (g++ -O2 -shared -fPIC, no dependencies)
// Load:  kubedl_tpu.native (ctypes), transparent fallback when absent.

#include <cstdint>

extern "C" {

// Packs n_docs documents (flattened into `flat`, lengths in doc_lens)
// into rows of seq_len+1 slots. out_* must hold max_rows * (seq_len+1)
// int32 each. Returns the number of rows written (the trailing partial
// row, if any, is flushed — matching the Python generator's tail), or
// -1 if max_rows would be exceeded (caller sized the buffers wrong).
long kubedl_pack_rows(const int32_t* flat, const int64_t* doc_lens,
                      long n_docs, long seq_len, int32_t pad_id,
                      int32_t* out_tokens, int32_t* out_segs,
                      int32_t* out_pos, long max_rows) {
    const long seq1 = seq_len + 1;
    long row_len = 0;       // filled slots in the current (open) row
    int32_t seg_id = 0;     // per-row segment counter
    long n_rows = 0;        // completed rows

    auto flush = [&]() {
        int32_t* t = out_tokens + n_rows * seq1;
        int32_t* s = out_segs + n_rows * seq1;
        int32_t* p = out_pos + n_rows * seq1;
        for (long i = row_len; i < seq1; ++i) {
            t[i] = pad_id;
            s[i] = -1;
            p[i] = 0;
        }
        ++n_rows;
        row_len = 0;
        seg_id = 0;
    };

    const int32_t* doc = flat;
    for (long d = 0; d < n_docs; ++d) {
        const long len = doc_lens[d];
        for (long start = 0; start < len; start += seq1) {
            long clen = len - start;
            if (clen > seq1) clen = seq1;
            if (clen < 2) continue;  // no (input, target) pair
            if (row_len + clen > seq1) {
                if (n_rows >= max_rows) return -1;
                flush();
            }
            if (n_rows >= max_rows) return -1;
            int32_t* t = out_tokens + n_rows * seq1 + row_len;
            int32_t* s = out_segs + n_rows * seq1 + row_len;
            int32_t* p = out_pos + n_rows * seq1 + row_len;
            for (long i = 0; i < clen; ++i) {
                t[i] = doc[start + i];
                s[i] = seg_id;
                p[i] = static_cast<int32_t>(i);
            }
            row_len += clen;
            ++seg_id;
            if (row_len == seq1) {
                if (n_rows >= max_rows) return -1;
                flush();
            }
        }
        doc += len;
    }
    if (row_len) {
        if (n_rows >= max_rows) return -1;
        flush();
    }
    return n_rows;
}

}  // extern "C"
