"""Cross-cutting utilities: conditions, retry classification, resources."""
