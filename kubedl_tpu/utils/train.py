"""Failure-retryability classification.

Port of reference ``pkg/util/train/train_util.go:22-43``, extended with the
TPU failure taxonomy: a preempted TPU VM or a libtpu init crash is transient
(the slice survives or is re-provisioned); a compilation error is permanent.
"""

from __future__ import annotations

# exit codes treated as permanent (shell conventions)
_PERMANENT = {1, 2, 126, 127, 128, 139}
# retryable signals: SIGINT(130), SIGKILL(137), user-defined SIGUSR1(138), SIGTERM(143)
_RETRYABLE = {130, 137, 138, 143}

RETRYABLE_POD_REASONS = {
    "OOMKilled", "Killed", "Evicted", "UnexpectedAdmissionError",
    # TPU-native additions: GKE node preemption / TPU VM maintenance events
    "Preempted", "Shutdown", "NodeShutdown", "Terminated",
}


def is_retryable_exit_code(exit_code: int) -> bool:
    if exit_code in _PERMANENT:
        return False
    if exit_code in _RETRYABLE:
        return True
    return False


def is_retryable_pod_failed_reason(reason: str) -> bool:
    return reason in RETRYABLE_POD_REASONS
