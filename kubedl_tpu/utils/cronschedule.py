"""Standard 5-field cron schedule parser (robfig/cron `ParseStandard`
analog used by the reference's cron engine, ``controllers/apps/
cron_controller.go:179``).

Supports ``minute hour day-of-month month day-of-week`` with ``*``,
``*/step``, ``a-b``, ``a-b/step``, comma lists, month/day names, and the
``@hourly``-style descriptors. Day-of-month and day-of-week combine with OR
when both are restricted (POSIX cron semantics).
"""

from __future__ import annotations

import calendar
import time
from dataclasses import dataclass

_DESCRIPTORS = {
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_MONTH_NAMES = {name.lower(): i for i, name in enumerate(calendar.month_abbr) if name}
_DAY_NAMES = {name.lower(): i for i, name in enumerate(
    ["sun", "mon", "tue", "wed", "thu", "fri", "sat"])}

_BOUNDS = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


class InvalidSchedule(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int, names: dict) -> frozenset:
    out = set()
    for part in field.split(","):
        part = part.strip()
        if not part:
            raise InvalidSchedule(f"empty cron field element in {field!r}")
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            if not step_s.isdigit() or int(step_s) == 0:
                raise InvalidSchedule(f"bad step {step_s!r}")
            step = int(step_s)
        if part == "*" or part == "":
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = _resolve(a, names), _resolve(b, names)
        else:
            start = end = _resolve(part, names)
            if step > 1:  # "N/step" means "N-hi/step" in vixie cron
                end = hi
        top = 7 if names is _DAY_NAMES else hi  # "5-7" (Fri-Sun) is valid
        if not (lo <= start <= top and lo <= end <= top and start <= end):
            raise InvalidSchedule(
                f"field {field!r} out of range [{lo},{top}]")
        values = range(start, end + 1, step)
        if names is _DAY_NAMES:
            out.update(v % 7 for v in values)  # 7 == Sunday == 0
        else:
            out.update(values)
    return frozenset(out)


def _resolve(token: str, names: dict) -> int:
    token = token.strip().lower()
    if token.isdigit():
        return int(token)  # dow 7 (Sunday) is folded to 0 by the caller
    if names and token in names:
        return names[token]
    raise InvalidSchedule(f"bad cron token {token!r}")


@dataclass(frozen=True)
class Schedule:
    minutes: frozenset
    hours: frozenset
    dom: frozenset
    months: frozenset
    dow: frozenset
    dom_star: bool
    dow_star: bool

    def _day_matches(self, t: time.struct_time) -> bool:
        # POSIX: if both dom and dow are restricted, either may match
        dom_ok = t.tm_mday in self.dom
        dow_ok = (t.tm_wday + 1) % 7 in self.dow  # struct_time: Mon=0
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok

    def matches(self, ts: float) -> bool:
        t = time.localtime(ts)
        return (t.tm_min in self.minutes and t.tm_hour in self.hours
                and t.tm_mon in self.months and self._day_matches(t))

    def next_after(self, ts: float, horizon_days: int = 366 * 4) -> float:
        """Earliest fire time strictly after ``ts``. Raises if none within
        the horizon (e.g. Feb 30)."""
        # round up to the next whole minute
        t = int(ts // 60 + 1) * 60
        limit = t + horizon_days * 86400
        while t < limit:
            st = time.localtime(t)
            if st.tm_mon not in self.months:
                # jump to the 1st of the next month
                y, mo = st.tm_year, st.tm_mon + 1
                if mo > 12:
                    y, mo = y + 1, 1
                t = time.mktime((y, mo, 1, 0, 0, 0, 0, 1, -1))
                continue
            if not self._day_matches(st):
                t = time.mktime((st.tm_year, st.tm_mon, st.tm_mday + 1,
                                 0, 0, 0, 0, 1, -1))
                continue
            if st.tm_hour not in self.hours:
                t = time.mktime((st.tm_year, st.tm_mon, st.tm_mday,
                                 st.tm_hour + 1, 0, 0, 0, 1, -1))
                continue
            if st.tm_min not in self.minutes:
                t += 60
                continue
            return float(t)
        raise InvalidSchedule("no matching time within horizon")


def parse(schedule: str) -> Schedule:
    schedule = schedule.strip()
    if schedule.lower() in _DESCRIPTORS:
        schedule = _DESCRIPTORS[schedule.lower()]
    fields = schedule.split()
    if len(fields) != 5:
        raise InvalidSchedule(
            f"expected 5 cron fields, got {len(fields)}: {schedule!r}")
    names = [None, None, None, _MONTH_NAMES, _DAY_NAMES]
    sets = [_parse_field(f, lo, hi, nm)
            for f, (lo, hi), nm in zip(fields, _BOUNDS, names)]
    return Schedule(minutes=sets[0], hours=sets[1], dom=sets[2],
                    months=sets[3], dow=sets[4],
                    dom_star=fields[2] == "*", dow_star=fields[4] == "*")
