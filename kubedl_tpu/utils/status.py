"""Job-condition state machine.

Behavioral port of the reference's ``pkg/util/status.go:26-146``: a job's
``status.conditions`` list holds at most one condition per type; Running and
Restarting are mutually exclusive; reaching Failed freezes the machine;
reaching a terminal state flips Running to ``False``; ``lastTransitionTime``
only moves when the condition's status actually changes.
"""

from __future__ import annotations

from typing import Optional

from ..api import common as c
from ..api.common import JobCondition, JobStatus
from ..core.meta import rfc3339

REASON_JOB_CREATED = "JobCreated"
REASON_JOB_QUEUING = "JobQueuing"
REASON_JOB_SUCCEEDED = "JobSucceeded"
REASON_JOB_RUNNING = "JobRunning"
REASON_JOB_FAILED = "JobFailed"
REASON_JOB_RESTARTING = "JobRestarting"
REASON_JOB_EVICTED = "JobEvicted"
#: event reason stamped when every gang pod reports Running — the
#: timestamp that bounds PJRT rendezvous latency (docs/tracing.md)
REASON_RENDEZVOUS_READY = "RendezvousReady"


def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for cond in status.conditions:
        if cond.type == cond_type:
            return cond
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    return any(cd.type == cond_type and cd.status == "True" for cd in status.conditions)


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_FAILED)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_RUNNING)


def is_created(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_CREATED)


def is_restarting(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_RESTARTING)


def is_queuing(status: JobStatus) -> bool:
    return has_condition(status, c.JOB_QUEUING)


def is_evicted(status: JobStatus) -> bool:
    cond = get_condition(status, c.JOB_FAILED)
    return bool(cond and cond.reason == REASON_JOB_EVICTED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def update_job_conditions(status: JobStatus, cond_type: str, reason: str,
                          message: str, now: Optional[float] = None) -> None:
    ts = rfc3339(now)
    cond = JobCondition(type=cond_type, status="True", reason=reason,
                       message=message, last_update_time=ts,
                       last_transition_time=ts)
    _set_condition(status, cond)


def _set_condition(status: JobStatus, condition: JobCondition) -> None:
    if is_failed(status):  # Failed is a frozen terminal state
        return
    current = get_condition(status, condition.type)
    if current is not None and current.status == condition.status and current.reason == condition.reason:
        return
    if current is not None and current.status == condition.status:
        condition.last_transition_time = current.last_transition_time
    status.conditions = _filter_out(status.conditions, condition.type) + [condition]


def _filter_out(conditions: list, cond_type: str) -> list:
    out = []
    for cond in conditions:
        if cond_type == c.JOB_RESTARTING and cond.type == c.JOB_RUNNING:
            continue
        if cond_type == c.JOB_RUNNING and cond.type == c.JOB_RESTARTING:
            continue
        if cond.type == cond_type:
            continue
        if cond_type in (c.JOB_FAILED, c.JOB_SUCCEEDED) and cond.type == c.JOB_RUNNING:
            cond = JobCondition(**{**cond.__dict__, "status": "False"})
        # leaving the queue (running/restarting/terminal) ends Queuing
        if cond_type in (c.JOB_RUNNING, c.JOB_RESTARTING, c.JOB_FAILED,
                         c.JOB_SUCCEEDED) and cond.type == c.JOB_QUEUING \
                and cond.status == "True":
            cond = JobCondition(**{**cond.__dict__, "status": "False"})
        out.append(cond)
    return out
