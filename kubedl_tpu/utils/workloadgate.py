"""Workload enablement gate.

Behavioral analog of ``pkg/util/workloadgate/workload_gate.go:27-61``: which
workload kinds the operator runs, decided by (priority order) the
``WORKLOADS_ENABLE`` env, then the ``--workloads`` flag, then CRD
auto-detection. The spec grammar is the reference's: ``*`` enables all,
``Kind`` enables one, ``-Kind`` disables one, ``auto`` defers to whether the
kind's CRD is installed.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Optional

ENV_WORKLOADS_ENABLE = "WORKLOADS_ENABLE"
AUTO = "auto"


def parse_workloads_enabled(spec: str) -> tuple[dict, bool]:
    """Returns (per-kind {kind: enabled}, enable_all)."""
    enables: dict[str, bool] = {}
    enable_all = False
    for workload in spec.split(","):
        workload = workload.strip()
        if not workload:
            continue
        enable = True
        if workload.startswith("-"):
            enable = False
            workload = workload[1:]
        if workload == "*":
            enable_all = enable
        else:
            enables[workload] = enable
    return enables, enable_all


def is_workload_enabled(kind: str, spec: Optional[str] = None,
                        env: Optional[dict] = None,
                        crd_installed: Optional[Callable[[str], bool]] = None,
                        ) -> bool:
    """Env overrides flag (workload_gate.go:48-56); ``auto`` asks
    ``crd_installed`` (the discovery-client analog; defaults to yes, matching
    a self-hosted control plane where every kind is served)."""
    env = env if env is not None else dict(os.environ)
    effective = env.get(ENV_WORKLOADS_ENABLE) or spec or AUTO
    if effective == AUTO:
        return crd_installed(kind) if crd_installed else True
    enables, enable_all = parse_workloads_enabled(effective)
    if kind in enables:
        return enables[kind]
    return enable_all


def enabled_kinds(all_kinds: Iterable[str], spec: Optional[str] = None,
                  env: Optional[dict] = None,
                  crd_installed: Optional[Callable[[str], bool]] = None) -> list:
    return [k for k in all_kinds
            if is_workload_enabled(k, spec, env, crd_installed)]
