"""Ticket semaphore (reference ``pkg/util/concurrent/concurrent.go``):
bounds fan-out of parallel operations (the elastic controller restarts
workers through one of these, ≤100 in flight) and joins them all."""

from __future__ import annotations

import threading
from typing import Callable


class Semaphore:
    """Acquire/Release bound concurrency; Wait joins everything started."""

    def __init__(self, tickets: int):
        if tickets < 1:
            raise ValueError("tickets must be >= 1")
        self._sem = threading.Semaphore(tickets)
        self._pending = 0
        self._cond = threading.Condition()

    def acquire(self) -> None:
        self._sem.acquire()
        with self._cond:
            self._pending += 1

    def release(self) -> None:
        self._sem.release()
        with self._cond:
            self._pending -= 1
            if self._pending == 0:
                self._cond.notify_all()

    def wait(self) -> None:
        with self._cond:
            while self._pending:
                self._cond.wait()

    def go(self, fn: Callable, *args) -> threading.Thread:
        """Run ``fn`` on a thread under a ticket (acquire here so a burst
        of go() calls blocks at the bound, like the reference's usage)."""
        self.acquire()

        def run():
            try:
                fn(*args)
            finally:
                self.release()
        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t
