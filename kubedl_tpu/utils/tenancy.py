"""Multi-tenancy extraction (reference ``pkg/util/tenancy/tenancy.go``):
the ``kubedl.io/tenancy`` annotation carries tenant/user/idc/region for
quota attribution and the persistence layer's tenant columns."""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

from ..api import common as c
from ..core import meta as m


@dataclass(frozen=True)
class Tenancy:
    tenant: str = ""
    user: str = ""
    idc: str = ""
    region: str = ""


def get_tenancy(obj: dict) -> Optional[Tenancy]:
    """Parse the tenancy annotation; None when absent, raises ValueError on
    malformed JSON (the caller decides whether that fails the job)."""
    raw = m.annotations(obj).get(c.ANNOTATION_TENANCY_INFO)
    if raw is None:
        return None
    data = json.loads(raw)
    if not isinstance(data, dict):
        raise ValueError(f"tenancy annotation must be an object, got {data!r}")
    return Tenancy(tenant=data.get("tenant", ""), user=data.get("user", ""),
                   idc=data.get("idc", ""), region=data.get("region", ""))
