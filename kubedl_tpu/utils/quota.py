"""Resource accounting (reference ``pkg/util/quota/resources.go`` +
``pkg/util/resource_utils/resources.go``): summing container requests the
kube-scheduler way, job-level totals, and TPU-chip accounting for slice
capacity checks."""

from __future__ import annotations

import math

from ..api import common as c
from ..core import meta as m

#: the full k8s suffix table (apimachinery ``resource.Quantity``):
#: decimalSI m/k/M/G/T/P/E and binarySI Ki..Ei. ``E`` (exa) is a suffix
#: only when it terminates the string — ``12E6`` is the decimalExponent
#: form (12 x 10^6), handled by the plain-float path below.
_SUFFIXES = {
    "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
    "Ei": 2**60,
}
#: binary suffixes first so "Ei"/"Ki"... win over the bare decimal suffix
#: their final letter would otherwise match
_SUFFIX_ORDER = ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei",
                 "m", "k", "M", "G", "T", "P", "E")


def parse_quantity(v) -> float:
    """Parse a k8s resource quantity to a float in base units (cores /
    bytes / chips): plain and signed numbers ("2", "-3", "1.5"),
    decimalExponent forms ("123e6", "1E2"), decimalSI ("500m", "10k",
    "2M".."3E") and binarySI ("10Ki".."2Ei") suffixes. Raises ValueError
    on anything else (including inf/nan, which are not quantities)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suf in _SUFFIX_ORDER:
        if s.endswith(suf):
            try:
                num = float(s[: -len(suf)])
            except ValueError:
                break  # suffix matched but the prefix is not a number
                       # ("xKi"): let the plain parse raise on the whole
            if math.isinf(num) or math.isnan(num):
                raise ValueError(f"invalid k8s quantity {v!r}")
            return num * _SUFFIXES[suf]
    f = float(s)  # ValueError on garbage propagates
    if math.isinf(f) or math.isnan(f):
        raise ValueError(f"invalid k8s quantity {v!r}")
    return f


def sum_containers(containers: list) -> dict:
    """Per-resource sum of max(requests, limits) over containers
    (``SumUpContainersResources``)."""
    total: dict[str, float] = {}
    for ct in containers or []:
        res = ct.get("resources", {}) or {}
        req = dict(res.get("requests", {}) or {})
        for key, val in (res.get("limits", {}) or {}).items():
            req.setdefault(key, val)
        for key, val in req.items():
            total[key] = total.get(key, 0.0) + parse_quantity(val)
    return total


def max_containers(containers: list) -> dict:
    """Per-resource max over containers (``MaximumContainersResources`` —
    init containers run sequentially, so their cost is the max)."""
    total: dict[str, float] = {}
    for ct in containers or []:
        one = sum_containers([ct])
        for key, val in one.items():
            total[key] = max(total.get(key, 0.0), val)
    return total


def pod_request(pod_spec: dict) -> dict:
    """Effective pod request = sum(containers) elementwise-max
    max(initContainers) (``GetPodResourceRequest``, kube-scheduler rule)."""
    total = sum_containers(pod_spec.get("containers"))
    for key, val in max_containers(pod_spec.get("initContainers")).items():
        total[key] = max(total.get(key, 0.0), val)
    return total


def job_request(replica_specs: dict) -> dict:
    """Whole-job request: per-replica pod request x replicas."""
    total: dict[str, float] = {}
    for spec in (replica_specs or {}).values():
        replicas = int(spec.get("replicas", 1) or 0)
        pod = m.get_in(spec, "template", "spec", default={}) or {}
        for key, val in pod_request(pod).items():
            total[key] = total.get(key, 0.0) + val * replicas
    return total


def tpu_chips(replica_specs: dict) -> int:
    """Total google.com/tpu chips the job requests."""
    return int(job_request(replica_specs).get(c.RESOURCE_TPU, 0))
