"""Jittered retry for transient API errors + the slice-restart backoff.

Two related pieces of failure-handling math live here:

* ``retry_transient`` — bounded retries with *decorrelated jitter*
  exponential backoff (the AWS architecture-blog formula:
  ``delay' = min(cap, U(base, delay * 3))``), used by the engine around
  every api-server write so a transient 5xx/timeout never turns one
  reconcile into a failed job. Jitter matters at fleet scale: a thundering
  herd of operators retrying in lockstep is what turns a blip into an
  outage.

* ``restart_delay`` — the same decorrelated-jitter sequence made
  *deterministic per (job, round)* so the slice-failover gate computes the
  identical delay on every reconcile of the same round (the round counter
  and last-restart timestamp persist in ``JobStatus``; re-rolling the
  jitter each reconcile would make the gate flap).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass
class RetryPolicy:
    """Bounds for one logical API call: ``attempts`` tries total, sleeping
    a decorrelated-jitter delay in ``[base, cap]`` between them."""

    attempts: int = 4
    base: float = 0.02
    cap: float = 1.0


def retry_transient(fn: Callable, policy: Optional[RetryPolicy] = None, *,
                    retry_on: tuple = (), rng=None,
                    sleep: Callable[[float], None] = time.sleep,
                    on_retry: Optional[Callable] = None):
    """Call ``fn`` until it succeeds or ``policy.attempts`` is exhausted,
    retrying only on ``retry_on`` exceptions; the last error re-raises.

    ``sleep`` is injectable so deterministic tests can advance a fake
    clock instead of blocking; ``on_retry(attempt, delay, exc)`` is the
    observability seam.
    """
    policy = policy or RetryPolicy()
    rng = rng or random
    delay = policy.base
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.attempts)):
        try:
            return fn()
        except retry_on as e:  # noqa: B030 — tuple supplied by caller
            last = e
            if attempt == policy.attempts - 1:
                break
            if on_retry is not None:
                on_retry(attempt + 1, delay, e)
            sleep(delay)
            delay = min(policy.cap, rng.uniform(policy.base, delay * 3))
    assert last is not None
    raise last


def restart_delay(rounds: int, base: float, cap: float, *, key: str = "",
                  seed: int = 0) -> float:
    """Deterministic decorrelated-jitter delay before slice-restart round
    ``rounds`` (1-based): round 1 is immediate-after-``base``, later rounds
    grow as ``min(cap, U(base, prev * 3))``. Seeding from ``(key, seed)``
    keeps the value stable across reconciles of the same round while still
    de-correlating different jobs from each other."""
    if rounds <= 0:
        return 0.0
    rng = random.Random(f"{key}:{seed}")
    d = base
    for _ in range(rounds - 1):
        d = min(cap, rng.uniform(base, d * 3))
    return min(cap, d)
