"""Shared descriptive statistics for benches and the fleet scorecard.

Every bench used to carry its own inline ``pct()`` closure
(``bench_controlplane.py``, ``bench_scheduler.py``); this module is the
one implementation they and the cluster replay scorecard share.

Two percentile methods:

* ``nearest`` (default) — the historical bench semantics: index
  ``min(int(n * q), n - 1)`` into the sorted samples. Deterministic,
  returns an actual sample, and keeps existing BENCH_*.json artifacts
  byte-stable.
* ``linear`` — classic linear interpolation between closest ranks (what
  ``numpy.percentile`` calls "linear"), for smoother small-sample
  summaries.

All functions are pure and wall-clock-free: the replay rig's bit-for-bit
reproducibility contract extends to everything computed here.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

__all__ = ["percentile", "mean", "summarize"]


def percentile(values: Iterable[float], q: float,
               method: str = "nearest",
               default: Optional[float] = None) -> float:
    """The ``q``-quantile (``0.0 <= q <= 1.0``) of ``values``.

    ``values`` need not be sorted. An empty input returns ``default``
    when given, else raises ValueError (a silent 0.0 for "no samples"
    poisons gate comparisons)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    data = sorted(float(v) for v in values)
    if not data:
        if default is not None:
            return default
        raise ValueError("percentile of empty sequence")
    n = len(data)
    if method == "nearest":
        return data[min(int(n * q), n - 1)]
    if method == "linear":
        if n == 1:
            return data[0]
        rank = q * (n - 1)
        lo = int(rank)
        frac = rank - lo
        if lo + 1 >= n:
            return data[-1]
        return data[lo] + (data[lo + 1] - data[lo]) * frac
    raise ValueError(f"unknown percentile method {method!r}")


def mean(values: Iterable[float], default: Optional[float] = None) -> float:
    data = [float(v) for v in values]
    if not data:
        if default is not None:
            return default
        raise ValueError("mean of empty sequence")
    return sum(data) / len(data)


def summarize(values: Sequence[float],
              percentiles: Sequence[float] = (0.50, 0.99),
              method: str = "nearest", ndigits: int = 4) -> dict:
    """One summary dict for a sample list: ``count``/``mean``/``min``/
    ``max`` plus a ``p<NN>`` key per requested quantile (``0.50`` →
    ``p50``, ``0.999`` → ``p99.9``). Empty input yields ``count: 0`` and
    zeros — a *summary* of nothing is legitimate scorecard output even
    though a bare percentile of nothing is an error."""
    data = sorted(float(v) for v in values)
    out = {"count": len(data)}
    keys = []
    for q in percentiles:
        pretty = f"{q * 100:g}"
        keys.append((f"p{pretty}", q))
    if not data:
        out.update({"mean": 0.0, "min": 0.0, "max": 0.0})
        out.update({k: 0.0 for k, _ in keys})
        return out
    out["mean"] = round(mean(data), ndigits)
    out["min"] = round(data[0], ndigits)
    out["max"] = round(data[-1], ndigits)
    for k, q in keys:
        out[k] = round(percentile(data, q, method=method), ndigits)
    return out
