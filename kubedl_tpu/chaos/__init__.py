"""Declarative chaos campaigns over the fault-injecting control plane.

:mod:`kubedl_tpu.controllers.chaos` injects *uncorrelated* faults — one
409, one dropped event, one preempted pod. Real TPU fleets fail in
*correlated* ways (docs/chaos.md): a whole ICI domain's OCS links flap
at once, a pool's spot capacity vanishes in one sweep, a bad release
hot-loops one controller shard, the WAL disk slows to 1/100th speed.
This package is the campaign layer on top: seeded, sim-clock-scheduled
scenario scripts composed from correlated fault primitives, executed
against the REAL stack through the cluster replay harness, and gated on
SLO survival by ``bench_cluster.py --profile adversarial``.
"""

from .campaign import (Campaign, CampaignRunner, FaultAction, PRIMITIVES,
                       SCENARIOS, build_campaign, control_plane_digest)

__all__ = [
    "Campaign", "CampaignRunner", "FaultAction", "PRIMITIVES",
    "SCENARIOS", "build_campaign", "control_plane_digest",
]
