"""Seeded chaos campaigns: correlated fault primitives, pure of wall time.

A **campaign** is a time-sorted script of :class:`FaultAction`\\ s — a
pure function of ``(scenario, seed, profile)`` exactly like
``replay/workload.py``'s day generator, with the same
:meth:`Campaign.fingerprint` contract: identical inputs reproduce the
identical script bit for bit, so an adversarial scorecard is replayable
from its committed seed. The grammar (docs/chaos.md):

* **scenario** — a named builder in :data:`SCENARIOS` that draws every
  fault time/target/rate from one namespaced ``random.Random`` stream;
* **primitive** — one correlated fault the :class:`CampaignRunner` knows
  how to execute against a live :class:`~kubedl_tpu.replay.harness
  .ClusterReplay`:

  ===================  ====================================================
  ``domain_outage``    every gang the inventory's per-domain accounting
                       places in one ICI domain loses a node at once
                       (slice-atomic failover must restart each whole gang)
  ``spot_dry``         ``_start``/``_end`` pair: a pool's spot capacity
                       vanishes in one sweep — every gang holding slices
                       there is preempted together AND the pool's capacity
                       drops to zero for the window (evicted and arriving
                       work must queue or land elsewhere until capacity
                       returns)
  ``drain``            one running job in a pool is drained (several
                       ``drain`` actions spaced by an interval make a
                       rolling drain)
  ``watch_storm``      ``_start``/``_end`` pair: watch events drop and
                       duplicate at storm rates (stresses the expectations
                       machinery, bookmark rings, and relist fallback)
  ``hot_loop``         one reconcile shard spins: every live job hashing
                       to the shard is re-enqueued (a bad release's
                       busy-looping controller)
  ``slow_fsync``       ``_start``/``_end`` pair: the WAL's group-commit
                       fsync takes extra injected seconds (a dying disk),
                       advancing the sim clock — never sleeping
  ``leader_kill``      the control-plane leader dies SIGKILL-style mid-
                       day (journal never closed, tail only write(2)-
                       flushed) and the most-caught-up WAL follower is
                       promoted through the Lease machinery — requires
                       the replay's ``replication_followers`` > 0
                       (docs/replication.md)
  ``region_down``      ``_start``/``_end`` pair: an entire REGION dies at
                       once — its leader, followers, and every pool —
                       and the federation layer evacuates (elastic jobs
                       emigrate via the object-store checkpoint tier,
                       serving streams re-route). Requires a federation
                       driver (``FederationReplay``); a single-cluster
                       replay raises loudly (docs/federation.md)
  ===================  ====================================================

Faults are injected through the seeded :class:`ChaosAPIServer`
machinery, so everything a campaign does lands in the injector's own
ledgers (``faults`` / ``latencies`` / ``preemptions``) and the
scorecard's ``chaos.attribution`` block needs zero bench-local
bookkeeping.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass

from ..core.manager import Request, shard_for

#: executable fault primitives (window primitives appear as _start/_end)
PRIMITIVES = frozenset({
    "domain_outage", "drain", "hot_loop",
    "spot_dry_start", "spot_dry_end",
    "watch_storm_start", "watch_storm_end",
    "slow_fsync_start", "slow_fsync_end",
    "leader_kill",
    "region_down_start", "region_down_end",
})


@dataclass(frozen=True)
class FaultAction:
    """One scheduled fault: ``params`` is a sorted tuple of (key, value)
    pairs so actions hash, compare, and serialize canonically."""
    time_s: float
    primitive: str
    params: tuple = ()

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


def _params(**kw) -> tuple:
    return tuple(sorted(kw.items()))


@dataclass(frozen=True)
class Campaign:
    """A compiled scenario: the full fault schedule, time-sorted."""
    scenario: str
    seed: int
    actions: tuple                # FaultAction, time-sorted

    def fingerprint(self) -> str:
        """sha256 over the canonical JSON rendering — same determinism
        probe as ``Workload.fingerprint`` (docs/benchmarks.md)."""
        doc = {
            "scenario": self.scenario, "seed": self.seed,
            "actions": [{"t": a.time_s, "p": a.primitive,
                         "params": [list(p) for p in a.params]}
                        for a in self.actions],
        }
        blob = json.dumps(doc, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()

    def window(self) -> tuple:
        """(first, last) action times, or (0, 0) for an empty script."""
        if not self.actions:
            return 0.0, 0.0
        return self.actions[0].time_s, self.actions[-1].time_s


# ---------------------------------------------------------------------------
# primitive emitters (build-time: pure, rng-streamed)
# ---------------------------------------------------------------------------


def _watch_storm(at: float, duration: float, drop: float,
                 dup: float) -> list:
    return [
        FaultAction(round(at, 3), "watch_storm_start",
                    _params(drop=round(drop, 4), dup=round(dup, 4))),
        FaultAction(round(at + duration, 3), "watch_storm_end"),
    ]


def _slow_fsync(at: float, duration: float, seconds: float) -> list:
    return [
        FaultAction(round(at, 3), "slow_fsync_start",
                    _params(seconds=round(seconds, 4))),
        FaultAction(round(at + duration, 3), "slow_fsync_end"),
    ]


def _hot_loop(at: float, duration: float, interval: float,
              shard: int) -> list:
    out = []
    t = at
    while t < at + duration:
        out.append(FaultAction(round(t, 3), "hot_loop",
                               _params(shard=shard)))
        t += interval
    return out


def _rolling_drain(at: float, count: int, interval: float, pool: str,
                   rng: random.Random) -> list:
    return [FaultAction(round(at + i * interval, 3), "drain",
                        _params(pool=pool, ordinal=rng.randrange(1 << 16)))
            for i in range(count)]


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _pools(profile) -> list:
    return sorted(profile.capacity)


def _spot_pools(profile, spot_pools) -> list:
    if spot_pools is not None:
        return sorted(p for p in spot_pools if p in profile.capacity)
    # late import: the replay package imports this module at load time,
    # so the fleet's spot-class constant is resolved at build time
    from ..replay.workload import POOL_SPOT
    return sorted(p for p in POOL_SPOT if p in profile.capacity)


def _biggest_pool(profile) -> str:
    """The pool with the most slices (ties: name order) — where a
    domain outage has the most correlated blast radius."""
    return max(_pools(profile), key=lambda p: (profile.capacity[p], p))


def _scn_domain_outage(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    return [FaultAction(round(rng.uniform(0.35, 0.45) * day, 3),
                        "domain_outage",
                        _params(pool=_biggest_pool(profile),
                                domain=rng.randrange(1 << 16)))]


def _scn_spot_dryness(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    spots = _spot_pools(profile, spot_pools) or _pools(profile)
    at = rng.uniform(0.45, 0.52) * day
    duration = rng.uniform(1500.0, 2100.0)
    return [
        FaultAction(round(at, 3), "spot_dry_start",
                    _params(pool=spots[0])),
        FaultAction(round(at + duration, 3), "spot_dry_end",
                    _params(pool=spots[0])),
    ]


def _scn_spot_shrink(rng, profile, spot_pools) -> list:
    """Partial spot dryness (docs/elastic.md): the spot pool's capacity
    halves for a window instead of vanishing. With the elastic gate on
    the scheduler sheds surplus slices in place (shrink); with it off
    every holder is swept (the full-restart baseline) — the SAME script
    drives both legs of the shrink-vs-evict comparison."""
    day = profile.sim_seconds
    spots = _spot_pools(profile, spot_pools) or _pools(profile)
    pool = spots[0]
    at = rng.uniform(0.38, 0.46) * day
    duration = rng.uniform(2000.0, 2600.0)
    level = max(profile.capacity.get(pool, 2) // 2, 1)
    return [
        FaultAction(round(at, 3), "spot_dry_start",
                    _params(pool=pool, level=level)),
        FaultAction(round(at + duration, 3), "spot_dry_end",
                    _params(pool=pool)),
    ]


def _scn_rolling_drain(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    return _rolling_drain(rng.uniform(0.60, 0.70) * day, count=4,
                          interval=150.0, pool=_biggest_pool(profile),
                          rng=rng)


def _scn_watch_storm(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    return _watch_storm(rng.uniform(0.15, 0.25) * day,
                        duration=rng.uniform(180.0, 300.0),
                        drop=0.15, dup=0.30)


def _scn_hot_loop(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    return _hot_loop(rng.uniform(0.40, 0.50) * day, duration=300.0,
                     interval=15.0, shard=rng.randrange(1 << 16))


def _scn_slow_fsync(rng, profile, spot_pools) -> list:
    day = profile.sim_seconds
    return _slow_fsync(rng.uniform(0.25, 0.35) * day, duration=600.0,
                       seconds=0.25)


def _scn_adversarial(rng, profile, spot_pools) -> list:
    """The bench scenario: every primitive, staggered across the day so
    each wave lands on a fleet still digesting the previous one. Clause
    order is fixed; every time/target draws from the one rng stream."""
    acts = []
    acts += _scn_watch_storm(rng, profile, spot_pools)
    acts += _scn_slow_fsync(rng, profile, spot_pools)
    acts += _scn_domain_outage(rng, profile, spot_pools)
    acts += _scn_hot_loop(rng, profile, spot_pools)
    acts += _scn_spot_dryness(rng, profile, spot_pools)
    acts += _scn_rolling_drain(rng, profile, spot_pools)
    # a second, shorter watch storm riding the recovery tail of the
    # spot sweep — correlated faults rarely arrive alone
    acts += _watch_storm(rng.uniform(0.72, 0.78) * profile.sim_seconds,
                         duration=rng.uniform(120.0, 200.0),
                         drop=0.10, dup=0.20)
    return acts


def _scn_leader_kill(rng, profile, spot_pools) -> list:
    """The full adversarial day PLUS a SIGKILL of the control-plane
    leader landing on the recovery tail of the spot sweep — failover
    exercised under correlated faults, not in a quiet lab. Draw order
    is fixed (adversarial's clauses first, then the kill time), and the
    scenario name seeds its own rng stream, so the committed
    ``adversarial`` scenario's script is untouched bit for bit."""
    acts = _scn_adversarial(rng, profile, spot_pools)
    acts.append(FaultAction(
        round(rng.uniform(0.55, 0.65) * profile.sim_seconds, 3),
        "leader_kill"))
    return acts


def _scn_region_evacuation(rng, profile, spot_pools, regions) -> list:
    """The federation tentpole (docs/federation.md): one region dies
    whole at mid-day — leader, followers, and pools in a single sweep —
    and stays down long enough that evacuation, emigration, and the
    global SLO verdicts all land inside the window. The victim is drawn
    from the sorted region names so the script is a pure function of
    ``(seed, profile, regions)``."""
    if not regions:
        raise ValueError("region-evacuation scenario needs regions=")
    day = profile.sim_seconds
    names = sorted(regions)
    victim = names[rng.randrange(len(names))]
    at = rng.uniform(0.45, 0.55) * day
    duration = rng.uniform(1500.0, 2100.0)
    return [
        FaultAction(round(at, 3), "region_down_start",
                    _params(region=victim)),
        FaultAction(round(at + duration, 3), "region_down_end",
                    _params(region=victim)),
    ]


SCENARIOS = {
    "domain-outage": _scn_domain_outage,
    "spot-dryness": _scn_spot_dryness,
    "spot-shrink": _scn_spot_shrink,
    "rolling-drain": _scn_rolling_drain,
    "watch-storm": _scn_watch_storm,
    "hot-loop": _scn_hot_loop,
    "slow-fsync": _scn_slow_fsync,
    "adversarial": _scn_adversarial,
    "leader-kill": _scn_leader_kill,
    "region-evacuation": _scn_region_evacuation,
}

#: scenarios whose builders take the region-name list as a 4th argument;
#: every other builder keeps its 3-arg signature, so pre-existing
#: scenario scripts stay bit-identical whether or not ``regions`` is
#: passed to :func:`build_campaign`
_REGION_SCENARIOS = frozenset({"region-evacuation"})


def build_campaign(scenario: str, seed: int, profile,
                   spot_pools=None, regions=None) -> Campaign:
    """Compile ``scenario`` for ``(seed, profile)`` — pure: no wall
    clock, no ambient entropy, one namespaced rng stream. ``spot_pools``
    overrides the fleet's spot-class set (defaults to the replay
    workload's ``POOL_SPOT``); ``regions`` is the sorted-then-drawn
    victim set for region scenarios (ignored elsewhere)."""
    builder = SCENARIOS.get(scenario)
    if builder is None:
        raise ValueError(f"unknown scenario {scenario!r}: want one of "
                         f"{', '.join(sorted(SCENARIOS))}")
    rng = random.Random(f"{seed}:campaign:{scenario}")
    if scenario in _REGION_SCENARIOS:
        actions = builder(rng, profile, spot_pools, regions)
    else:
        actions = builder(rng, profile, spot_pools)
    bad = sorted({a.primitive for a in actions} - PRIMITIVES)
    if bad:
        raise ValueError(f"scenario {scenario!r} emitted unknown "
                         f"primitives {bad}")
    return Campaign(scenario=scenario, seed=seed,
                    actions=tuple(sorted(actions,
                                         key=lambda a: (a.time_s,
                                                        a.primitive,
                                                        a.params))))


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


class CampaignRunner:
    """Executes a :class:`Campaign` against a live ``ClusterReplay``.

    The replay schedules each action on its event heap and calls
    :meth:`execute` when sim time reaches it; primitives act only
    through surfaces the system itself owns — the chaos server's
    preemption/latency/watch machinery, the scheduler inventory's
    per-domain accounting, the manager's workqueue — so the blast is
    exactly what production would see, not a bench-side shortcut."""

    def __init__(self, campaign: Campaign, replay):
        self.campaign = campaign
        self.replay = replay
        #: primitive -> times executed (an action that found no victim
        #: still counts as executed; ``gangs_preempted`` says who bled)
        self.executed: dict[str, int] = {}
        #: distinct (job, primitive) gang preemptions performed
        self.gang_preemptions: list = []
        #: the same evictions with their sim times, for the incident
        #: timeline (docs/forensics.md): {"t", "job", "primitive"}
        self.preemption_log: list = []
        #: watch-storm rate stack: each _start pushes the rates it
        #: found, each _end restores the most recent push (overlapping
        #: windows degrade to nested semantics instead of a mid-storm
        #: fall-back to baseline or a no-op _end)
        self._storm_stack: list = []
        #: pool -> stack of static capacity entries to restore (None =
        #: the pool had NO static entry and goes back to Node-derived
        #: capacity); a stack for the same reason as _storm_stack —
        #: overlapping windows nest instead of ending the outage early
        self._dry_base: dict[str, list] = {}

    # -- dispatch ----------------------------------------------------------

    def execute(self, action: FaultAction) -> None:
        handler = getattr(self, "_do_" + action.primitive, None)
        if handler is None:
            raise ValueError(f"no handler for primitive "
                             f"{action.primitive!r}")
        self.executed[action.primitive] = \
            self.executed.get(action.primitive, 0) + 1
        handler(action)

    def summary(self) -> dict:
        return {
            "scenario": self.campaign.scenario,
            "fingerprint": self.campaign.fingerprint(),
            "actions_total": len(self.campaign.actions),
            "actions_executed": dict(sorted(self.executed.items())),
            "gangs_preempted": len(self.gang_preemptions),
            "gangs_preempted_by_primitive": self._gangs_by_primitive(),
        }

    def _gangs_by_primitive(self) -> dict:
        out: dict[str, int] = {}
        for _job, primitive in self.gang_preemptions:
            out[primitive] = out.get(primitive, 0) + 1
        return dict(sorted(out.items()))

    # -- correlated preemption primitives ---------------------------------

    def _preempt_jobs(self, names, primitive: str, fn=None) -> None:
        """Preempt ``names`` via ``fn`` (default: the replay's one-pod
        ``preempt_job``), recording each hit in the shared ledgers."""
        for name in names:
            hit = (fn(name) if fn is not None
                   else self.replay.preempt_job(name))
            if hit:
                self.gang_preemptions.append((name, primitive))
                self.preemption_log.append({
                    "t": self.replay.clock(), "job": name,
                    "primitive": primitive})

    def _running_in_pool(self, pool: str) -> list:
        return sorted(n for n, r in self.replay._jobs.items()
                      if r.running and not r.succeeded
                      and r.spec.pool == pool)

    def _do_domain_outage(self, action: FaultAction) -> None:
        pool = action.param("pool")
        inv = self.replay.inventory
        gangs = inv.domain_gangs(pool)
        free = inv.domain_free_map(pool)
        if not gangs or not free:
            return
        dom = action.param("domain", 0) % len(free)
        victims = sorted(job for (_ns, job), doms in gangs.items()
                         if dom in doms)
        self._preempt_jobs(victims, "domain_outage")

    def _do_spot_dry_start(self, action: FaultAction) -> None:
        pool = action.param("pool")
        #: partial dryness (docs/elastic.md): ``level`` pins capacity at
        #: a floor instead of zero. Absent (every committed scenario)
        #: the classic total-dryness semantics apply bit for bit.
        level = action.param("level")
        inv = self.replay.inventory
        # save the STATIC entry, not capacity_slices(): a pool with
        # Node-derived capacity has no static entry, and restoring
        # must remove the 0-pin (None), not freeze a snapshot of
        # the node count as a permanent static override
        self._dry_base.setdefault(pool, []).append(
            inv.static_capacity.get(pool))
        # capacity vanishes FIRST, then the response: evicted gangs must
        # not be re-admitted into a pool that no longer exists
        inv.set_static_capacity(pool, 0 if level is None else int(level))
        if level is not None:
            if getattr(self.replay, "elastic", False):
                # the scheduler's shrink pass is the authority over an
                # overcommitted pool (docs/elastic.md): elastic gangs
                # shed surplus slices in place, only the remainder
                # evicts whole — one nudged pass, no harness-side sweep
                self.replay.scheduler.schedule_pass()
                return
            # baseline (gate off): partial dryness still reclaims WHOLE
            # gangs — one pod per slice, so slice-atomic failover tears
            # each gang down in a single round and it re-enters its
            # queue complete (a lone pending slice would starve behind
            # a fully-evicted head's reservation forever)
            holders = sorted({h.job for h in inv.held_records()
                              if h.pool == pool})
            self._preempt_jobs(holders, "spot_dry",
                               fn=self.replay.preempt_gang)
            return
        holders = sorted({h.job for h in inv.held_records()
                          if h.pool == pool})
        self._preempt_jobs(holders, "spot_dry")

    def _do_spot_dry_end(self, action: FaultAction) -> None:
        pool = action.param("pool")
        stack = self._dry_base.get(pool)
        if not stack:
            return                       # no matching _start
        base = stack.pop()
        if not stack:
            del self._dry_base[pool]
        self.replay.inventory.set_static_capacity(pool, base)

    def _do_drain(self, action: FaultAction) -> None:
        running = self._running_in_pool(action.param("pool"))
        if not running:
            return
        name = running[action.param("ordinal", 0) % len(running)]
        self._preempt_jobs([name], "drain")

    # -- watch storm -------------------------------------------------------

    def _do_watch_storm_start(self, action: FaultAction) -> None:
        cfg = self.replay.chaos.config
        self._storm_stack.append((cfg.drop_watch_events,
                                  cfg.duplicate_watch_events))
        cfg.drop_watch_events = float(action.param("drop", 0.0))
        cfg.duplicate_watch_events = float(action.param("dup", 0.0))

    def _do_watch_storm_end(self, action: FaultAction) -> None:
        if not self._storm_stack:
            return                       # no matching _start
        cfg = self.replay.chaos.config
        cfg.drop_watch_events, cfg.duplicate_watch_events = \
            self._storm_stack.pop()

    # -- hot-looping controller -------------------------------------------

    def _do_hot_loop(self, action: FaultAction) -> None:
        mgr = self.replay.manager
        shard = action.param("shard", 0) % mgr.shards
        for name in sorted(self.replay._jobs):
            rec = self.replay._jobs[name]
            if rec.succeeded:
                continue
            if shard_for("default", name, mgr.shards) == shard:
                mgr.enqueue(Request("TestJob", "default", name))

    # -- leader kill -------------------------------------------------------

    def _do_leader_kill(self, action: FaultAction) -> None:
        """SIGKILL the control-plane leader and promote the most-
        caught-up WAL follower (docs/replication.md). The replay owns
        the process model; it raises loudly when the campaign was run
        without ``replication_followers`` — a silently skipped failover
        would gut the scenario's whole point."""
        self.replay.kill_leader()

    # -- region down -------------------------------------------------------

    def _do_region_down_start(self, action: FaultAction) -> None:
        """Kill an entire region — leader, followers, pools — and hand
        evacuation to the federation driver (docs/federation.md). Like
        ``leader_kill``, a replay that cannot evacuate raises loudly: a
        silently skipped region death would gut the scenario. Evacuated
        jobs land in the shared preemption ledgers so the forensics
        timeline can chain their pages to the ``region_down`` window."""
        region = action.param("region")
        evacuate = getattr(self.replay, "region_down", None)
        if evacuate is None:
            raise RuntimeError(
                "region_down needs a federation driver (FederationReplay"
                "); a single-cluster replay has no region to kill")
        for name in evacuate(region):
            self.gang_preemptions.append((name, "region_down"))
            self.preemption_log.append({
                "t": self.replay.clock(), "job": name,
                "primitive": "region_down"})

    def _do_region_down_end(self, action: FaultAction) -> None:
        """Close the forensics window. The region does NOT come back —
        evacuation is one-way for the day (a revived region would need a
        rejoin/backfill protocol this layer doesn't model yet); the
        driver only notes the window so timeline attribution can pair
        start and end by region param."""
        region = action.param("region")
        restore = getattr(self.replay, "region_down_end", None)
        if restore is not None:
            restore(region)

    # -- slow fsync --------------------------------------------------------

    def _do_slow_fsync_start(self, action: FaultAction) -> None:
        seconds = float(action.param("seconds", 0.1))
        self.replay.chaos.config.op_latency["fsync"] = (1.0, seconds)

    def _do_slow_fsync_end(self, action: FaultAction) -> None:
        self.replay.chaos.config.op_latency.pop("fsync", None)


# ---------------------------------------------------------------------------
# recovery parity
# ---------------------------------------------------------------------------


def control_plane_digest(api, exclude_kinds=("Event",)) -> dict:
    """Deterministic digest of the store's object-level state: every
    (kind, namespace, name) with its spec, statuses excluded (a campaign
    legitimately writes alert conditions; *object-level* parity means
    the same world of objects with the same declared intent). The
    adversarial gate holds a post-campaign run to the same digest as a
    fault-free reference run of the identical workload."""
    rows = []
    for kind in sorted(api.kinds()):
        if kind in exclude_kinds:
            continue
        for obj in api.list(kind):
            md = obj.get("metadata") or {}
            rows.append({
                "kind": kind,
                "namespace": md.get("namespace", "default"),
                "name": md.get("name", ""),
                "spec": obj.get("spec"),
            })
    rows.sort(key=lambda r: (r["kind"], r["namespace"], r["name"]))
    blob = json.dumps(rows, sort_keys=True).encode()
    return {"objects": len(rows),
            "digest": hashlib.sha256(blob).hexdigest()}
