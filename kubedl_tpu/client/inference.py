"""Typed client for kubedl-tpu predictors (stdlib-only, pip-installable
with the base package).

The reference exposes generated clientsets for its CRDs but nothing for
the data plane (predictors are stock TFServing/Triton images). Here the
predictor is in-tree, so a first-party client ships with it:

    from kubedl_tpu.client.inference import InferenceClient

    c = InferenceClient("http://llama-chat.default.svc:8000")
    print(c.chat([{"role": "user", "content": "hi"}]))
    for delta in c.chat_stream([{"role": "user", "content": "hi"}]):
        print(delta, end="", flush=True)
    vectors = c.embed(["query text", "doc text"])

Every method maps 1:1 onto the predictor's OpenAI-convention routes
(``serving/server.py``), so the client also works against any other
OpenAI-compatible endpoint.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence


class InferenceError(RuntimeError):
    """Server-side failure, carrying the HTTP status and the message
    from the OpenAI error envelope when present."""

    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class InferenceClient:
    def __init__(self, base_url: str, timeout_s: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    # -- plumbing ----------------------------------------------------------

    def _post(self, route: str, payload: dict, stream: bool = False):
        req = urllib.request.Request(
            self.base_url + route, method="POST",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read()).get("error")
                msg = (err.get("message") if isinstance(err, dict)
                       else str(err))
            except Exception:  # noqa: BLE001 — body is best-effort
                msg = e.reason
            raise InferenceError(e.code, msg or str(e.reason)) from None
        if stream:
            return resp
        with resp:
            return json.loads(resp.read())

    @staticmethod
    def _sse(resp) -> Iterator[dict]:
        with resp:
            for raw in resp:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    return
                yield json.loads(data)

    @staticmethod
    def _gen_params(max_tokens, temperature, top_p, stop) -> dict:
        out = {"max_tokens": max_tokens}
        if temperature is not None:
            out["temperature"] = temperature
        if top_p is not None:
            out["top_p"] = top_p
        if stop:
            out["stop"] = stop
        return out

    # -- generation --------------------------------------------------------

    def complete(self, prompt, max_tokens: int = 256,
                 temperature: Optional[float] = None,
                 top_p: Optional[float] = None, stop=None,
                 n: int = 1) -> List[str]:
        """Completion texts for a prompt (string, list of strings, or
        token-id list); ``n`` samples per prompt."""
        body = {"prompt": prompt, "n": n,
                **self._gen_params(max_tokens, temperature, top_p, stop)}
        res = self._post("/v1/completions", body)
        return [c["text"] for c in res["choices"]]

    def complete_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: Optional[float] = None,
                        top_p: Optional[float] = None,
                        stop=None) -> Iterator[str]:
        """Yield completion text deltas as they decode."""
        body = {"prompt": prompt, "stream": True,
                **self._gen_params(max_tokens, temperature, top_p, stop)}
        for chunk in self._sse(self._post("/v1/completions", body,
                                          stream=True)):
            delta = chunk["choices"][0].get("text", "")
            if delta:
                yield delta

    def chat(self, messages: Sequence[dict], max_tokens: int = 256,
             temperature: Optional[float] = None,
             top_p: Optional[float] = None, stop=None) -> str:
        """Assistant reply for a chat conversation."""
        body = {"messages": list(messages),
                **self._gen_params(max_tokens, temperature, top_p, stop)}
        res = self._post("/v1/chat/completions", body)
        return res["choices"][0]["message"]["content"]

    def chat_stream(self, messages: Sequence[dict],
                    max_tokens: int = 256,
                    temperature: Optional[float] = None,
                    top_p: Optional[float] = None,
                    stop=None) -> Iterator[str]:
        """Yield assistant content deltas as they decode."""
        body = {"messages": list(messages), "stream": True,
                **self._gen_params(max_tokens, temperature, top_p, stop)}
        for chunk in self._sse(self._post("/v1/chat/completions", body,
                                          stream=True)):
            delta = chunk["choices"][0].get("delta", {}).get("content", "")
            if delta:
                yield delta

    def embed(self, inputs, chunk: int = 16) -> List[List[float]]:
        """L2-normalized embedding vectors for a string or list of
        strings. Inputs beyond the server's batch cap are chunked
        transparently (``chunk`` should not exceed the predictor's
        ``max_batch``)."""
        if isinstance(inputs, str):
            inputs = [inputs]
        chunk = max(chunk, 1)          # clamp ONCE: the slice uses it too
        out: List[List[float]] = []
        for start in range(0, len(inputs), chunk):
            res = self._post("/v1/embeddings",
                             {"input": inputs[start:start + chunk]})
            out.extend(d["embedding"] for d in
                       sorted(res["data"], key=lambda d: d["index"]))
        return out

    # -- introspection -----------------------------------------------------

    def models(self) -> List[str]:
        req = urllib.request.Request(self.base_url + "/v1/models")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return [m["id"] for m in json.loads(r.read())["data"]]

    def healthy(self) -> bool:
        try:
            req = urllib.request.Request(self.base_url + "/healthz")
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status == 200
        except OSError:
            return False


__all__ = ["InferenceClient", "InferenceError"]
