"""Clientset: typed per-kind resource clients.

Mirrors the reference's generated clientset
(``client/clientset/versioned/typed/training/v1alpha1``): one client per
kind with Create/Get/List/Update/UpdateStatus/Patch/Delete/Watch, grouped
by API group the way ``versioned.Interface`` groups them
(``TrainingV1alpha1()``, etc.).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Callable, Optional

from ..core import meta as m
from ..core.apiserver import APIServer


@dataclass(frozen=True)
class ResourceInfo:
    kind: str
    api_version: str
    plural: str
    namespaced: bool = True


#: every kind the operator serves (the 13 CRDs of config/crd/bases plus the
#: core-group objects the engine manages on the in-memory control plane)
KIND_TABLE = {
    # training.kubedl.io (reference client/ covers exactly this group)
    "TFJob": ResourceInfo("TFJob", "training.kubedl.io/v1alpha1", "tfjobs"),
    "PyTorchJob": ResourceInfo("PyTorchJob", "training.kubedl.io/v1alpha1", "pytorchjobs"),
    "JAXJob": ResourceInfo("JAXJob", "training.kubedl.io/v1alpha1", "jaxjobs"),
    "MPIJob": ResourceInfo("MPIJob", "training.kubedl.io/v1alpha1", "mpijobs"),
    "XGBoostJob": ResourceInfo("XGBoostJob", "training.kubedl.io/v1alpha1", "xgboostjobs"),
    "XDLJob": ResourceInfo("XDLJob", "training.kubedl.io/v1alpha1", "xdljobs"),
    "MarsJob": ResourceInfo("MarsJob", "training.kubedl.io/v1alpha1", "marsjobs"),
    "ElasticDLJob": ResourceInfo("ElasticDLJob", "training.kubedl.io/v1alpha1", "elasticdljobs"),
    "RLJob": ResourceInfo("RLJob", "training.kubedl.io/v1alpha1", "rljobs"),
    # platform groups
    "Model": ResourceInfo("Model", "model.kubedl.io/v1alpha1", "models"),
    "ModelVersion": ResourceInfo("ModelVersion", "model.kubedl.io/v1alpha1", "modelversions"),
    "Inference": ResourceInfo("Inference", "serving.kubedl.io/v1alpha1", "inferences"),
    "Notebook": ResourceInfo("Notebook", "notebook.kubedl.io/v1alpha1", "notebooks"),
    "CacheBackend": ResourceInfo("CacheBackend", "cache.kubedl.io/v1alpha1", "cachebackends"),
    "Cron": ResourceInfo("Cron", "apps.kubedl.io/v1alpha1", "crons"),
    # core/scheduling substrate
    "Pod": ResourceInfo("Pod", "v1", "pods"),
    "Service": ResourceInfo("Service", "v1", "services"),
    "Event": ResourceInfo("Event", "v1", "events"),
    "ConfigMap": ResourceInfo("ConfigMap", "v1", "configmaps"),
    "PersistentVolumeClaim": ResourceInfo("PersistentVolumeClaim", "v1", "persistentvolumeclaims"),
    "Deployment": ResourceInfo("Deployment", "apps/v1", "deployments"),
    "Ingress": ResourceInfo("Ingress", "networking.k8s.io/v1", "ingresses"),
    "PodGroup": ResourceInfo("PodGroup", "scheduling.sigs.k8s.io/v1alpha1", "podgroups"),
    # slice-scheduler tenancy quota (docs/scheduling.md)
    "Queue": ResourceInfo("Queue", "scheduling.kubedl.io/v1alpha1", "queues",
                          namespaced=False),
    # fleet telemetry: persisted per-(profile, pool) throughput estimates
    # (docs/telemetry.md)
    "ThroughputProfile": ResourceInfo(
        "ThroughputProfile", "telemetry.kubedl.io/v1alpha1",
        "throughputprofiles", namespaced=False),
    # SLO engine: declared objectives over fleet signals (docs/slo.md)
    "SLO": ResourceInfo("SLO", "slo.kubedl.io/v1alpha1", "slos",
                        namespaced=False),
}

TRAINING_KINDS = tuple(k for k, v in KIND_TABLE.items()
                       if v.api_version.startswith("training.kubedl.io"))


def plural_to_kind(plural: str) -> Optional[str]:
    for kind, info in KIND_TABLE.items():
        if info.plural == plural:
            return kind
    return None


class ResourceClient:
    """Typed client for one kind (the generated ``tfJobs`` interface shape:
    Create/Update/UpdateStatus/Delete/Get/List/Watch/Patch)."""

    def __init__(self, api: APIServer, info: ResourceInfo,
                 namespace: Optional[str] = None):
        self.api = api
        self.info = info
        self.namespace = namespace

    def _ns(self, namespace: Optional[str]) -> str:
        return namespace or self.namespace or "default"

    def create(self, obj: dict, namespace: Optional[str] = None) -> dict:
        obj = copy.deepcopy(obj)  # never mutate the caller's manifest
        obj.setdefault("apiVersion", self.info.api_version)
        obj.setdefault("kind", self.info.kind)
        target_ns = self._ns(namespace)
        obj_ns = m.meta(obj).get("namespace")
        if obj_ns and (namespace or self.namespace) and obj_ns != target_ns:
            # client-go rejects a request-namespace/object-namespace mismatch
            raise ValueError(
                f"object namespace {obj_ns!r} conflicts with request "
                f"namespace {target_ns!r}")
        m.meta(obj).setdefault("namespace", target_ns)
        return self.api.create(obj)

    def get(self, name: str, namespace: Optional[str] = None) -> dict:
        return self.api.get(self.info.kind, self._ns(namespace), name)

    def try_get(self, name: str, namespace: Optional[str] = None) -> Optional[dict]:
        return self.api.try_get(self.info.kind, self._ns(namespace), name)

    def list(self, namespace: Optional[str] = None,
             selector: Optional[dict] = None,
             all_namespaces: bool = False) -> list:
        ns = None if all_namespaces else self._ns(namespace)
        return self.api.list(self.info.kind, ns, selector)

    def update(self, obj: dict) -> dict:
        return self.api.update(obj)

    def update_status(self, obj: dict) -> dict:
        return self.api.update_status(obj)

    def patch(self, name: str, patch: dict,
              namespace: Optional[str] = None) -> dict:
        return self.api.patch_merge(self.info.kind, self._ns(namespace),
                                    name, patch)

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self.api.delete(self.info.kind, self._ns(namespace), name)

    def watch(self, fn: Callable[[str, dict], None]) -> Callable[[], None]:
        """Subscribe to this kind's events only; returns unsubscribe fn."""
        kind = self.info.kind

        def filtered(event_type: str, obj: dict):
            if m.kind(obj) == kind:
                fn(event_type, obj)
        return self.api.watch(filtered)


class _Group:
    """One API group's typed accessors (``TrainingV1alpha1Interface``)."""

    def __init__(self, api: APIServer, kinds: list[str]):
        self._api = api
        self._kinds = kinds
        for kind in kinds:
            info = KIND_TABLE[kind]
            setattr(self, info.plural, ResourceClient(api, info))

    def __iter__(self):
        return iter(self._kinds)


class Clientset:
    """The ``versioned.Interface`` analog: one handle exposing every group.

    >>> cs = Clientset(api)
    >>> cs.training.tfjobs.create({...})
    >>> cs.kind("PyTorchJob").list(all_namespaces=True)
    """

    def __init__(self, api: APIServer):
        self.api = api
        by_group: dict[str, list[str]] = {}
        for kind, info in KIND_TABLE.items():
            group = info.api_version.split("/")[0]
            alias = {
                "training.kubedl.io": "training",
                "model.kubedl.io": "model",
                "serving.kubedl.io": "serving",
                "notebook.kubedl.io": "notebook",
                "cache.kubedl.io": "cache",
                "apps.kubedl.io": "apps",
                "v1": "core",
                "apps": "k8s_apps",
                "networking.k8s.io": "networking",
                "scheduling.sigs.k8s.io": "scheduling",
                "slo.kubedl.io": "slo",
            }.get(group, group.replace(".", "_"))
            by_group.setdefault(alias, []).append(kind)
        for alias, kinds in by_group.items():
            setattr(self, alias, _Group(api, kinds))

    def kind(self, kind: str, namespace: Optional[str] = None) -> ResourceClient:
        """Dynamic accessor by kind name (the ``dynamic.Interface`` analog)."""
        if kind not in KIND_TABLE:
            raise KeyError(f"unknown kind {kind!r}; known: {sorted(KIND_TABLE)}")
        return ResourceClient(self.api, KIND_TABLE[kind], namespace)
