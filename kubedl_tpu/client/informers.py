"""Shared informers and listers.

The analog of the reference's generated informer/lister tree
(``client/informers/externalversions``, ``client/listers``): a shared
factory hands out one informer per kind; each informer keeps a local cache
(indexed by namespace/name, bucketed by namespace for listers) synced from
the API server's watch stream, replays the initial list to late-added
handlers, and exposes a ``Lister`` over the cache so reads don't hit the
store.

Ownership rule (docs/control-plane-perf.md): cached objects are the API
server's shared snapshots — handlers and lister callers must treat them as
frozen and copy before mutating, exactly like client-go informer caches.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..core import meta as m
from ..core.apiserver import APIServer, TooOldResourceVersion
from .clientset import KIND_TABLE


class Lister:
    """Cache-backed reads (``client/listers/.../tfjob.go`` shape)."""

    def __init__(self, informer: "Informer"):
        self._informer = informer

    def get(self, namespace: str, name: str) -> Optional[dict]:
        return self._informer._cache_get(namespace, name)

    def list(self, namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list:
        return self._informer._cache_list(namespace, selector)


class Informer:
    """One kind's shared informer: local cache + event handlers."""

    def __init__(self, api: APIServer, kind: str):
        self.api = api
        self.kind = kind
        self._cache: dict[tuple[str, str], dict] = {}
        #: namespace -> {key -> obj}: listers filter per-namespace without
        #: scanning the whole cache (mirror of the server-side ns index)
        self._by_ns: dict[str, dict[tuple[str, str], dict]] = {}
        self._handlers: list[dict] = []
        self._lock = threading.RLock()
        self._synced = False
        self._syncing = False
        self._sync_tombstones: set = set()  # deletes seen during initial sync
        self._cancel: Optional[Callable[[], None]] = None
        #: resourceVersion bookmark: the newest rv this informer has seen
        #: (docs/durability.md). ``resume()`` reconnects from here so a
        #: dropped watch replays the gap from the server's bounded event
        #: ring instead of forcing a full relist.
        self.last_rv = 0
        #: reconnects served from the bookmark ring (relists avoided)
        self.bookmark_resumes = 0
        #: reconnects that had to fall back to a full list+watch
        self.full_relists = 0
        #: resume-in-flight guard: two racing resume() calls must not
        #: register duplicate watch subscriptions
        self._resuming = False
        #: recent deletions' tombstone rvs (bounded, insertion-ordered):
        #: deletion pops the cache and with it the level information the
        #: staleness guards need — without this, a bookmark-replayed
        #: MODIFIED landing after a live DELETED would resurrect the
        #: object (old is None -> cache_put). A genuine recreate carries
        #: a HIGHER rv and clears the tombstone.
        self._dead: dict[tuple[str, str], int] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Subscribe to the watch stream and sync the initial list.

        The API-server calls (watch/list) run *outside* the informer lock:
        holding it across them would deadlock against a concurrent writer
        whose watch fan-out blocks on this lock (ABBA with the store lock).
        """
        with self._lock:
            if self._cancel is not None:
                return
            self._syncing = True
            self._sync_tombstones.clear()
            self._cancel = self.api.watch(self._on_event)
        snapshot = self.api.list(self.kind)
        with self._lock:
            for obj in snapshot:
                key = (m.namespace(obj), m.name(obj))
                self.last_rv = max(self.last_rv, m.resource_version(obj))
                # skip keys the watch already saw — including DELETED
                # events for snapshot objects, which must not resurrect
                if key not in self._cache and key not in self._sync_tombstones:
                    self._cache_put(key, obj)
                    self._dispatch("add", None, obj)
            self._syncing = False
            self._sync_tombstones.clear()
            self._synced = True
        # list+watch consistency: the initial list reflects the store at
        # its current rv, so the bookmark starts there (real reflectors
        # take the LIST response's resourceVersion the same way)
        if hasattr(self.api, "latest_resource_version"):
            rv = self.api.latest_resource_version()
            if rv > self.last_rv:
                self.last_rv = rv

    def resume(self) -> None:
        """Reconnect from the ``last_rv`` bookmark (docs/durability.md):
        the server replays the missed events from its bounded per-kind
        ring, so a restarted/briefly-disconnected informer catches up
        without relisting the world. A too-old bookmark (ring evicted
        past it, or no ring on this store) falls back to a full
        :meth:`start` — counted in ``full_relists`` informer-side and
        ``kubedl_watch_relists_total{reason}`` server-side."""
        with self._lock:
            if self._cancel is not None or self._resuming:
                return             # still connected / resume in flight
            self._resuming = True
        try:
            # resolved OUTSIDE the try below: a missing seam (a
            # real-cluster adapter) must relist, but an AttributeError
            # raised by a user handler during the synchronous replay
            # must propagate — swallowing it would mask the handler bug
            # AND leak a duplicate subscription via the fallback
            watch_from = getattr(self.api, "watch_from", None)
            if watch_from is None:
                self.full_relists += 1
                self._relist()
                return
            try:
                cancel, caught_up = watch_from(
                    self._on_event, self.last_rv, kinds=(self.kind,))
            except TooOldResourceVersion:
                self.full_relists += 1
                self._relist()
                return
            with self._lock:
                self._cancel = cancel
                self.last_rv = max(self.last_rv, caught_up)
                self._synced = True
                self.bookmark_resumes += 1
        finally:
            with self._lock:
                self._resuming = False

    def _relist(self) -> None:
        """Full list+watch over a non-empty cache (client-go
        ``Replace()`` semantics): vanished keys get synthesized delete
        events, changed keys get updates, new keys get adds. ``start()``
        alone only ADDS missing keys — after a gap the ring could not
        cover, that would serve deleted objects forever."""
        with self._lock:
            if self._cancel is not None:
                self._cancel()
            self._syncing = True
            self._sync_tombstones.clear()
            self._cancel = self.api.watch(self._on_event)
        # captured BEFORE the list: the vanished-key sweep below spares
        # cached objects with rv > list_rv (created during the relist,
        # delivered live) — reading the counter after the list could
        # cover such a creation and synthesize a delete for a live
        # object; an underestimate only spares too much, never deletes
        list_rv = 0
        if hasattr(self.api, "latest_resource_version"):
            list_rv = self.api.latest_resource_version()
        snapshot = self.api.list(self.kind)
        with self._lock:
            fresh = {}
            for obj in snapshot:
                key = (m.namespace(obj), m.name(obj))
                fresh[key] = obj
                if key in self._sync_tombstones:
                    continue            # deleted while we listed
                old = self._cache.get(key)
                if old is None:
                    self._cache_put(key, obj)
                    self._dispatch("add", None, obj)
                elif m.resource_version(obj) > m.resource_version(old):
                    self._cache_put(key, obj)
                    self._dispatch("update", old, obj)
            for key in [k for k in self._cache if k not in fresh]:
                old = self._cache[key]
                if list_rv and m.resource_version(old) > list_rv:
                    continue            # created after the list: live
                self._cache_pop(key)
                self._dispatch("delete", None, old)
            self._syncing = False
            self._sync_tombstones.clear()
            self._synced = True
            if list_rv > self.last_rv:
                self.last_rv = list_rv

    def disconnect(self) -> None:
        """Drop the watch subscription but KEEP the cache and bookmark
        (the dropped-connection half of a resume cycle; ``stop()`` is
        the full teardown)."""
        with self._lock:
            if self._cancel is not None:
                self._cancel()
                self._cancel = None

    def stop(self) -> None:
        with self._lock:
            if self._cancel is not None:
                self._cancel()
                self._cancel = None
            self._synced = False

    def has_synced(self) -> bool:
        return self._synced

    # -- handlers ---------------------------------------------------------

    def add_event_handler(self, on_add: Optional[Callable] = None,
                          on_update: Optional[Callable] = None,
                          on_delete: Optional[Callable] = None) -> None:
        """Handlers get (obj) for add/delete and (old, new) for update.
        A handler added after start() gets the current cache replayed as
        adds (client-go semantics)."""
        handler = {"add": on_add, "update": on_update, "delete": on_delete}
        with self._lock:
            self._handlers.append(handler)
            if self._synced and on_add is not None:
                for obj in list(self._cache.values()):
                    on_add(obj)

    def lister(self) -> Lister:
        return Lister(self)

    # -- internals --------------------------------------------------------

    def _cache_put(self, key: tuple[str, str], obj: dict) -> None:
        self._cache[key] = obj
        self._by_ns.setdefault(key[0], {})[key] = obj

    def _cache_pop(self, key: tuple[str, str]) -> None:
        self._cache.pop(key, None)
        bucket = self._by_ns.get(key[0])
        if bucket is not None:
            bucket.pop(key, None)
            if not bucket:
                del self._by_ns[key[0]]

    def _on_event(self, event_type: str, obj: dict) -> None:
        # the bookmark tracks the GLOBAL rv stream: this subscription
        # sees every kind's events (the kind filter is ours), so after a
        # quiescent point last_rv equals the store's counter — which is
        # what makes a post-restart resume land exactly on the recovered
        # store's ring base (k8s reflectors get this from BOOKMARK
        # events; here the fan-out itself carries it). GIL-atomic max.
        rv = m.resource_version(obj)
        if rv > self.last_rv:
            self.last_rv = rv
        if m.kind(obj) != self.kind:
            return
        key = (m.namespace(obj), m.name(obj))
        with self._lock:
            if event_type in ("ADDED", "MODIFIED"):
                dead_rv = self._dead.get(key)
                if dead_rv is not None:
                    if rv <= dead_rv:
                        # a stale replayed event for an object a newer
                        # DELETED already removed: applying it would
                        # resurrect the deleted object (the cache pop
                        # erased the level the guards below compare to)
                        return
                    del self._dead[key]      # genuine recreate
            if event_type == "ADDED":
                prev = self._cache.get(key)
                if prev is not None and \
                        m.resource_version(prev) >= m.resource_version(obj):
                    # already replayed by start()'s list snapshot: an object
                    # created while start() held the lock would otherwise be
                    # dispatched as 'add' twice
                    return
                self._cache_put(key, obj)
                self._dispatch("add", None, obj)
            elif event_type == "MODIFIED":
                old = self._cache.get(key)
                if old is not None and \
                        m.resource_version(old) >= rv:
                    # stale or duplicate (a bookmark replay racing a
                    # live delivery, a chaos-duplicated event): the
                    # cache is level-based, never regressed
                    return
                self._cache_put(key, obj)
                if old is None:
                    self._dispatch("add", None, obj)
                else:
                    self._dispatch("update", old, obj)
            elif event_type == "DELETED":
                old = self._cache.get(key)
                if old is not None and m.resource_version(old) > rv:
                    # a stale replayed tombstone must not delete the
                    # newer (recreated) object the live stream put here
                    return
                self._dead[key] = max(rv, self._dead.get(key, 0))
                while len(self._dead) > 1024:   # bounded, oldest first
                    self._dead.pop(next(iter(self._dead)))
                if self._syncing:
                    self._sync_tombstones.add(key)
                self._cache_pop(key)
                self._dispatch("delete", None, obj)

    def _dispatch(self, which: str, old: Optional[dict], obj: dict) -> None:
        for handler in list(self._handlers):
            fn = handler.get(which)
            if fn is None:
                continue
            if which == "update":
                fn(old, obj)
            else:
                fn(obj)

    def _cache_get(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get((namespace, name))

    def _cache_list(self, namespace: Optional[str],
                    selector: Optional[dict]) -> list:
        with self._lock:
            if namespace is not None:
                candidates = list(self._by_ns.get(namespace, {}).values())
            else:
                candidates = list(self._cache.values())
            out = []
            for obj in candidates:
                if selector is not None and not m.match_labels(
                        m.get_labels(obj), selector):
                    continue
                out.append(obj)
        out.sort(key=lambda o: (m.namespace(o), m.name(o)))
        return out


class SharedInformerFactory:
    """``externalversions.SharedInformerFactory``: one informer per kind,
    shared across consumers; ``start()`` starts them all."""

    def __init__(self, api: APIServer):
        self.api = api
        self._informers: dict[str, Informer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str) -> Informer:
        if kind not in KIND_TABLE:
            raise KeyError(f"unknown kind {kind!r}")
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self.api, kind)
                self._informers[kind] = inf
            return inf

    def lister(self, kind: str) -> Lister:
        return self.informer(kind).lister()

    def start(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()

    def wait_for_cache_sync(self) -> bool:
        return all(inf.has_synced() for inf in self._informers.values())
