"""Shared informers and listers.

The analog of the reference's generated informer/lister tree
(``client/informers/externalversions``, ``client/listers``): a shared
factory hands out one informer per kind; each informer keeps a local cache
(indexed by namespace/name, bucketed by namespace for listers) synced from
the API server's watch stream, replays the initial list to late-added
handlers, and exposes a ``Lister`` over the cache so reads don't hit the
store.

Ownership rule (docs/control-plane-perf.md): cached objects are the API
server's shared snapshots — handlers and lister callers must treat them as
frozen and copy before mutating, exactly like client-go informer caches.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..core import meta as m
from ..core.apiserver import APIServer
from .clientset import KIND_TABLE


class Lister:
    """Cache-backed reads (``client/listers/.../tfjob.go`` shape)."""

    def __init__(self, informer: "Informer"):
        self._informer = informer

    def get(self, namespace: str, name: str) -> Optional[dict]:
        return self._informer._cache_get(namespace, name)

    def list(self, namespace: Optional[str] = None,
             selector: Optional[dict] = None) -> list:
        return self._informer._cache_list(namespace, selector)


class Informer:
    """One kind's shared informer: local cache + event handlers."""

    def __init__(self, api: APIServer, kind: str):
        self.api = api
        self.kind = kind
        self._cache: dict[tuple[str, str], dict] = {}
        #: namespace -> {key -> obj}: listers filter per-namespace without
        #: scanning the whole cache (mirror of the server-side ns index)
        self._by_ns: dict[str, dict[tuple[str, str], dict]] = {}
        self._handlers: list[dict] = []
        self._lock = threading.RLock()
        self._synced = False
        self._syncing = False
        self._sync_tombstones: set = set()  # deletes seen during initial sync
        self._cancel: Optional[Callable[[], None]] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Subscribe to the watch stream and sync the initial list.

        The API-server calls (watch/list) run *outside* the informer lock:
        holding it across them would deadlock against a concurrent writer
        whose watch fan-out blocks on this lock (ABBA with the store lock).
        """
        with self._lock:
            if self._cancel is not None:
                return
            self._syncing = True
            self._sync_tombstones.clear()
            self._cancel = self.api.watch(self._on_event)
        snapshot = self.api.list(self.kind)
        with self._lock:
            for obj in snapshot:
                key = (m.namespace(obj), m.name(obj))
                # skip keys the watch already saw — including DELETED
                # events for snapshot objects, which must not resurrect
                if key not in self._cache and key not in self._sync_tombstones:
                    self._cache_put(key, obj)
                    self._dispatch("add", None, obj)
            self._syncing = False
            self._sync_tombstones.clear()
            self._synced = True

    def stop(self) -> None:
        with self._lock:
            if self._cancel is not None:
                self._cancel()
                self._cancel = None
            self._synced = False

    def has_synced(self) -> bool:
        return self._synced

    # -- handlers ---------------------------------------------------------

    def add_event_handler(self, on_add: Optional[Callable] = None,
                          on_update: Optional[Callable] = None,
                          on_delete: Optional[Callable] = None) -> None:
        """Handlers get (obj) for add/delete and (old, new) for update.
        A handler added after start() gets the current cache replayed as
        adds (client-go semantics)."""
        handler = {"add": on_add, "update": on_update, "delete": on_delete}
        with self._lock:
            self._handlers.append(handler)
            if self._synced and on_add is not None:
                for obj in list(self._cache.values()):
                    on_add(obj)

    def lister(self) -> Lister:
        return Lister(self)

    # -- internals --------------------------------------------------------

    def _cache_put(self, key: tuple[str, str], obj: dict) -> None:
        self._cache[key] = obj
        self._by_ns.setdefault(key[0], {})[key] = obj

    def _cache_pop(self, key: tuple[str, str]) -> None:
        self._cache.pop(key, None)
        bucket = self._by_ns.get(key[0])
        if bucket is not None:
            bucket.pop(key, None)
            if not bucket:
                del self._by_ns[key[0]]

    def _on_event(self, event_type: str, obj: dict) -> None:
        if m.kind(obj) != self.kind:
            return
        key = (m.namespace(obj), m.name(obj))
        with self._lock:
            if event_type == "ADDED":
                prev = self._cache.get(key)
                if prev is not None and \
                        m.resource_version(prev) >= m.resource_version(obj):
                    # already replayed by start()'s list snapshot: an object
                    # created while start() held the lock would otherwise be
                    # dispatched as 'add' twice
                    return
                self._cache_put(key, obj)
                self._dispatch("add", None, obj)
            elif event_type == "MODIFIED":
                old = self._cache.get(key)
                self._cache_put(key, obj)
                if old is None:
                    self._dispatch("add", None, obj)
                else:
                    self._dispatch("update", old, obj)
            elif event_type == "DELETED":
                if self._syncing:
                    self._sync_tombstones.add(key)
                self._cache_pop(key)
                self._dispatch("delete", None, obj)

    def _dispatch(self, which: str, old: Optional[dict], obj: dict) -> None:
        for handler in list(self._handlers):
            fn = handler.get(which)
            if fn is None:
                continue
            if which == "update":
                fn(old, obj)
            else:
                fn(obj)

    def _cache_get(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get((namespace, name))

    def _cache_list(self, namespace: Optional[str],
                    selector: Optional[dict]) -> list:
        with self._lock:
            if namespace is not None:
                candidates = list(self._by_ns.get(namespace, {}).values())
            else:
                candidates = list(self._cache.values())
            out = []
            for obj in candidates:
                if selector is not None and not m.match_labels(
                        m.get_labels(obj), selector):
                    continue
                out.append(obj)
        out.sort(key=lambda o: (m.namespace(o), m.name(o)))
        return out


class SharedInformerFactory:
    """``externalversions.SharedInformerFactory``: one informer per kind,
    shared across consumers; ``start()`` starts them all."""

    def __init__(self, api: APIServer):
        self.api = api
        self._informers: dict[str, Informer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str) -> Informer:
        if kind not in KIND_TABLE:
            raise KeyError(f"unknown kind {kind!r}")
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self.api, kind)
                self._informers[kind] = inf
            return inf

    def lister(self, kind: str) -> Lister:
        return self.informer(kind).lister()

    def start(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()

    def wait_for_cache_sync(self) -> bool:
        return all(inf.has_synced() for inf in self._informers.values())
