"""Fleet flight recorder: WAL time-travel and causal incident forensics.

The rig can injure itself (chaos campaigns, docs/chaos.md) and detect
the injury (SLO pages, docs/slo.md); this package explains it. Three
layers, all pure reads over state the system already records:

* :mod:`worldline` — a :class:`WorldLine` over a journal directory
  (docs/durability.md) reconstructs the exact store at ANY
  resourceVersion (newest snapshot <= rv + WAL replay of the tail,
  riding ``Journal.iter_records``), diffs two rvs, and emits a
  per-object commit history with the WAL's ``ts`` timestamps.
* :mod:`timeline` — an :class:`IncidentTimeline` merges a campaign's
  fingerprinted fault actions, SLO fire/clear transitions,
  chaos-attributed preemptions, and lifecycle-trace restart rounds into
  one time-ordered stream, then causally links each SLO page to the
  fault window(s) overlapping its burn window and the specific jobs
  whose bad samples drove the burn.
* :mod:`report` — a deterministic postmortem (JSON + rendered markdown)
  per campaign, folded into the adversarial scorecard as its
  ``forensics`` block (``make postmortem`` renders the committed one).

docs/forensics.md has the WorldLine contract, the timeline grammar, the
causal-linking rules, and the postmortem schema.
"""

from .worldline import HistoryUnavailable, WorldLine  # noqa: F401
from .timeline import IncidentTimeline  # noqa: F401
from .report import build_postmortem, render_postmortem_md  # noqa: F401
