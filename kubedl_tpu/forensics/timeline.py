"""One time-ordered incident stream, with pages causally linked to faults.

The adversarial day produces four disjoint records of what happened: the
campaign's fault script (:class:`~kubedl_tpu.chaos.campaign.Campaign`),
the SLO evaluator's alert transitions (``SLOEvaluator.alert_log``), the
chaos injector's preemption ledger, and the lifecycle traces' restart
rounds. An operator doing a postmortem today hand-correlates them. The
:class:`IncidentTimeline` merges them into one stream and then does the
correlation mechanically (docs/forensics.md "causal-linking rules"):

* **fault windows** — ``_start``/``_end`` primitive pairs become one
  window ``[start, end]``; instantaneous primitives (``domain_outage``,
  ``drain``, ``hot_loop``) are point windows at their action time.
* **incidents** — each alert ``fire`` opens an incident for its
  ``(slo, severity)``, the matching ``clear`` closes it.
* **links** — a page is linked to a fault by (strongest first):

  1. ``preempted-sample``: a bad sample inside the page's long burn
     window names a job (``labels.job``) that a campaign primitive
     preempted at or before the fire — the sample chain from the page
     back through the bleeding job to the fault that hit it.
  2. ``window-overlap``: the fault window intersects the page's burn
     window ``[fire - longSeconds, fire]``.
  3. ``lagged``: the fault window closed before the burn window opened
     but within ``lag_horizon_s`` of it — queued/delayed work surfaces
     its bad samples (retirement-time signals like ``queue_delay``)
     after the fault itself is over, so the effect trails the cause.

All times are sim-relative seconds (callers pass ``epoch`` — the sim
clock's ``t0`` — and absolute inputs are normalized), so the built
document is bit-for-bit deterministic for a fixed seed.
"""

from __future__ import annotations

from typing import Optional

#: primitives whose _start/_end pairs bound a window; everything else
#: is a point fault at its action time. ``region_down`` ends carry the
#: region param (like ``spot_dry_end`` names its pool), so two staggered
#: region outages pair by region instead of LIFO-swapping attribution.
_WINDOW_PRIMITIVES = ("spot_dry", "watch_storm", "slow_fsync",
                      "region_down")

#: how long a closed fault window keeps explaining later bad samples
#: (rule 3): retirement-time signals report a fault's damage when the
#: delayed job finally retires, long after the fault cleared
DEFAULT_LAG_HORIZON_S = 2.0 * 3600.0

#: point actions of one primitive spaced at most this far apart merge
#: into one fault window: a rolling drain is four spaced ``drain``
#: actions and a hot-looping controller is a 15s-interval ``hot_loop``
#: train — one correlated event each, not twenty separate links
POINT_COALESCE_GAP_S = 600.0


def _r(t: Optional[float], nd: int = 3) -> Optional[float]:
    return None if t is None else round(float(t), nd)


class IncidentTimeline:
    """Accumulates the four source streams, then :meth:`build`\\ s the
    merged document. Feed methods are independent — a live operator
    without a campaign feeds only alerts, and its incidents simply
    carry no fault links."""

    def __init__(self, epoch: float = 0.0,
                 lag_horizon_s: float = DEFAULT_LAG_HORIZON_S):
        #: absolute-time inputs (alert log, samples, restarts) are
        #: normalized to sim-relative seconds by subtracting this
        self.epoch = float(epoch)
        self.lag_horizon_s = float(lag_horizon_s)
        self._actions: list = []      # {"t", "primitive", "params"}
        self._windows: list = []      # {"primitive","start","end","params"}
        self._alerts: list = []       # normalized alert_log entries
        self._preemptions: list = []  # {"t", "job", "primitive"}
        self._restarts: list = []     # {"t", "end", "job"}
        self._bad_samples: list = []  # normalized evaluator bad samples
        self._alerting: dict = {}     # slo -> {severity: (short, long)}

    # -- feeds -------------------------------------------------------------

    def add_campaign(self, campaign) -> None:
        """Fold a compiled campaign's actions in; ``_start``/``_end``
        pairs are matched in time order per primitive (an unmatched
        ``_start`` window stays open to the end of time)."""
        open_starts: dict = {}
        for a in campaign.actions:
            self._actions.append({"t": _r(a.time_s),
                                  "primitive": a.primitive,
                                  "params": [list(p) for p in a.params]})
            base = None
            for w in _WINDOW_PRIMITIVES:
                if a.primitive == f"{w}_start":
                    open_starts.setdefault(w, []).append(a)
                    base = w
                    break
                if a.primitive == f"{w}_end":
                    stack = open_starts.get(w) or []
                    # pair with the newest start TARGETING THE SAME
                    # THING: spot_dry_end names its pool, and two
                    # overlapping pools' windows must not swap
                    # attribution. Ends without params (watch_storm)
                    # fall back to LIFO, matching the runner's stacks.
                    idx = None
                    end_params = dict(a.params)
                    if end_params:
                        for i in range(len(stack) - 1, -1, -1):
                            sp = dict(stack[i].params)
                            if all(sp.get(k) == v
                                   for k, v in end_params.items()):
                                idx = i
                                break
                    if idx is None and stack:
                        idx = len(stack) - 1
                    start = stack.pop(idx) if idx is not None else None
                    self._windows.append({
                        "primitive": w,
                        "start": _r(start.time_s if start else 0.0),
                        "end": _r(a.time_s),
                        "params": [list(p) for p in
                                   (start.params if start else a.params)],
                        "actions": 2,
                    })
                    base = w
                    break
            if base is None:
                prev = next((w for w in reversed(self._windows)
                             if w["primitive"] == a.primitive), None)
                if prev is not None and prev["end"] is not None \
                        and a.time_s - prev["end"] \
                        <= POINT_COALESCE_GAP_S:
                    # same-primitive action train: widen the window
                    prev["end"] = _r(a.time_s)
                    prev["actions"] = prev.get("actions", 1) + 1
                else:
                    self._windows.append({
                        "primitive": a.primitive,
                        "start": _r(a.time_s), "end": _r(a.time_s),
                        "params": [list(p) for p in a.params],
                        "actions": 1,
                    })
        for w, stack in sorted(open_starts.items()):
            for start in stack:       # never-closed window: open-ended
                self._windows.append({
                    "primitive": w, "start": _r(start.time_s),
                    "end": None,
                    "params": [list(p) for p in start.params],
                    "actions": 1,
                })
        self._windows.sort(key=lambda w: (w["start"], w["primitive"]))

    def add_alert_log(self, alert_log, specs: Optional[dict] = None) -> None:
        """Fold the evaluator's transition history in. ``specs`` maps
        slo name -> :class:`~kubedl_tpu.api.slo.SLOSpec`, used to
        resolve each severity's burn-window widths for linking."""
        for a in alert_log:
            self._alerts.append({
                "t": _r(a["t"] - self.epoch),
                "slo": a["slo"], "severity": a["severity"],
                "event": a["event"],
                "shortBurn": _r(a.get("shortBurn"), 6),
                "longBurn": _r(a.get("longBurn"), 6),
            })
        for name, spec in (specs or {}).items():
            self._alerting[name] = {
                w.severity: (w.short_s, w.long_s)
                for w in spec.alerting}

    def add_preemptions(self, preemption_log) -> None:
        """``[{"t", "job", "primitive"}]`` — the campaign runner's
        per-gang eviction log (absolute times normalized)."""
        for p in preemption_log:
            self._preemptions.append({
                "t": _r(p["t"] - self.epoch),
                "job": p["job"], "primitive": p["primitive"]})

    def add_restarts(self, restart_windows) -> None:
        """``[(start, end, job)]`` restart rounds harvested from
        lifecycle traces (absolute times normalized)."""
        for start, end, job in restart_windows:
            self._restarts.append({
                "t": _r(start - self.epoch),
                "end": _r(end - self.epoch), "job": job})

    def add_bad_samples(self, samples) -> None:
        """The evaluator's bad-sample attribution log
        (``SLOEvaluator.bad_samples``): which sample burned which
        objective, carrying the sample's labels (``job`` when the
        feeder stamped one)."""
        for s in samples:
            self._bad_samples.append({
                "t": _r(s["t"] - self.epoch), "slo": s["slo"],
                "signal": s["signal"], "value": _r(s["value"]),
                "job": (s.get("labels") or {}).get("job", ""),
            })

    # -- linking -----------------------------------------------------------

    def _burn_window(self, slo: str, severity: str,
                     fired_at: float) -> tuple:
        pair = (self._alerting.get(slo) or {}).get(severity)
        long_s = pair[1] if pair else 3600.0
        return fired_at - long_s, fired_at

    def _link_page(self, slo: str, severity: str,
                   fired_at: float) -> list:
        lo, hi = self._burn_window(slo, severity, fired_at)
        links = []
        seen = set()

        def add(rule: str, window: dict, jobs=()):
            key = (window["primitive"], window["start"])
            if key in seen:
                for lk in links:
                    if (lk["primitive"], lk["windowStart"]) == key:
                        lk["evidenceJobs"] = sorted(
                            set(lk["evidenceJobs"]) | set(jobs))
                        return
            seen.add(key)
            links.append({
                "rule": rule, "primitive": window["primitive"],
                "windowStart": window["start"],
                "windowEnd": window["end"],
                "evidenceJobs": sorted(jobs),
            })

        # rule 1: bad samples in the burn window -> preempted jobs ->
        # the primitive that evicted them (strongest: a named chain)
        burned_jobs = {s["job"] for s in self._bad_samples
                       if s["slo"] == slo and s["job"]
                       and lo <= s["t"] <= hi}
        if burned_jobs:
            hits = [p for p in self._preemptions
                    if p["job"] in burned_jobs and p["t"] <= hi]
            # evidence sticks to the NEAREST PRECEDING window of the
            # evicting primitive — not to every train of it (a second
            # train hours later never touched this job). Nearest-
            # preceding rather than strict containment because the
            # eviction lands when the event loop executes the action,
            # which can trail the scripted window by a tick.
            jobs_by_window: dict = {}
            for p in hits:
                best = None
                for i, w in enumerate(self._windows):
                    if w["primitive"] == p["primitive"] \
                            and w["start"] <= p["t"] + 1e-3 \
                            and (best is None or w["start"]
                                 > self._windows[best]["start"]):
                        best = i
                if best is not None:
                    jobs_by_window.setdefault(best, set()).add(p["job"])
            for i, jobs in sorted(jobs_by_window.items()):
                w = self._windows[i]
                if w["start"] <= hi:
                    add("preempted-sample", w, jobs)
        # rule 2: fault window intersects the burn window
        for w in self._windows:
            end = hi if w["end"] is None else w["end"]
            if w["start"] <= hi and end >= lo:
                add("window-overlap", w)
        # rule 3: fault closed before the burn window opened, but the
        # effect (queued/delayed work retiring late) trails the cause
        for w in self._windows:
            end = w["end"]
            if end is not None and end < lo \
                    and end + self.lag_horizon_s >= lo:
                add("lagged", w)
        links.sort(key=lambda lk: (
            ("preempted-sample", "window-overlap",
             "lagged").index(lk["rule"]),
            lk["windowStart"], lk["primitive"]))
        return links

    # -- build -------------------------------------------------------------

    def build(self) -> dict:
        """The merged document: ``entries`` (time-ordered stream of
        fault / preemption / restart / alert records) and ``incidents``
        (one per alert onset, page severities carrying their causal
        fault links)."""
        entries = []
        for a in self._actions:
            entries.append({"t": a["t"], "type": "fault",
                            "primitive": a["primitive"],
                            "params": a["params"]})
        for p in self._preemptions:
            entries.append({"t": p["t"], "type": "preemption",
                            "job": p["job"],
                            "primitive": p["primitive"]})
        for r in self._restarts:
            entries.append({"t": r["t"], "type": "restart",
                            "job": r["job"],
                            "durationS": _r(r["end"] - r["t"])})
        for a in self._alerts:
            entries.append({"t": a["t"], "type": "alert",
                            "slo": a["slo"], "severity": a["severity"],
                            "event": a["event"],
                            "shortBurn": a["shortBurn"],
                            "longBurn": a["longBurn"]})
        entries.sort(key=lambda e: (e["t"], e["type"],
                                    e.get("slo", ""), e.get("job", ""),
                                    e.get("primitive", "")))

        incidents = []
        open_fires: dict = {}
        for a in self._alerts:
            key = (a["slo"], a["severity"])
            if a["event"] == "fire":
                inc = {
                    "slo": a["slo"], "severity": a["severity"],
                    "firedAt": a["t"], "clearedAt": None,
                    "durationS": None,
                    "shortBurn": a["shortBurn"],
                    "longBurn": a["longBurn"],
                    "links": (self._link_page(a["slo"], a["severity"],
                                              a["t"])
                              if a["severity"] == "page" else []),
                }
                lo, hi = self._burn_window(a["slo"], a["severity"],
                                           a["t"])
                inc["badSamplesInWindow"] = sum(
                    1 for s in self._bad_samples
                    if s["slo"] == a["slo"] and lo <= s["t"] <= hi)
                open_fires.setdefault(key, []).append(inc)
                incidents.append(inc)
            elif a["event"] == "clear":
                stack = open_fires.get(key) or []
                if stack:
                    inc = stack.pop(0)
                    inc["clearedAt"] = a["t"]
                    inc["durationS"] = _r(a["t"] - inc["firedAt"])
        incidents.sort(key=lambda i: (i["firedAt"], i["slo"],
                                      i["severity"]))
        pages = [i for i in incidents if i["severity"] == "page"]
        return {
            "entries": entries,
            "incidents": incidents,
            "summary": {
                "entries": len(entries),
                "faults": len(self._actions),
                "fault_windows": len(self._windows),
                "preemptions": len(self._preemptions),
                "restart_rounds": len(self._restarts),
                "bad_samples": len(self._bad_samples),
                "incidents": len(incidents),
                "pages": len(pages),
                "pages_linked": sum(1 for p in pages if p["links"]),
                "pages_unlinked": sum(1 for p in pages
                                      if not p["links"]),
                "links_total": sum(len(p["links"]) for p in pages),
                "unresolved_incidents": sum(
                    1 for i in incidents if i["clearedAt"] is None),
            },
        }
