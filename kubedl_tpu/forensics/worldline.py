"""WAL time-travel: the store at any resourceVersion, and object history.

A :class:`WorldLine` is a read-only view over a journal directory
(docs/durability.md). The journal already holds everything needed to
answer "what did the world look like at rv N": recovery's own recipe —
newest parseable snapshot at or below N, plus a replay of every WAL
record with ``snap_rv < rv <= N`` — generalized from "N = the newest
acknowledged write" to any rv the retained generations cover. The reader
is :meth:`Journal.iter_records`, the same public read side recovery and
future WAL followers use; this module never parses a WAL line itself.

Coverage: with the journal's default pruning only the newest retained
checkpoint's world onward is reconstructible (older snapshot bases are
gone); with ``Journal(retain_all=True)`` — the forensics retention mode
every campaign replay runs under — the worldline reaches rv 1. Asking
below the horizon raises :class:`HistoryUnavailable` (a ``ValueError``:
the console maps it to a client error, not a crash).
"""

from __future__ import annotations

from typing import Optional

from ..core.journal import Journal


def _fmt_key(k: tuple) -> str:
    return "/".join(k)


class HistoryUnavailable(ValueError):
    """The asked rv predates the retained journal generations (the
    checkpoint pruned the WAL files that covered it). Re-run with
    ``Journal(retain_all=True)`` to keep the full worldline."""


class WorldLine:
    """Time-travel reads over one journal directory.

    Stateless between calls — every query re-resolves the on-disk
    generations, so a live journal (the operator still appending) is
    safe to inspect: at worst a query sees the world as of its own
    read, exactly like any other snapshot-isolated reader."""

    def __init__(self, journal_dir: str):
        self.journal = Journal(journal_dir)
        #: provenance of the last ``at()`` reconstruction — the same
        #: shape as ``Journal.recovered_from`` (docs/durability.md)
        self.reconstructed_from: dict = {}

    # -- coverage ----------------------------------------------------------

    def head_rv(self) -> int:
        """Highest rv the retained generations know about."""
        head = max((rv for rv, _ in self.journal.snapshots()), default=0)
        for rec in self.journal.iter_records():
            head = max(head, int(rec["rv"]))
        return head

    def snapshot_rvs(self) -> list:
        """rvs of the on-disk snapshot generations (time-travel anchor
        points), ascending."""
        return [rv for rv, _ in self.journal.snapshots()]

    def _full_history(self) -> bool:
        """Whether the retained WAL files reach back to rv 0 (journal
        birth generation still on disk — no checkpoint ever pruned, or
        retain_all mode)."""
        wals = self.journal.wal_generations()
        return bool(wals) and wals[0][0] == 0

    def _base_for(self, rv: int) -> tuple:
        """``(base_rv, {key: obj})`` to replay from for a target rv:
        the newest parseable snapshot at or below rv, else rv 0 when the
        WAL reaches journal birth."""
        for srv, path in reversed(self.journal.snapshots()):
            if srv > rv:
                continue
            try:
                return srv, path, self.journal.read_snapshot(path)[1]
            except (OSError, ValueError, KeyError):
                continue           # torn snapshot: fall back a generation
        if self._full_history():
            return 0, None, {}
        raise HistoryUnavailable(
            f"rv {rv} predates the retained journal history (no "
            f"snapshot <= {rv} and the WAL birth generation was pruned); "
            f"run the journal with retain_all=True to keep the full "
            f"worldline")

    # -- reconstruction ----------------------------------------------------

    def at(self, rv: int) -> dict:
        """The exact ``{(kind, ns, name): obj}`` store at resourceVersion
        ``rv`` — bit-for-bit what a live store held after committing that
        rv (rvs above the head return the head world). Torn WAL tails
        are tolerated exactly like recovery."""
        rv = int(rv)
        if rv < 0:
            raise ValueError(f"rv must be >= 0, got {rv}")
        base_rv, snap_path, objs = self._base_for(rv)
        objs = dict(objs)
        counts: dict = {}
        applied_max = base_rv
        for rec in self.journal.iter_records(from_rv=base_rv, to_rv=rv,
                                             counts=counts):
            k = tuple(rec["k"])
            if rec["t"] == "c":
                objs[k] = rec["o"]
            elif rec["t"] == "d":
                objs.pop(k, None)
            applied_max = max(applied_max, int(rec["rv"]))
        self.reconstructed_from = {
            "rv": rv,
            "snapshot_rv": base_rv if snap_path is not None else None,
            "wal_records": counts.get("records", 0),
            "torn_records": counts.get("torn", 0),
            "objects": len(objs),
            "applied_rv": applied_max,
        }
        return objs

    def world_summary(self, rv: int) -> dict:
        """The console's rendering of :meth:`at`: object count, per-kind
        counts, and the reconstruction provenance (the objects themselves
        are one drill-down away via :meth:`object_history`). One WAL
        scan serves both the reconstruction and ``headRv`` — calling
        ``at(rv)`` + ``head_rv()`` would parse every retained record
        twice per console hit."""
        rv = int(rv)
        if rv < 0:
            raise ValueError(f"rv must be >= 0, got {rv}")
        base_rv, snap_path, objs = self._base_for(rv)
        objs = dict(objs)
        counts: dict = {}
        applied = 0
        applied_max = base_rv
        head = max((srv for srv, _ in self.journal.snapshots()),
                   default=0)
        for rec in self.journal.iter_records(from_rv=base_rv,
                                             counts=counts):
            r = int(rec["rv"])
            head = max(head, r)
            if r > rv:
                continue
            k = tuple(rec["k"])
            if rec["t"] == "c":
                objs[k] = rec["o"]
            elif rec["t"] == "d":
                objs.pop(k, None)
            applied += 1
            applied_max = max(applied_max, r)
        self.reconstructed_from = {
            "rv": rv,
            "snapshot_rv": base_rv if snap_path is not None else None,
            "wal_records": applied,
            "torn_records": counts.get("torn", 0),
            "objects": len(objs),
            "applied_rv": applied_max,
        }
        by_kind: dict[str, int] = {}
        for k in objs:
            by_kind[k[0]] = by_kind.get(k[0], 0) + 1
        return {
            "rv": rv,
            "headRv": head,
            "objects": len(objs),
            "byKind": dict(sorted(by_kind.items())),
            "keys": sorted(_fmt_key(k) for k in objs),
            "reconstructedFrom": dict(self.reconstructed_from),
        }

    def diff(self, rv_a: int, rv_b: int) -> dict:
        """Object-level delta between two worldline points: keys added,
        removed, and changed (any content difference) going a -> b."""
        wa, wb = self.at(rv_a), self.at(rv_b)
        added = sorted(_fmt_key(k) for k in wb if k not in wa)
        removed = sorted(_fmt_key(k) for k in wa if k not in wb)
        changed = sorted(_fmt_key(k) for k in wb
                         if k in wa and wa[k] != wb[k])
        return {
            "fromRv": int(rv_a), "toRv": int(rv_b),
            "added": added, "removed": removed, "changed": changed,
            "unchanged": len(wb) - len(added) - len(changed),
        }

    # -- per-object history ------------------------------------------------

    def object_history(self, kind: str, namespace: str,
                       name: str) -> list:
        """Every retained commit/delete of one object, rv-ordered:
        ``{"rv", "ts", "op", "generation", "changed"}`` where ``op`` is
        create/update/delete, ``ts`` is the WAL record's store-clock
        stamp (None for pre-forensics records), and ``changed`` names
        which of spec/status/metadata moved vs the previous retained
        version. History starts at the oldest reconstructible world —
        an object born before the horizon opens with a synthetic
        ``op: "snapshot"`` entry (its pre-history is pruned)."""
        key = (kind, namespace, name)
        base_rv = 0
        prev: Optional[dict] = None
        if not self._full_history():
            for srv, path in self.journal.snapshots():
                try:
                    base_rv, objs = self.journal.read_snapshot(path)
                except (OSError, ValueError, KeyError):
                    continue
                prev = objs.get(key)
                break
        out = []
        if prev is not None:
            out.append({
                "rv": int((prev.get("metadata") or {})
                          .get("resourceVersion") or base_rv),
                "ts": None, "op": "snapshot",
                "generation": (prev.get("metadata") or {})
                .get("generation"),
                "changed": ["pre-history"],
            })
        for rec in self.journal.iter_records(from_rv=base_rv):
            if tuple(rec["k"]) != key:
                continue
            ts = rec.get("ts")
            if rec["t"] == "d":
                out.append({"rv": int(rec["rv"]), "ts": ts,
                            "op": "delete", "generation": None,
                            "changed": []})
                prev = None
                continue
            obj = rec["o"]
            if prev is None:
                op = "create"
                changed = []
            else:
                op = "update"
                changed = []
                if obj.get("spec") != prev.get("spec"):
                    changed.append("spec")
                if obj.get("status") != prev.get("status"):
                    changed.append("status")
                if not changed:
                    body = {k: v for k, v in obj.items()
                            if k not in ("metadata", "spec", "status")}
                    prev_body = {k: v for k, v in prev.items()
                                 if k not in ("metadata", "spec",
                                              "status")}
                    changed.append("other" if body != prev_body
                                   else "metadata")
            out.append({"rv": int(rec["rv"]), "ts": ts, "op": op,
                        "generation": (obj.get("metadata") or {})
                        .get("generation"),
                        "changed": changed})
            prev = obj
        return out
