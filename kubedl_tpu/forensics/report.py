"""Deterministic campaign postmortems: JSON block + rendered markdown.

:func:`build_postmortem` folds one campaign replay's timeline document
(:mod:`timeline`) together with the campaign identity into the
``forensics`` block the adversarial scorecard commits per seed
(docs/chaos.md, docs/forensics.md "postmortem schema") — floats rounded,
keys sorted at serialization, no wall clocks, so a fixed seed reproduces
it bit for bit and the in-run determinism gate covers it for free.

:func:`render_postmortem_md` renders one block as a human postmortem;
``python -m kubedl_tpu.forensics.report [ARTIFACT.json]`` renders every
seed of a committed ``BENCH_CLUSTER_ADVERSARIAL.json`` (the ``make
postmortem`` target).
"""

from __future__ import annotations

from typing import Optional


def build_postmortem(scenario: str, seed: int, fingerprint: str,
                     timeline_doc: dict,
                     slo_health: Optional[dict] = None) -> dict:
    """One seed's forensics block: campaign identity + the merged
    timeline + its incident table and link summary. ``slo_health`` is
    the replay's stranded/budget rollup, embedded so the rendered
    postmortem is self-contained."""
    return {
        "scenario": scenario,
        "seed": int(seed),
        "campaign_fingerprint": fingerprint,
        "summary": dict(timeline_doc["summary"]),
        "incidents": list(timeline_doc["incidents"]),
        "timeline": list(timeline_doc["entries"]),
        "slo_health": dict(slo_health or {}),
    }


def _fmt_t(t) -> str:
    if t is None:
        return "-"
    t = float(t)
    h, rem = divmod(int(round(t)), 3600)
    mnt, s = divmod(rem, 60)
    return f"{h:d}:{mnt:02d}:{s:02d}"


def _fmt_params(params) -> str:
    return ", ".join(f"{k}={v}" for k, v in params) if params else ""


def render_postmortem_md(pm: dict) -> str:
    """Markdown postmortem for one seed's forensics block. Pure
    function of the block — rendering the committed artifact twice
    yields identical bytes."""
    s = pm["summary"]
    lines = [
        f"# Postmortem: `{pm['scenario']}` campaign, seed {pm['seed']}",
        "",
        f"Campaign fingerprint: `{pm['campaign_fingerprint'][:16]}`",
        "",
        "## Summary",
        "",
        f"- **{s['pages']} page(s)** fired ({s['incidents']} alert "
        f"onsets total), {s['pages_linked']} causally linked to "
        f"injected faults, {s['pages_unlinked']} unlinked",
        f"- {s['faults']} fault actions across {s['fault_windows']} "
        f"windows; {s['preemptions']} gang preemptions; "
        f"{s['restart_rounds']} restart rounds",
        f"- {s['bad_samples']} bad SLO samples attributed; "
        f"{s['unresolved_incidents']} incident(s) never cleared",
    ]
    health = pm.get("slo_health") or {}
    if health:
        lines.append(
            f"- budgets survived: min remaining "
            f"{health.get('min_budget_remaining')}, stranded alerts "
            f"{health.get('stranded_alerts')}, stranded conditions "
            f"{health.get('stranded_conditions')}")
    lines += ["", "## Incidents", ""]
    if not pm["incidents"]:
        lines.append("None fired.")
    for i, inc in enumerate(pm["incidents"], 1):
        lines += [
            f"### {i}. `{inc['slo']}` {inc['severity']} at "
            f"{_fmt_t(inc['firedAt'])}",
            "",
            f"- fired {_fmt_t(inc['firedAt'])}, cleared "
            f"{_fmt_t(inc['clearedAt'])}"
            + (f" ({_fmt_t(inc['durationS'])} on fire)"
               if inc['durationS'] is not None else " (never cleared)"),
            f"- burn at onset: short {inc['shortBurn']}, long "
            f"{inc['longBurn']}; {inc['badSamplesInWindow']} bad "
            f"sample(s) in the burn window",
        ]
        if inc["links"]:
            lines.append("- caused by:")
            for lk in inc["links"]:
                jobs = (f" (evidence: {', '.join(lk['evidenceJobs'])})"
                        if lk["evidenceJobs"] else "")
                window = (f"{_fmt_t(lk['windowStart'])}"
                          + (f"–{_fmt_t(lk['windowEnd'])}"
                             if lk["windowEnd"] is not None
                             and lk["windowEnd"] != lk["windowStart"]
                             else ""))
                lines.append(f"  - `{lk['primitive']}` [{window}] via "
                             f"rule `{lk['rule']}`{jobs}")
        elif inc["severity"] == "page":
            lines.append("- **UNLINKED**: no injected fault explains "
                         "this page (investigate)")
        lines.append("")
    lines += ["## Timeline", "",
              "| t | type | detail |", "|---|---|---|"]
    for e in pm["timeline"]:
        if e["type"] == "fault":
            detail = f"`{e['primitive']}` {_fmt_params(e['params'])}"
        elif e["type"] == "preemption":
            detail = f"gang `{e['job']}` evicted by `{e['primitive']}`"
        elif e["type"] == "restart":
            detail = f"`{e['job']}` restart round ({e['durationS']}s)"
        else:
            detail = (f"`{e['slo']}` {e['severity']} {e['event']} "
                      f"(burn short={e['shortBurn']} "
                      f"long={e['longBurn']})")
        lines.append(f"| {_fmt_t(e['t'])} | {e['type']} | {detail} |")
    lines.append("")
    return "\n".join(lines)


def render_artifact(doc: dict) -> str:
    """Render every seed's forensics block of a committed adversarial
    scorecard (``BENCH_CLUSTER_ADVERSARIAL.json``) into one markdown
    document."""
    out = []
    seeds = doc.get("seeds") or {}
    # seed keys are stringified ints; lexicographic order would put
    # "10" before "2"
    for seed in sorted(seeds, key=int):
        pm = seeds[seed].get("forensics")
        if not pm:
            out.append(f"# seed {seed}: no forensics block (regenerate "
                       f"with `make bench-cluster-adversarial`)\n")
            continue
        out.append(render_postmortem_md(pm))
    return "\n".join(out)


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        description="Render the committed adversarial scorecard's "
                    "forensics blocks as markdown postmortems "
                    "(docs/forensics.md).")
    ap.add_argument("artifact", nargs="?",
                    default="BENCH_CLUSTER_ADVERSARIAL.json")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    args = ap.parse_args(argv)
    with open(args.artifact) as f:
        doc = json.load(f)
    text = render_artifact(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
