"""Platform-service controllers (SURVEY.md §1.6).

The reference groups these under ``controllers/{model,serving,notebook,
cache,apps,persist}``: everything that is not a training-job controller —
model registry + image build, inference serving, notebooks, dataset cache,
cron, record persistence.
"""

from .models import (  # noqa: F401
    ModelReconciler,
    ModelVersionReconciler,
    add_model_path_env,
    provider_for,
)
from .serving import InferenceReconciler  # noqa: F401
