"""Code sync: git-sync init containers injected into every replica.

Behavioral analog of ``pkg/code_sync`` (reference ``sync_handler.go:34-75``,
``git_sync_handler.go:20-70``): a job annotated with
``kubedl.io/git-sync-config`` (a JSON blob) gets

* a ``git-sync-code`` init container that clones the repo once into a shared
  ``emptyDir`` volume, and
* a volume mount of the checked-out tree under each main container's
  ``workingDir/<dest>`` (subPath = dest), so training code lands next to the
  entrypoint.

The handler seam is kept (``CodeSyncHandler`` interface in the reference) so
other sources (GCS buckets on TPU VMs) plug in beside git.
"""

from __future__ import annotations

import copy
import json
import shlex
from typing import Optional

from ..api import common as c
from ..core import meta as m

DEFAULT_CODE_ROOT = "/code"
DEFAULT_GIT_SYNC_IMAGE = "kubedl/git-sync:v1"
DEFAULT_MAX_FAILURES = 3
DEFAULT_GCS_SYNC_IMAGE = "google/cloud-sdk:slim"


class CodeSyncError(ValueError):
    pass


def dest_from_source(source: str, fallback: str = "code") -> str:
    """Last path segment of a git/GCS/OSS source URL, ``.git`` stripped —
    the default checkout/sync directory name (shared with the dataset-cache
    warm-up, which syncs with the same one-shot rsync contract)."""
    cleaned = source.split("://", 1)[-1]
    parts = [p for p in cleaned.strip("/").split("/") if p]
    dest = parts[-1] if parts else fallback
    return dest[:-4] if dest.endswith(".git") else dest


_dest_from_source = dest_from_source


def gcs_rsync_command(source: str, dest_dir: str) -> str:
    """The one-shot GCS sync shell line used by both code-sync init
    containers and dataset-cache warm-up pods. Source/dest come from
    user-controlled spec fields, so they are shell-quoted."""
    src, dst = shlex.quote(source), shlex.quote(dest_dir)
    return f"mkdir -p {dst} && gsutil -m rsync -r {src} {dst}"


def _git_init_container(opts: dict, volume_name: str) -> tuple[dict, str]:
    """Returns (init container, dest path). Env contract is the upstream
    kubernetes/git-sync one (git_sync_handler.go:85-140)."""
    source = opts.get("source") or ""
    if not source:
        raise CodeSyncError("git-sync-config requires 'source'")
    root = opts.get("rootPath") or DEFAULT_CODE_ROOT
    dest = opts.get("destPath") or _dest_from_source(source)
    envs = list(opts.get("envs") or [])
    envs += [
        {"name": "GIT_SYNC_REPO", "value": source},
        # one-shot clone: without this the init container never exits
        {"name": "GIT_SYNC_ONE_TIME", "value": "true"},
        {"name": "GIT_SYNC_ROOT", "value": root},
        {"name": "GIT_SYNC_DEST", "value": dest},
        {"name": "GIT_SYNC_MAX_SYNC_FAILURES",
         "value": str(opts.get("maxFailures") or DEFAULT_MAX_FAILURES)},
    ]
    if opts.get("branch"):
        envs.append({"name": "GIT_SYNC_BRANCH", "value": opts["branch"]})
    if opts.get("revision"):
        envs.append({"name": "GIT_SYNC_REV", "value": opts["revision"]})
    if opts.get("depth"):
        envs.append({"name": "GIT_SYNC_DEPTH", "value": str(opts["depth"])})
    if opts.get("ssh"):
        envs.append({"name": "GIT_SYNC_SSH", "value": "true"})
        if opts.get("sshFile"):
            envs.append({"name": "GIT_SSH_KEY_FILE", "value": opts["sshFile"]})
    if opts.get("user"):
        envs.append({"name": "GIT_SYNC_USERNAME", "value": opts["user"]})
    if opts.get("password"):
        envs.append({"name": "GIT_SYNC_PASSWORD", "value": opts["password"]})
    ctr = {
        "name": "git-sync-code",
        "image": opts.get("image") or DEFAULT_GIT_SYNC_IMAGE,
        "imagePullPolicy": "IfNotPresent",
        "env": envs,
        "volumeMounts": [{"name": volume_name, "mountPath": root}],
    }
    return ctr, dest


def _gcs_init_container(opts: dict, volume_name: str) -> tuple[dict, str]:
    """TPU-native source: one-shot ``gsutil rsync`` of a GCS prefix — the
    natural code/data channel on Cloud TPU VMs (no git credentials needed
    when the node SA has storage.objectViewer)."""
    source = opts.get("source") or ""
    if not source.startswith("gs://"):
        raise CodeSyncError("gcs-sync-config requires a gs:// 'source'")
    root = opts.get("rootPath") or DEFAULT_CODE_ROOT
    dest = opts.get("destPath") or _dest_from_source(source)
    ctr = {
        "name": "gcs-sync-code",
        "image": opts.get("image") or DEFAULT_GCS_SYNC_IMAGE,
        "imagePullPolicy": "IfNotPresent",
        "command": ["/bin/sh", "-c", gcs_rsync_command(source, f"{root}/{dest}")],
        "env": list(opts.get("envs") or []),
        "volumeMounts": [{"name": volume_name, "mountPath": root}],
    }
    return ctr, dest


_HANDLERS = {
    c.ANNOTATION_GIT_SYNC_CONFIG: ("git-sync", _git_init_container),
    c.ANNOTATION_GCS_SYNC_CONFIG: ("gcs-sync", _gcs_init_container),
}


def inject_code_sync_init_containers(job: dict, replica_specs: dict) -> None:
    """Mutates every replica template in ``replica_specs`` (the raw spec
    dicts) in memory, once per reconcile (reference ``job.go:110``).
    Idempotent: skips replicas that already carry the init container."""
    ann = m.annotations(job)
    for annotation, (volume_name, handler) in _HANDLERS.items():
        cfg = ann.get(annotation)
        if not cfg:
            continue
        try:
            opts = json.loads(cfg)
        except json.JSONDecodeError as e:
            raise CodeSyncError(f"bad {annotation} annotation: {e}") from e
        init_ctr, dest = handler(opts, volume_name)
        volume = {"name": volume_name, "emptyDir": {}}
        for spec in replica_specs.values():
            pod_spec = m.get_in(spec, "template", "spec")
            if not pod_spec or not pod_spec.get("containers"):
                continue
            inits = pod_spec.setdefault("initContainers", [])
            if any(x.get("name") == init_ctr["name"] for x in inits):
                continue
            ctr = copy.deepcopy(init_ctr)
            # init container inherits the main container's resources so it
            # schedules onto the same node class (sync_handler.go:58)
            if pod_spec["containers"][0].get("resources"):
                ctr["resources"] = copy.deepcopy(
                    pod_spec["containers"][0]["resources"])
            inits.append(ctr)
            vols = pod_spec.setdefault("volumes", [])
            if not any(v.get("name") == volume_name for v in vols):
                vols.append(copy.deepcopy(volume))
            for main in pod_spec["containers"]:
                mounts = main.setdefault("volumeMounts", [])
                if any(x.get("name") == volume_name for x in mounts):
                    continue
                workdir = main.get("workingDir", "")
                mounts.append({
                    "name": volume_name,
                    "readOnly": False,
                    "mountPath": _join(workdir, dest),
                    "subPath": dest,
                })


def _join(workdir: str, dest: str) -> str:
    if not workdir:
        return "/" + dest
    return workdir.rstrip("/") + "/" + dest


def code_sync_enabled(job: dict) -> bool:
    ann = m.annotations(job)
    return any(k in ann for k in _HANDLERS)
