"""Model registry: Model / ModelVersion CRDs + image build.

The capability mirror of reference ``controllers/model`` +
``apis/model/v1alpha1``: every successful training job can emit a
``ModelVersion``; the controller bakes the exported artifacts into an OCI
image (reference: a **Kaniko** pod, ``modelversion_controller.go:374-457``)
and records it in ``status.image``, so serving simply runs that image.

TPU-native redesign: artifacts on TPU VMs live on **GCS** (that is where
Orbax checkpoints go), so a ``gcs`` storage flavor is first-class here: the
build pod fuse-mounts the bucket at ``/workspace/build`` — no PV/PVC
staging hop. Local host-disk and NFS (Filestore) flavors keep the
reference's PV → PVC → build-pod pipeline
(``modelversion_controller.go:245-330``).
"""

from __future__ import annotations

import copy
from typing import Optional

from ..core import meta as m
from ..core.apiserver import APIServer, AlreadyExists, Conflict, NotFound
from ..core.manager import Reconciler, Request, Result

# env key the training container reads to know where to export the model
# (reference apis/model/v1alpha1/modelversion_types.go:24-25)
MODEL_PATH_ENV = "KUBEDL_MODEL_PATH"
# where artifacts land inside the built image (modelversion_types.go:27-28)
DEFAULT_MODEL_PATH_IN_IMAGE = "/kubedl-model"

IMAGE_BUILDING = "ImageBuilding"
IMAGE_BUILD_FAILED = "ImageBuildFailed"
IMAGE_BUILD_SUCCEEDED = "ImageBuildSucceeded"

MODEL_API_VERSION = "model.kubedl.io/v1alpha1"
DEFAULT_IMAGE_BUILDER = "gcr.io/kaniko-project/executor:latest"


# ---------------------------------------------------------------------------
# Storage providers (reference controllers/model/storage/storage_provider.go)
# ---------------------------------------------------------------------------

class StorageProvider:
    """Where model artifacts live while being trained and built."""

    def create_persistent_volume(self, storage: dict, pv_name: str) -> Optional[dict]:
        """PV staging the artifacts for the build pod; None = not needed."""
        return None

    def add_model_volume(self, pod_template: dict, storage: dict) -> None:
        """Mount the artifact location into every container of a pod."""
        raise NotImplementedError

    def mount_path(self, storage: dict) -> str:
        raise NotImplementedError

    def build_volume(self, storage: dict, mv: dict) -> dict:
        """Volume the build pod mounts at ``/workspace/build`` so the shared
        dockerfile's ``COPY build/`` sees the artifacts. Local/NFS flavors
        stage through the PVC; GCS fuse-mounts the bucket directly."""
        return {"name": "build-source",
                "persistentVolumeClaim": {"claimName": pvc_name_for(mv)}}

    def needs_pvc(self) -> bool:
        return True


def _mount_all_containers(pod_template: dict, volume: dict, mount_path: str) -> None:
    spec = pod_template.setdefault("spec", {})
    vols = spec.setdefault("volumes", [])
    if not any(v.get("name") == volume["name"] for v in vols):
        vols.append(volume)
    for container in spec.get("containers", []) or []:
        mounts = container.setdefault("volumeMounts", [])
        if not any(vm.get("name") == volume["name"] for vm in mounts):
            mounts.append({"name": volume["name"], "mountPath": mount_path})


class LocalStorageProvider(StorageProvider):
    """TPU-VM host disk (reference local_storage_provider.go): hostPath
    volume pinned to one node via PV node affinity."""

    def create_persistent_volume(self, storage, pv_name):
        ls = storage["localStorage"]
        return {
            "apiVersion": "v1", "kind": "PersistentVolume",
            "metadata": {"name": pv_name},
            "spec": {
                "accessModes": ["ReadWriteMany"],
                "persistentVolumeReclaimPolicy": "Retain",
                "capacity": {"storage": "500Mi"},
                "storageClassName": "",
                "local": {"path": ls["path"]},
                "nodeAffinity": {"required": {"nodeSelectorTerms": [{
                    "matchExpressions": [{
                        "key": "kubernetes.io/hostname",
                        "operator": "In",
                        "values": [ls.get("nodeName", "")],
                    }]}]}},
            },
        }

    def add_model_volume(self, pod_template, storage):
        ls = storage["localStorage"]
        _mount_all_containers(
            pod_template,
            {"name": "modelvolume", "hostPath": {"path": ls["path"]}},
            self.mount_path(storage))

    def mount_path(self, storage):
        return storage["localStorage"].get("mountPath") or DEFAULT_MODEL_PATH_IN_IMAGE


class NFSProvider(StorageProvider):
    """NFS / GCP Filestore (reference nfs_provider.go)."""

    def create_persistent_volume(self, storage, pv_name):
        nfs = storage["nfs"]
        return {
            "apiVersion": "v1", "kind": "PersistentVolume",
            "metadata": {"name": pv_name},
            "spec": {
                "accessModes": ["ReadWriteMany"],
                "persistentVolumeReclaimPolicy": "Retain",
                "capacity": {"storage": "30Gi"},
                "storageClassName": "",
                "nfs": {"server": nfs.get("server", ""),
                        "path": nfs.get("path", "/")},
            },
        }

    def add_model_volume(self, pod_template, storage):
        nfs = storage["nfs"]
        _mount_all_containers(
            pod_template,
            {"name": "modelvolume",
             "nfs": {"server": nfs.get("server", ""), "path": nfs.get("path", "/")}},
            self.mount_path(storage))

    def mount_path(self, storage):
        return storage["nfs"].get("mountPath") or DEFAULT_MODEL_PATH_IN_IMAGE


class GCSProvider(StorageProvider):
    """TPU-native primary flavor: artifacts on GCS (where Orbax checkpoints
    land), mounted through the GKE gcsfuse CSI driver for both training and
    the build pod — no PV/PVC staging copy."""

    @staticmethod
    def _fuse_volume(name: str, gcs: dict) -> dict:
        """gcsfuse CSI volume scoped to gcs.path via only-dir, so training,
        build, and serving all see the same directory."""
        attrs = {"bucketName": gcs.get("bucket", "")}
        path = (gcs.get("path") or "").strip("/")
        opts = "implicit-dirs"
        if path:
            opts += f",only-dir={path}"
        attrs["mountOptions"] = opts
        return {"name": name,
                "csi": {"driver": "gcsfuse.csi.storage.gke.io",
                        "volumeAttributes": attrs}}

    def add_model_volume(self, pod_template, storage):
        gcs = storage["gcs"]
        md = pod_template.setdefault("metadata", {})
        ann = md.setdefault("annotations", {})
        ann.setdefault("gke-gcsfuse/volumes", "true")
        _mount_all_containers(pod_template,
                              self._fuse_volume("modelvolume", gcs),
                              self.mount_path(storage))

    def mount_path(self, storage):
        return storage["gcs"].get("mountPath") or DEFAULT_MODEL_PATH_IN_IMAGE

    def build_volume(self, storage, mv):
        return self._fuse_volume("build-source", storage["gcs"])

    def needs_pvc(self) -> bool:
        return False


_PROVIDERS = {
    "localStorage": LocalStorageProvider(),
    "nfs": NFSProvider(),
    "gcs": GCSProvider(),
}


def provider_for(storage: Optional[dict]) -> Optional[StorageProvider]:
    """Pick by which storage flavor is set (storage_provider.go:27-39)."""
    for key, provider in _PROVIDERS.items():
        if storage and storage.get(key) is not None:
            return provider
    return None


def add_model_path_env(replicas_raw: dict, mv_spec: dict) -> None:
    """Inject ``KUBEDL_MODEL_PATH`` + the artifact volume into every replica
    template of a job carrying ``spec.modelVersion`` (reference
    ``pkg/job_controller/job.go:471-498``). Idempotent."""
    provider = provider_for(mv_spec.get("storage"))
    if provider is None:
        return
    path = provider.mount_path(mv_spec["storage"])
    for spec in replicas_raw.values():
        template = spec.setdefault("template", {})
        for container in m.get_in(template, "spec", "containers", default=[]) or []:
            env = container.setdefault("env", [])
            if not any(e.get("name") == MODEL_PATH_ENV for e in env):
                env.append({"name": MODEL_PATH_ENV, "value": path})
        provider.add_model_volume(template, mv_spec["storage"])


# ---------------------------------------------------------------------------
# ModelVersion controller
# ---------------------------------------------------------------------------

def pv_name_for(mv: dict) -> str:
    return f"mv-pv-{m.name(mv)}"


def pvc_name_for(mv: dict) -> str:
    return f"mv-pvc-{m.name(mv)}"


def build_pod_name_for(mv: dict) -> str:
    return f"image-build-{m.name(mv)}"


class ModelVersionReconciler(Reconciler):
    """ModelVersion → image-build pod → status.image
    (reference ``controllers/model/modelversion_controller.go:67-225``)."""

    kind = "ModelVersion"
    owns = ("Pod",)

    def __init__(self, api: APIServer, recorder=None,
                 image_builder: str = DEFAULT_IMAGE_BUILDER):
        self.api = api
        self.recorder = recorder
        self.image_builder = image_builder

    def reconcile(self, req: Request) -> Optional[Result]:
        mv = self.api.try_get(self.kind, req.namespace, req.name)
        if mv is None or m.is_deleting(mv):
            return None
        phase = m.get_in(mv, "status", "imageBuildPhase")
        if phase in (IMAGE_BUILD_SUCCEEDED, IMAGE_BUILD_FAILED):
            return None

        spec = mv.get("spec", {})
        storage = spec.get("storage")
        provider = provider_for(storage)
        if provider is None:
            # permanent config error: fail before creating any side objects
            self._set_status(mv, IMAGE_BUILD_FAILED,
                             message="modelVersion has no recognized storage "
                                     "(gcs/localStorage/nfs)")
            return None

        model = self._ensure_model(mv)
        self._own_by_model(mv, model)

        tag = spec.get("imageTag") or m.uid(mv)[:5]
        image = f"{spec.get('imageRepo', '')}:{tag}"

        pod = self.api.try_get("Pod", req.namespace, build_pod_name_for(mv))
        if pod is None:
            self._ensure_dockerfile_configmap(req.namespace)
            if provider.needs_pvc():
                self._ensure_pv_and_pvc(mv, storage, provider)
            pod = self._create_build_pod(mv, image,
                                         provider.build_volume(storage, mv))
            self._set_status(mv, IMAGE_BUILDING,
                             message=f"building image {image}")
            return None

        pod_phase = m.get_in(pod, "status", "phase")
        if pod_phase == "Succeeded":
            # Model.status.latestVersion follows via the ModelReconciler,
            # which this status MODIFIED event reaches through the Model
            # owner ref added in _own_by_model
            self._set_status(mv, IMAGE_BUILD_SUCCEEDED, image=image,
                             finished=True)
        elif pod_phase == "Failed":
            msg = m.get_in(pod, "status", "message",
                           default="image build pod failed")
            self._set_status(mv, IMAGE_BUILD_FAILED, message=msg,
                             finished=True)
        else:
            self._set_status(mv, IMAGE_BUILDING,
                             message=f"building image {image}")
        return None

    # -- pieces -----------------------------------------------------------

    def _ensure_model(self, mv: dict) -> dict:
        """Create the parent Model on first version (utils.go analog).
        When the version omits modelName, the Model is named after the
        version and the name is written back so the ModelReconciler's
        version filter matches it later."""
        model_name = m.get_in(mv, "spec", "modelName", default="")
        if not model_name:
            model_name = m.name(mv)
            mv.setdefault("spec", {})["modelName"] = model_name
            try:
                updated = self.api.update(mv)
                mv.clear()
                mv.update(updated)
            except (Conflict, NotFound):
                pass
        model = self.api.try_get("Model", m.namespace(mv), model_name)
        if model is None:
            model = m.new_obj(MODEL_API_VERSION, "Model", model_name,
                              m.namespace(mv), spec={})
            try:
                model = self.api.create(model)
            except AlreadyExists:
                model = self.api.get("Model", m.namespace(mv), model_name)
        return model

    def _own_by_model(self, mv: dict, model: dict) -> None:
        """Model owns its versions so deleting a Model GCs them
        (modelversion_controller.go:351-377). A job-created version keeps
        the job as controller owner; the Model is appended as an extra
        owner, exactly like the reference."""
        refs = m.owner_references(mv)
        if any(r.get("uid") == m.uid(model) for r in refs):
            return
        if m.get_controller_ref(mv):
            refs.append(m.owner_ref(model, controller=False))
        else:
            m.set_controller_ref(mv, model)
        try:
            self.api.update(mv)
        except (Conflict, NotFound):
            pass

    def _ensure_dockerfile_configmap(self, namespace: str) -> None:
        if self.api.try_get("ConfigMap", namespace, "dockerfile") is not None:
            return
        cm = m.new_obj("v1", "ConfigMap", "dockerfile", namespace)
        cm["data"] = {
            "dockerfile": ("FROM busybox\n"
                           f"COPY build/ {DEFAULT_MODEL_PATH_IN_IMAGE}\n"),
        }
        try:
            self.api.create(cm)
        except AlreadyExists:
            pass

    def _ensure_pv_and_pvc(self, mv: dict, storage: dict,
                           provider: StorageProvider) -> None:
        ns = m.namespace(mv)
        pv_name, pvc_name = pv_name_for(mv), pvc_name_for(mv)
        if self.api.try_get("PersistentVolume", "default", pv_name) is None:
            pv = provider.create_persistent_volume(storage, pv_name)
            if pv is not None:
                pv.setdefault("metadata", {}).setdefault("namespace", "default")
                try:
                    self.api.create(pv)
                except AlreadyExists:
                    pass
        if self.api.try_get("PersistentVolumeClaim", ns, pvc_name) is None:
            pvc = m.new_obj("v1", "PersistentVolumeClaim", pvc_name, ns)
            pvc["spec"] = {
                "accessModes": ["ReadWriteMany"],
                "storageClassName": "",
                "volumeName": pv_name,
                "resources": {"requests": {"storage": "500Mi"}},
            }
            m.set_controller_ref(pvc, mv)
            try:
                self.api.create(pvc)
            except AlreadyExists:
                pass

    def _create_build_pod(self, mv: dict, image: str,
                          build_volume: dict) -> dict:
        """The Kaniko-analog builder pod (modelversion_controller.go:374-457).
        The artifact source is always mounted at ``/workspace/build`` so the
        shared dockerfile's ``COPY build/`` works for every flavor."""
        ns = m.namespace(mv)
        pod = m.new_obj("v1", "Pod", build_pod_name_for(mv), ns)
        container = {
            "name": "image-build",
            "image": self.image_builder,
            "args": ["--dockerfile=/workspace/dockerfile",
                     "--context=dir:///workspace/",
                     f"--destination={image}"],
            "volumeMounts": [
                {"name": "kaniko-secret", "mountPath": "/kaniko/.docker"},
                {"name": "dockerfile", "mountPath": "/workspace/"},
                {"name": "build-source", "mountPath": "/workspace/build"},
            ],
        }
        volumes = [
            {"name": "kaniko-secret",
             "secret": {"secretName": "regcred",
                        "items": [{"key": ".dockerconfigjson",
                                   "path": "config.json"}]}},
            {"name": "dockerfile",
             "configMap": {"name": "dockerfile"}},
            build_volume,
        ]
        if build_volume.get("csi", {}).get("driver", "").startswith("gcsfuse"):
            m.annotations(pod)["gke-gcsfuse/volumes"] = "true"
        pod["spec"] = {"restartPolicy": "Never",
                       "containers": [container], "volumes": volumes}
        m.set_controller_ref(pod, mv)
        try:
            return self.api.create(pod)
        except AlreadyExists:
            return self.api.get("Pod", ns, m.name(pod))

    def _set_status(self, mv: dict, phase: str, image: str = "",
                    message: str = "", finished: bool = False) -> None:
        status = dict(mv.get("status", {}) or {})
        new = {"imageBuildPhase": phase}
        if image:
            new["image"] = image
        if message:
            new["message"] = message
        if finished and not status.get("finishTime"):
            new["finishTime"] = m.rfc3339(self.api.now())
        if all(status.get(k) == v for k, v in new.items()):
            return
        if self.recorder is not None and status.get("imageBuildPhase") != phase:
            event_type = ("Warning" if phase == IMAGE_BUILD_FAILED
                          else "Normal")
            self.recorder.event(mv, event_type, phase,
                                message or f"image build {phase}")
        status.update(new)
        mv["status"] = status
        try:
            self.api.update_status(mv)
        except (Conflict, NotFound):
            pass


class ModelReconciler(Reconciler):
    """Keeps ``Model.status.latestVersion`` honest when versions come and go
    (the reference folds this into the ModelVersion controller; a dedicated
    reconciler also heals after out-of-band version deletion)."""

    kind = "Model"
    owns = ("ModelVersion",)

    def __init__(self, api: APIServer):
        self.api = api

    def reconcile(self, req: Request) -> Optional[Result]:
        model = self.api.try_get(self.kind, req.namespace, req.name)
        if model is None or m.is_deleting(model):
            return None
        versions = [
            v for v in self.api.list("ModelVersion", req.namespace)
            if (m.get_in(v, "spec", "modelName") == req.name
                or m.is_controlled_by(v, model))
            and m.get_in(v, "status", "imageBuildPhase") == IMAGE_BUILD_SUCCEEDED
        ]
        if not versions:
            latest = None
        else:
            newest = max(versions,
                         key=lambda v: (m.get_in(v, "status", "finishTime",
                                                 default="") or "",
                                        m.name(v)))
            latest = {"modelVersion": m.name(newest),
                      "imageName": m.get_in(newest, "status", "image",
                                            default="")}
        if m.get_in(model, "status", "latestVersion") == latest:
            return None
        status = model.setdefault("status", {})
        if latest is None:
            status.pop("latestVersion", None)
        else:
            status["latestVersion"] = latest
        try:
            self.api.update_status(model)
        except (Conflict, NotFound):
            pass
        return None


def build_model_version_spec(job: dict, mv_spec: dict, pods=()) -> dict:
    """Normalize a job's ``spec.modelVersion`` into a ModelVersion spec.

    For localStorage, the node that actually holds the artifacts is the one
    the master/chief ran on — resolved from the job's pods like the
    reference's ``GetNodeForModelOutput`` (``job.go:525-529``) — so the PV's
    node affinity pins the build pod to the right host."""
    spec = copy.deepcopy(mv_spec)
    spec.setdefault("createdBy", m.name(job))
    spec.setdefault("modelName", m.name(job))
    ls = m.get_in(spec, "storage", "localStorage")
    if ls is not None and not ls.get("nodeName"):
        node = node_for_model_output(pods)
        if node:
            ls["nodeName"] = node
    return spec


def node_for_model_output(pods) -> str:
    """The node of the master/chief pod, else worker-0's, else any index-0
    replica's — the rank that conventionally exports the model (reference
    ``GetNodeForModelOutput``)."""
    from ..api import common as c
    worker0, any0 = "", ""
    for pod in pods:
        lbls = m.get_labels(pod)
        node = m.get_in(pod, "spec", "nodeName", default="")
        if not node or lbls.get(c.LABEL_REPLICA_INDEX) != "0":
            continue
        rtype = lbls.get(c.LABEL_REPLICA_TYPE, "").lower()
        if rtype in ("master", "chief"):
            return node
        if rtype == "worker" and not worker0:
            worker0 = node
        if not any0:
            any0 = node
    return worker0 or any0
