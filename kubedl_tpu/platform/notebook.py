"""Notebook controller: Notebook CRD → pod + service + ingress.

Behavioral analog of ``controllers/notebook/notebook_controller.go:71-340``:
the CR carries a pod template; the controller runs it as ``nb-{name}`` with
the Jupyter port defaulted, fronts it with a service and an ingress at
``/notebooks/{ns}/{name}``, mirrors the pod phase into the Notebook
condition (Created/Running/Terminated), and publishes the reachable URL —
with the auth token passed through from the template env so the link works
first click.

TPU twist: a notebook template that requests ``google.com/tpu`` gets the
PJRT single-host env (one-process JAX on the notebook's own slice) so
``jax.devices()`` works out of the box.
"""

from __future__ import annotations

from typing import Optional

from ..api import common as c
from ..core import meta as m
from ..core.apiserver import AlreadyExists, Conflict, NotFound
from ..core.manager import Reconciler, Request, Result
from ..tpu import placement as pl

KIND = "Notebook"
API_VERSION = "notebook.kubedl.io/v1alpha1"
CONTAINER_NAME = "notebook"
PORT_NAME = "notebook"
DEFAULT_PORT = 8888

COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_TERMINATED = "Terminated"


def nb_name(notebook_name: str) -> str:
    return "nb-" + notebook_name


def ingress_path(notebook: dict) -> str:
    return f"/notebooks/{m.namespace(notebook)}/{m.name(notebook)}"


class NotebookReconciler(Reconciler):
    kind = KIND
    owns = ("Pod", "Service", "Ingress")

    def __init__(self, api, recorder=None):
        self.api = api
        self.recorder = recorder

    def reconcile(self, req: Request) -> Optional[Result]:
        nb = self.api.try_get(KIND, req.namespace, req.name)
        if nb is None or m.is_deleting(nb):
            return None
        pod = self._sync_pod(nb)
        self._sync_service(nb)
        self._sync_ingress(nb)
        return self._update_status(nb, pod)

    # -- children ---------------------------------------------------------

    def _sync_pod(self, nb: dict) -> dict:
        name, ns = nb_name(m.name(nb)), m.namespace(nb)
        pod = self.api.try_get("Pod", ns, name)
        if pod is not None:
            return pod
        import copy
        template = copy.deepcopy(m.get_in(nb, "spec", "template") or {})
        pod_spec = template.get("spec") or {}
        containers = pod_spec.setdefault("containers", [])
        if not containers:
            containers.append({"name": CONTAINER_NAME,
                               "image": "jupyter/base-notebook:latest"})
        ctr = _main_container(pod_spec)
        ports = ctr.setdefault("ports", [])
        if not any(p.get("name") == PORT_NAME for p in ports):
            ports.append({"name": PORT_NAME, "containerPort": DEFAULT_PORT})
        # jupyter must serve under the ingress path or every redirect 404s
        pl.upsert_env(ctr, "NOTEBOOK_ARGS",
                      f"--NotebookApp.base_url={ingress_path(nb)}")
        # TPU twist: a template requesting chips gets single-host PJRT env
        # so jax.devices() in the notebook finds its slice out of the box
        res = ctr.get("resources") or {}
        if any("google.com/tpu" in (res.get(k) or {})
               for k in ("limits", "requests")):
            pl.upsert_env(ctr, pl.ENV_TPU_WORKER_ID, 0)
            pl.upsert_env(ctr, pl.ENV_TPU_WORKER_HOSTNAMES, "localhost")
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": name, "namespace": ns,
                "labels": {**(template.get("metadata", {}).get("labels") or {}),
                           c.LABEL_JOB_NAME: m.name(nb),
                           c.LABEL_REPLICA_TYPE: "notebook"},
            },
            "spec": pod_spec,
        }
        m.set_controller_ref(pod, nb)
        try:
            self.api.create(pod)
        except AlreadyExists:
            pass
        return self.api.get("Pod", ns, name)

    def _sync_service(self, nb: dict) -> None:
        name, ns = nb_name(m.name(nb)), m.namespace(nb)
        if self.api.try_get("Service", ns, name) is not None:
            return
        sel = {c.LABEL_JOB_NAME: m.name(nb), c.LABEL_REPLICA_TYPE: "notebook"}
        svc = m.new_obj("v1", "Service", name, ns, labels=sel)
        svc["spec"] = {
            "selector": sel,
            "ports": [{"name": PORT_NAME, "port": DEFAULT_PORT,
                       "targetPort": PORT_NAME}],
        }
        m.set_controller_ref(svc, nb)
        try:
            self.api.create(svc)
        except AlreadyExists:
            pass

    def _sync_ingress(self, nb: dict) -> None:
        name, ns = nb_name(m.name(nb)), m.namespace(nb)
        if self.api.try_get("Ingress", ns, name) is not None:
            return
        ing = m.new_obj("networking.k8s.io/v1", "Ingress", name, ns)
        ing["spec"] = {"rules": [{"http": {"paths": [{
            "path": ingress_path(nb), "pathType": "Prefix",
            "backend": {"service": {"name": name,
                                    "port": {"number": DEFAULT_PORT}}},
        }]}}]}
        m.set_controller_ref(ing, nb)
        try:
            self.api.create(ing)
        except AlreadyExists:
            pass

    # -- status -----------------------------------------------------------

    def _update_status(self, nb: dict, pod: dict) -> Optional[Result]:
        phase = m.get_in(pod, "status", "phase", default="Pending")
        cond, msg, requeue = COND_CREATED, f"created notebook pod {m.name(pod)}", True
        if phase == "Running":
            cond, msg, requeue = (COND_RUNNING,
                                  f"notebook pod {m.name(pod)} is running", False)
        elif phase in ("Failed", "Succeeded"):
            cond, msg, requeue = (COND_TERMINATED,
                                  f"notebook pod {m.name(pod)} terminated: {phase}",
                                  False)
        status = nb.setdefault("status", {})
        # recompute the url while Running on every pass, not only on the
        # condition transition: the ingress LB host typically lands *after*
        # the pod went Running, and the published link must pick it up
        url = self._url(nb, pod) if cond == COND_RUNNING else status.get("url")
        if status.get("condition") != cond or status.get("url") != url:
            status["condition"] = cond
            status["message"] = msg
            status["lastTransitionTime"] = m.rfc3339(self.api.now())
            if url:
                status["url"] = url
            try:
                self.api.update_status(nb)
            except (Conflict, NotFound):
                return Result(requeue=True)
        return Result(requeue_after=2.0) if requeue else None

    def _url(self, nb: dict, pod: dict) -> str:
        ing = self.api.try_get("Ingress", m.namespace(nb), nb_name(m.name(nb)))
        host = ""
        if ing is not None:
            lbs = m.get_in(ing, "status", "loadBalancer", "ingress",
                           default=[]) or []
            if lbs:
                host = lbs[0].get("hostname") or lbs[0].get("ip") or ""
            if not host:
                host = m.get_in(ing, "spec", "rules", default=[{}])[0].get("host", "")
        url = f"http://{host}{ingress_path(nb)}" if host else ingress_path(nb)
        # auth token passthrough: surface the template's token in the URL so
        # the published link opens without a login prompt
        ctr = _main_container(pod.get("spec", {}))
        token = pl.get_env(ctr, "JUPYTER_TOKEN") if ctr else None
        if token:
            url += f"?token={token}"
        return url


def _main_container(pod_spec: dict) -> Optional[dict]:
    containers = pod_spec.get("containers") or []
    for ctr in containers:
        if ctr.get("name") == CONTAINER_NAME:
            return ctr
    return containers[0] if containers else None
