"""TensorBoard sidecar-jobs.

Behavioral analog of ``pkg/tensorboard/tensorboard.go:55-386``: a job
annotated with ``kubedl.io/tensorboard-config`` (JSON: logDir,
ttlSecondsAfterJobFinished, image, ingressSpec{host,pathPrefix,annotations},
updateTimestamp) gets one TensorBoard pod + headless service + optional
ingress, owned by the job. After the job finishes, the trio lives on for the
configured TTL (profile triage window), then the annotation is stripped and
everything is garbage-collected on the next pass.

TPU twist: the default command serves both scalars and **XProf profiles** —
JAX's ``jax.profiler.start_trace(logdir)`` writes traces under
``<logdir>/plugins/profile``, which stock TensorBoard picks up from the same
``--logdir``, so one config covers loss curves and TPU traces.
"""

from __future__ import annotations

import copy
import json
from typing import Optional

from ..api import common as c
from ..core import meta as m
from ..core.apiserver import AlreadyExists, Conflict, NotFound
from ..tpu import placement as pl
from ..utils import status as st

TB_REPLICA_TYPE = "tensorboard"
TB_PORT = 6006
DEFAULT_TB_IMAGE = "tensorflow/tensorflow:2.9.1"


def get_config(job: dict) -> Optional[dict]:
    cfg = m.annotations(job).get(c.ANNOTATION_TENSORBOARD_CONFIG)
    if cfg is None:
        return None
    try:
        return json.loads(cfg)
    except json.JSONDecodeError:
        return None


def reconcile_tensorboard(api, job: dict, job_status, master_spec: dict,
                          recorder=None, dns_domain: str = "",
                          had_config: bool = True) -> Optional[float]:
    """Sync (or TTL-reap) the job's TensorBoard trio. Returns a
    requeue-after in seconds while waiting out the TTL, else None.
    ``master_spec`` is the replica template the TB pod is derived from
    (node tolerations/volumes follow the master, reference syncPod).
    ``had_config=False`` skips the reap lookups for jobs that never carried
    the annotation (the common case — saves 3 GETs/job/pass)."""
    cfg_raw = m.annotations(job).get(c.ANNOTATION_TENSORBOARD_CONFIG)
    if cfg_raw is None:
        if had_config:
            _delete_all(api, job)
        return None
    opts = get_config(job)
    if opts is None:
        return None  # unparseable config: leave user artifacts alone

    # TTL after job finish (tensorboard.go:99-135): config updates after
    # completion restart the clock so users can re-open a finished job's TB
    if st.is_finished(job_status):
        finished = m.parse_rfc3339(job_status.completion_time)
        updated = m.parse_rfc3339(opts.get("updateTimestamp"))
        if finished is None:
            return None
        base = max(finished, updated or 0.0)
        delete_at = base + float(opts.get("ttlSecondsAfterJobFinished") or 0)
        now = api.now()
        if now >= delete_at:
            fresh = api.try_get(m.kind(job), m.namespace(job), m.name(job))
            if fresh is not None:
                m.annotations(fresh).pop(c.ANNOTATION_TENSORBOARD_CONFIG, None)
                try:
                    api.update(fresh)
                except (Conflict, NotFound):
                    pass
            _delete_all(api, job)
            return None
        _try_sync(api, job, opts, cfg_raw, master_spec, recorder)
        return delete_at - now

    _try_sync(api, job, opts, cfg_raw, master_spec, recorder)
    return None


def _try_sync(api, job, opts, cfg_raw, master_spec, recorder) -> None:
    """An ownership conflict on the TB pod must not wedge the whole job
    reconcile — record it and move on."""
    try:
        _sync(api, job, opts, cfg_raw, master_spec)
    except ValueError as e:
        if recorder is not None:
            recorder.event(job, "Warning", "TensorBoardConflict", str(e))


def tb_resource_name(job_name: str) -> str:
    """Public naming seam: the pod/service/ingress name this subsystem
    gives a job's TensorBoard (the console's status/reapply routes resolve
    the same name)."""
    return pl.replica_name(job_name, TB_REPLICA_TYPE, 0)


def _name(job: dict) -> str:
    return tb_resource_name(m.name(job))


def _labels(job: dict) -> dict:
    return {
        c.LABEL_REPLICA_TYPE: TB_REPLICA_TYPE,
        c.LABEL_REPLICA_INDEX: "0",
        c.LABEL_REPLICA_NAME: _name(job),
        c.LABEL_JOB_NAME: m.name(job),
    }


def _sync(api, job: dict, opts: dict, cfg_raw: str, master_spec: dict) -> None:
    _sync_pod(api, job, opts, cfg_raw, master_spec)
    _sync_service(api, job)
    _sync_ingress(api, job, opts)


def _sync_pod(api, job: dict, opts: dict, cfg_raw: str, master_spec: dict) -> None:
    name = _name(job)
    existing = api.try_get("Pod", m.namespace(job), name)
    if existing is not None:
        ref = m.get_controller_ref(existing)
        if not ref or ref.get("uid") != m.uid(job):
            raise ValueError(f"TensorBoard pod {name} is owned by someone else")
        # config change (ignoring updateTimestamp) -> recreate
        old = None
        try:
            old = json.loads(m.annotations(existing).get(
                c.ANNOTATION_TENSORBOARD_CONFIG, "null"))
        except json.JSONDecodeError:
            pass
        a, b = dict(opts), dict(old or {})
        a.pop("updateTimestamp", None)
        b.pop("updateTimestamp", None)
        if a == b:
            return
        try:
            api.delete("Pod", m.namespace(job), name)
        except NotFound:
            pass

    template = copy.deepcopy(m.get_in(master_spec, "template") or {})
    pod_spec = template.get("spec") or {"containers": [{"name": "tensorboard"}]}
    pod_spec["restartPolicy"] = "Always"
    # the viewer must not inherit trainer-side machinery: injected init
    # containers (code-sync) carry the trainer's TPU resource requests and
    # would pin a TPU host (or pend forever) for a UI pod
    pod_spec.pop("initContainers", None)
    path_prefix = _path_prefix(job, opts)
    containers = pod_spec.get("containers") or [{"name": "tensorboard"}]
    tb = containers[0]
    tb["name"] = "tensorboard"
    tb["command"] = [
        "/bin/sh", "-c",
        f"python -m tensorboard.main --logdir {opts.get('logDir', '/logs')} "
        f"--path_prefix {path_prefix} --host 0.0.0.0 --port {TB_PORT}",
    ]
    if opts.get("image"):
        tb["image"] = opts["image"]
    elif not tb.get("image"):
        tb["image"] = DEFAULT_TB_IMAGE
    # TB is a viewer: drop trainer resources so it never requests TPU chips
    # (the reference strips GPU visibility the same way)
    tb.pop("resources", None)
    pod_spec["containers"] = [tb]
    pod_spec.pop("nodeSelector", None)
    tb["ports"] = [{"name": "tensorboard", "containerPort": TB_PORT}]

    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": name, "namespace": m.namespace(job),
            "labels": {**(template.get("metadata", {}).get("labels") or {}),
                       **_labels(job)},
            "annotations": {c.ANNOTATION_TENSORBOARD_CONFIG: cfg_raw},
        },
        "spec": pod_spec,
    }
    m.set_controller_ref(pod, job)
    try:
        api.create(pod)
    except AlreadyExists:
        pass


def _sync_service(api, job: dict) -> None:
    name = _name(job)
    if api.try_get("Service", m.namespace(job), name) is not None:
        return
    svc = m.new_obj("v1", "Service", name, m.namespace(job), labels=_labels(job))
    svc["spec"] = {
        "clusterIP": "None",
        "selector": _labels(job),
        "ports": [{"name": "tensorboard", "port": TB_PORT,
                   "targetPort": TB_PORT}],
    }
    m.set_controller_ref(svc, job)
    try:
        api.create(svc)
    except AlreadyExists:
        pass


def _path_prefix(job: dict, opts: dict) -> str:
    prefix = (opts.get("ingressSpec") or {}).get("pathPrefix") or ""
    parts = [p for p in prefix.split("/") if p]
    parts += [m.namespace(job), m.name(job)]
    return "/" + "/".join(parts)


def _sync_ingress(api, job: dict, opts: dict) -> None:
    ing_spec = opts.get("ingressSpec")
    if not ing_spec:
        return
    name = _name(job)
    if api.try_get("Ingress", m.namespace(job), name) is not None:
        return
    path = _path_prefix(job, opts)
    rule: dict = {"http": {"paths": [{
        "path": path, "pathType": "Prefix",
        "backend": {"service": {"name": name,
                                "port": {"number": TB_PORT}}},
    }]}}
    if ing_spec.get("host"):
        rule["host"] = ing_spec["host"]
    ing = m.new_obj("networking.k8s.io/v1", "Ingress", name, m.namespace(job),
                    labels=_labels(job),
                    annotations=dict(ing_spec.get("annotations") or {}))
    ing["spec"] = {"rules": [rule]}
    m.set_controller_ref(ing, job)
    try:
        api.create(ing)
    except AlreadyExists:
        pass


def _delete_all(api, job: dict) -> None:
    name = _name(job)
    for kind in ("Pod", "Service", "Ingress"):
        obj = api.try_get(kind, m.namespace(job), name)
        if obj is None:
            continue
        ref = m.get_controller_ref(obj)
        if ref and ref.get("uid") == m.uid(job):
            try:
                api.delete(kind, m.namespace(job), name)
            except NotFound:
                pass
