"""Dataset cache: the CacheBackend CRD, cache-engine plugins, and the job
engine's mount integration.

Behavioral analog of ``apis/cache/v1alpha1`` + ``pkg/cache_backend`` +
``controllers/cache`` + the job-engine hooks at
``pkg/job_controller/job_controller.go:202-315``:

* a job spec carries an inline ``cacheBackend`` (mountPath + dataset
  sources + engine choice); the engine creates a ``CacheBackend`` CR owned
  by the job and records its name in job status,
* the CacheBackend controller drives an engine plugin until a PVC with the
  cache's name exists (status CacheCreating → PVCCreating → PVCCreated),
* once the PVC exists the job engine mounts it into every replica at
  ``mountPath`` and injects ``KUBEDL_CACHE_NAME``; until then the job waits.

Engine plugins (the ``CacheEngine`` seam, reference ``interface.go:9-13``):

* ``hostDisk`` — TPU-native default. TPU VMs ship large local NVMe; instead
  of an Alluxio tier the engine renders a hostPath PV + PVC and a one-shot
  warm-up pod that ``gsutil rsync``-s each data source onto the host disk.
  Dataset locality comes from the gang scheduler placing the whole slice on
  the same hosts the warm-up ran on.
* ``fluid`` — parity plugin for clusters running Fluid: renders ``Dataset``
  + ``AlluxioRuntime`` CRs (``fluid/fluidcache.go:35-120``) and lets Fluid's
  own controllers produce the PVC.
"""

from __future__ import annotations

import shlex
from typing import Optional

from ..core import meta as m
from ..core.apiserver import AlreadyExists, Conflict, NotFound
from ..core.manager import Reconciler, Request, Result
from .codesync import dest_from_source, gcs_rsync_command


class CacheError(Exception):
    """Permanent cache-config failure; the job engine fails the job on it."""

# status progression (reference cachebackend_types.go / cache_backend consts)
CACHE_CREATING = "CacheCreating"
PVC_CREATING = "PVCCreating"
PVC_CREATED = "PVCCreated"
CACHE_FAILED = "CacheFailed"

ENV_CACHE_NAME = "KUBEDL_CACHE_NAME"
CACHE_VOLUME_NAME = "cachevolume"
API_VERSION = "cache.kubedl.io/v1alpha1"
KIND = "CacheBackend"

DEFAULT_HOST_CACHE_ROOT = "/mnt/stateful_partition/kubedl-cache"
DEFAULT_WARMUP_IMAGE = "google/cloud-sdk:slim"


def get_cache_name(job: dict) -> str:
    return f"{m.name(job)}-cache"


# ---------------------------------------------------------------------------
# engine plugins
# ---------------------------------------------------------------------------

class CacheEngine:
    name = ""

    def __init__(self, api):
        self.api = api

    def create_cache_job(self, cache_backend: dict) -> None:
        raise NotImplementedError

    def _create_owned(self, obj: dict, owner: dict) -> None:
        m.set_controller_ref(obj, owner)
        try:
            self.api.create(obj)
        except AlreadyExists:
            pass


class HostDiskEngine(CacheEngine):
    """hostPath PV/PVC + one-shot GCS warm-up pod on the TPU VM's local disk."""

    name = "hostDisk"

    def create_cache_job(self, cache_backend: dict) -> None:
        name, ns = m.name(cache_backend), m.namespace(cache_backend)
        opts = m.get_in(cache_backend, "spec", "cacheEngine", "hostDisk",
                        default={}) or {}
        root = opts.get("path") or DEFAULT_HOST_CACHE_ROOT
        host_path = f"{root.rstrip('/')}/{ns}/{name}"
        capacity = opts.get("capacity") or "100Gi"
        if self.api.try_get("PersistentVolume", ns, name) is None:
            pv = m.new_obj("v1", "PersistentVolume", name, ns)
            pv["spec"] = {
                "capacity": {"storage": capacity},
                "accessModes": ["ReadOnlyMany"],
                "hostPath": {"path": host_path},
                "persistentVolumeReclaimPolicy": "Delete",
                "storageClassName": "kubedl-host-cache",
            }
            self._create_owned(pv, cache_backend)
        if self.api.try_get("PersistentVolumeClaim", ns, name) is None:
            pvc = m.new_obj("v1", "PersistentVolumeClaim", name, ns)
            pvc["spec"] = {
                "accessModes": ["ReadOnlyMany"],
                "resources": {"requests": {"storage": capacity}},
                "storageClassName": "kubedl-host-cache",
                "volumeName": name,
            }
            self._create_owned(pvc, cache_backend)
        if self.api.try_get("Pod", ns, f"{name}-warmup") is None:
            sources = m.get_in(cache_backend, "spec", "dataset", "dataSources",
                               default=[]) or []
            cmds = []
            for src in sources:
                sub = src.get("subDirName") or dest_from_source(
                    src.get("location", ""), fallback="data")
                dst = f"/cache/{sub}"
                loc = src.get("location", "")
                # locations/dir names are user-controlled spec fields that
                # land in a /bin/sh -c string on a hostPath-mounted pod:
                # quote them
                if loc.startswith("gs://"):
                    cmds.append(gcs_rsync_command(loc, dst))
                else:
                    # non-GCS source: web/nfs fetch left to a custom image
                    cmds.append(f"mkdir -p {shlex.quote(dst)} "
                                f"&& echo skip {shlex.quote(loc)}")
            pod = m.new_obj("v1", "Pod", f"{name}-warmup", ns)
            pod["spec"] = {
                "restartPolicy": "OnFailure",
                "containers": [{
                    "name": "warmup",
                    "image": opts.get("warmupImage") or DEFAULT_WARMUP_IMAGE,
                    "command": ["/bin/sh", "-c", " && ".join(cmds) or "true"],
                    "volumeMounts": [{"name": "cache", "mountPath": "/cache"}],
                }],
                "volumes": [{"name": "cache",
                             "hostPath": {"path": host_path,
                                          "type": "DirectoryOrCreate"}}],
            }
            self._create_owned(pod, cache_backend)


class FluidEngine(CacheEngine):
    """Fluid parity: Dataset + AlluxioRuntime CRs named after the cache
    (``fluidcache.go:35-120``); Fluid's controllers then bind the PVC."""

    name = "fluid"

    def create_cache_job(self, cache_backend: dict) -> None:
        name, ns = m.name(cache_backend), m.namespace(cache_backend)
        if self.api.try_get("Dataset", ns, name) is None:
            mounts = []
            for src in m.get_in(cache_backend, "spec", "dataset", "dataSources",
                                default=[]) or []:
                mounts.append({"mountPoint": src.get("location", ""),
                               "name": src.get("subDirName", "")})
            ds = m.new_obj("data.fluid.io/v1alpha1", "Dataset", name, ns)
            ds["spec"] = {"mounts": mounts}
            self._create_owned(ds, cache_backend)
        fluid_opts = m.get_in(cache_backend, "spec", "cacheEngine", "fluid",
                              default={}) or {}
        runtime_opts = fluid_opts.get("alluxioRuntime")
        if runtime_opts and self.api.try_get("AlluxioRuntime", ns, name) is None:
            levels = [{"mediumtype": lv.get("mediumType", "MEM"),
                       "path": lv.get("cachePath", "/dev/shm"),
                       "quota": lv.get("quota", "1Gi")}
                      for lv in runtime_opts.get("tieredStorage", []) or []]
            rt = m.new_obj("data.fluid.io/v1alpha1", "AlluxioRuntime", name, ns)
            rt["spec"] = {"replicas": runtime_opts.get("replicas", 1),
                          "tieredstore": {"levels": levels}}
            self._create_owned(rt, cache_backend)


ENGINES = {e.name: e for e in (HostDiskEngine, FluidEngine)}


def select_engine(cache_backend: dict) -> Optional[str]:
    engine_spec = m.get_in(cache_backend, "spec", "cacheEngine", default={}) or {}
    for key in engine_spec:
        if key in ENGINES:
            return key
    return None


# ---------------------------------------------------------------------------
# CacheBackend controller
# ---------------------------------------------------------------------------

class CacheBackendReconciler(Reconciler):
    """Drives CacheBackend status to PVCCreated (reference
    ``cachebackend_controller.go:57-133``)."""

    kind = KIND
    owns = ("PersistentVolumeClaim", "Pod")

    def __init__(self, api, recorder=None):
        self.api = api
        self.recorder = recorder

    def reconcile(self, req: Request) -> Optional[Result]:
        cb = self.api.try_get(KIND, req.namespace, req.name)
        if cb is None or m.is_deleting(cb):
            return None
        status = cb.setdefault("status", {})
        if status.get("cacheStatus") == PVC_CREATED:
            return None
        if self.api.try_get("PersistentVolumeClaim", req.namespace,
                            req.name) is not None and self._warmup_done(cb):
            return self._set_status(cb, PVC_CREATED)
        engine_name = select_engine(cb)
        if engine_name is None:
            return self._set_status(cb, CACHE_FAILED)
        ENGINES[engine_name](self.api).create_cache_job(cb)
        if status.get("cacheStatus") != PVC_CREATING:
            return self._set_status(cb, PVC_CREATING, requeue=2.0)
        return Result(requeue_after=2.0)

    def _warmup_done(self, cb: dict) -> bool:
        """hostDisk creates its PVC immediately but data lands via the
        warm-up pod — the cache is ready only once that pod Succeeded, or
        the engine has no warm-up concept (fluid: PVC binding = ready)."""
        warm = self.api.try_get("Pod", m.namespace(cb),
                                f"{m.name(cb)}-warmup")
        if warm is None:
            return True
        return m.get_in(warm, "status", "phase", default="") == "Succeeded"

    def _set_status(self, cb: dict, s: str,
                    requeue: float = 0.0) -> Optional[Result]:
        cb["status"]["cacheStatus"] = s
        try:
            self.api.update_status(cb)
        except (Conflict, NotFound):
            return Result(requeue=True)
        return Result(requeue_after=requeue) if requeue else None


# ---------------------------------------------------------------------------
# job engine integration
# ---------------------------------------------------------------------------

def reconcile_job_cache(api, job: dict, cache_spec: dict, raw_specs: dict,
                        job_status) -> Optional[float]:
    """Create the job's CacheBackend and, once its PVC exists, mount it into
    every replica (reference ``job_controller.go:202-315``). Returns a
    requeue delay while the cache is still warming, else None."""
    name, ns = get_cache_name(job), m.namespace(job)
    cb = api.try_get(KIND, ns, name)
    if cb is None:
        cb = m.new_obj(API_VERSION, KIND, name, ns, spec=dict(cache_spec))
        m.set_controller_ref(cb, job)
        try:
            cb = api.create(cb)
        except AlreadyExists:
            cb = api.get(KIND, ns, name)
        cb["status"] = {"jobName": m.name(job), "cacheStatus": CACHE_CREATING}
        try:
            api.update_status(cb)
        except (Conflict, NotFound):
            pass
    job_status.cache_backend_name = name
    # gate on the controller's readiness verdict, not bare PVC existence:
    # hostDisk binds its PVC before the warm-up rsync finished
    if m.get_in(cb, "status", "cacheStatus", default="") != PVC_CREATED:
        cb = api.get(KIND, ns, name)
        cache_status = m.get_in(cb, "status", "cacheStatus", default="")
        if cache_status == CACHE_FAILED:
            raise CacheError(
                f"cache backend {name} failed: no usable cacheEngine in "
                f"{sorted(m.get_in(cb, 'spec', 'cacheEngine', default={}) or {})}")
        if cache_status != PVC_CREATED:
            return 2.0  # cache warming; hold off pod creation
    mount_path = cache_spec.get("mountPath") or "/dataset"
    for spec in raw_specs.values():
        pod_spec = m.get_in(spec, "template", "spec")
        if not pod_spec or not pod_spec.get("containers"):
            continue
        vols = pod_spec.setdefault("volumes", [])
        if not any(v.get("name") == CACHE_VOLUME_NAME for v in vols):
            vols.append({"name": CACHE_VOLUME_NAME,
                         "persistentVolumeClaim": {"claimName": name}})
        for ctr in pod_spec["containers"]:
            envs = ctr.setdefault("env", [])
            if not any(e.get("name") == ENV_CACHE_NAME for e in envs):
                envs.append({"name": ENV_CACHE_NAME, "value": name})
            mounts = ctr.setdefault("volumeMounts", [])
            if not any(x.get("name") == CACHE_VOLUME_NAME for x in mounts):
                mounts.append({"name": CACHE_VOLUME_NAME,
                               "mountPath": mount_path})
    return None


