"""Cron engine: scheduled instantiation of arbitrary workloads.

Capability mirror of reference ``controllers/apps`` + ``apis/apps/v1alpha1``:
a Cron CR embeds any workload (a raw object in ``spec.template.workload``)
and stamps out a fresh copy per schedule fire, with standard cron semantics —
concurrency policy Allow/Forbid/Replace, suspend, absolute deadline, history
limit (``cron_controller.go:109-200``). Training jobs carrying
``runPolicy.cronPolicy`` self-convert into one of these (the engine's
``_reconcile_cron``), so this controller is what actually runs them.
"""

from __future__ import annotations

import copy
from typing import Optional

from ..api import common as c
from ..core import meta as m
from ..core.apiserver import (AlreadyExists, APIServer, Conflict, Invalid,
                              NotFound)
from ..core.manager import Reconciler, Request, Result
from ..utils import cronschedule
from ..utils import status as st

DEFAULT_HISTORY_LIMIT = 10
# misses beyond this emit a warning and only the latest fires
# (kubernetes cronjob "TooManyMissedTimes" analog)
MAX_MISSED = 100


_parse_ts = m.parse_rfc3339


class CronReconciler(Reconciler):
    kind = "Cron"

    def __init__(self, api: APIServer, recorder=None, workload_kinds=()):
        self.api = api
        self.recorder = recorder
        # completion of spawned workloads routes back via their Cron owner ref
        self.owns = tuple(workload_kinds)

    def reconcile(self, req: Request) -> Optional[Result]:
        cron = self.api.try_get(self.kind, req.namespace, req.name)
        if cron is None or m.is_deleting(cron):
            return None
        now = self.api.now()
        status = copy.deepcopy(cron.get("status", {}) or {})
        actives = self._live_actives(cron, status)
        self._fold_finished_into_history(cron, status, actives)

        spec = cron.get("spec", {}) or {}
        result = None
        if not self._gated(cron, spec, now):
            result = self._schedule_next(cron, spec, status, actives, now)

        if cron.get("status") != status:
            cron["status"] = status
            try:
                self.api.update_status(cron)
            except (Conflict, NotFound):
                return Result(requeue=True)
        return result

    # ------------------------------------------------------------------

    def _live_actives(self, cron: dict, status: dict) -> list:
        """Resolve status.active refs to live workload objects, dropping
        refs to deleted workloads (listActiveWorkloads analog)."""
        live = []
        refs = status.get("active", []) or []
        kept = []
        for ref in refs:
            obj = self.api.try_get(ref.get("kind", ""), m.namespace(cron),
                                   ref.get("name", ""))
            if obj is not None:
                live.append(obj)
                kept.append(ref)
        status["active"] = kept
        return live

    def _fold_finished_into_history(self, cron: dict, status: dict,
                                    actives: list) -> None:
        """Finished workloads leave the active list and enter bounded
        history (refreshCronHistory + trimFinishedWorkloadsFromActiveList)."""
        history = status.get("history", []) or []
        known = {(h.get("object", {}).get("kind"), h.get("object", {}).get("name"))
                 for h in history}
        still_active = []
        for wl in actives:
            phase, finished = _workload_phase(wl)
            if not finished:
                still_active.append(wl)
                continue
            key = (m.kind(wl), m.name(wl))
            if key not in known:
                history.append({
                    "object": {"kind": m.kind(wl), "name": m.name(wl),
                               "apiGroup": m.api_version(wl).split("/")[0]},
                    "status": phase,
                    "created": m.meta(wl).get("creationTimestamp"),
                    "finished": m.get_in(wl, "status", "completionTime"),
                })
        status["active"] = [
            ref for ref in status.get("active", [])
            if ref.get("name") in {m.name(w) for w in still_active}]
        limit = m.get_in(cron, "spec", "historyLimit",
                         default=DEFAULT_HISTORY_LIMIT)
        history.sort(key=lambda h: h.get("created") or "")
        if limit is not None and len(history) > limit:
            # drop the oldest beyond the limit, and their objects with them
            # (limit may be 0 = keep nothing, so slice by count kept)
            drop = len(history) - limit
            for h in history[:drop]:
                obj = h.get("object", {})
                try:
                    self.api.delete(obj.get("kind", ""), m.namespace(cron),
                                    obj.get("name", ""))
                except NotFound:
                    pass
            history = history[drop:]
        status["history"] = history
        actives[:] = still_active

    def _gated(self, cron: dict, spec: dict, now: float) -> bool:
        if spec.get("suspend"):
            return True
        deadline = _parse_ts(spec.get("deadline"))
        if deadline is not None and now > deadline:
            self._event(cron, "Normal", "Deadline",
                        "cron has reached deadline and stopped scheduling")
            return True
        return False

    def _schedule_next(self, cron: dict, spec: dict, status: dict,
                       actives: list, now: float) -> Optional[Result]:
        try:
            sched = cronschedule.parse(spec.get("schedule", ""))
            earliest = (_parse_ts(status.get("lastScheduleTime"))
                        or _parse_ts(m.meta(cron).get("creationTimestamp"))
                        or now)
            fire, missed = None, 0
            t = earliest
            while True:
                nxt = sched.next_after(t)
                if nxt > now:
                    break
                fire, t = nxt, nxt
                missed += 1
                if missed > MAX_MISSED:
                    # long outage: skip the backlog entirely and resync so
                    # the cron keeps living (kubernetes "TooManyMissedTimes")
                    self._event(cron, "Warning", "TooManyMissedTimes",
                                f"too many missed start times "
                                f"(> {MAX_MISSED}); skipping the backlog")
                    fire = None
                    status["lastScheduleTime"] = m.rfc3339(now)
                    break

            next_wake = sched.next_after(now) - now
        except cronschedule.InvalidSchedule as e:
            # user error (unparseable, or parseable-but-unsatisfiable like
            # "0 0 30 2 *"): warn and wait for a spec update, don't retry-loop
            self._event(cron, "Warning", "InvalidSchedule",
                        f"invalid schedule {spec.get('schedule')!r}: {e}")
            return None
        if fire is None:
            return Result(requeue_after=max(next_wake, 1.0))

        policy = spec.get("concurrencyPolicy") or c.CONCURRENCY_ALLOW
        if policy == c.CONCURRENCY_FORBID and actives:
            self._event(cron, "Normal", "AlreadyActive",
                        "not starting: prior execution still running and "
                        "concurrency policy is Forbid")
            status["lastScheduleTime"] = m.rfc3339(fire)
            return Result(requeue_after=max(next_wake, 1.0))
        if policy == c.CONCURRENCY_REPLACE:
            for wl in actives:
                try:
                    self.api.delete(m.kind(wl), m.namespace(wl), m.name(wl))
                except NotFound:
                    pass
            status["active"] = []

        created = self._spawn_workload(cron, spec, fire)
        if created is not None:
            status.setdefault("active", []).append({
                "apiVersion": m.api_version(created),
                "kind": m.kind(created),
                "namespace": m.namespace(created),
                "name": m.name(created),
                "uid": m.uid(created),
            })
        status["lastScheduleTime"] = m.rfc3339(fire)
        return Result(requeue_after=max(next_wake, 1.0))

    def _spawn_workload(self, cron: dict, spec: dict,
                        fire: float) -> Optional[dict]:
        template = m.get_in(spec, "template", "workload")
        if not template:
            self._event(cron, "Warning", "EmptyTemplate",
                        "cron has no spec.template.workload")
            return None
        wl = copy.deepcopy(template)
        wmeta = wl.setdefault("metadata", {})
        # unique per fire time (getDefaultJobName analog)
        wmeta["name"] = f"{m.name(cron)}-{int(fire)}"
        wmeta["namespace"] = m.namespace(cron)
        lbls = wmeta.setdefault("labels", {})
        lbls[c.LABEL_CRON_NAME] = m.name(cron)
        m.set_controller_ref(wl, cron)
        try:
            created = self.api.create(wl)
        except AlreadyExists:
            return None  # this fire already spawned (idempotent re-run)
        except Invalid as e:
            # template rejected by admission: surface it and move on —
            # retry-looping would hammer the api-server every backoff tick
            # with the same doomed create until the user edits the Cron
            self._event(cron, "Warning", "InvalidWorkloadTemplate", str(e))
            return None
        self._event(cron, "Normal", "SuccessfulCreate",
                    f"created {m.kind(wl)} {wmeta['name']}")
        return created

    def _event(self, cron, etype, reason, msg):
        if self.recorder is not None:
            self.recorder.event(cron, etype, reason, msg)


def _workload_phase(wl: dict) -> tuple:
    """(phase, finished) from the workload's condition state machine
    (cron_utils.go IsWorkloadFinished)."""
    from ..api.common import JobStatus
    status = JobStatus.from_dict(wl.get("status"))
    if st.is_succeeded(status):
        return c.JOB_SUCCEEDED, True
    if st.is_failed(status):
        return c.JOB_FAILED, True
    return c.JOB_RUNNING, False
