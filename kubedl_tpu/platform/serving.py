"""Inference serving: the Inference CRD and its controller.

Capability mirror of reference ``controllers/serving`` +
``apis/serving/v1alpha1``: an Inference declares a backend framework and N
*predictors*, each pinned to a ModelVersion; every predictor becomes a
Deployment + Service, and with more than one predictor the controller
renders weighted canary routes (reference: an Istio VirtualService,
``inference_controller.go:216-259``).

TPU-native redesign:

* a ``JAXServing`` framework joins TFServing/Triton — it runs a JAX/PJRT
  server (``python -m kubedl_tpu.serving``) and gets ``PJRT_DEVICE=TPU``;
* an Inference may carry ``spec.tpuPolicy`` with a **single-host** slice
  (e.g. v5e-4): predictor replicas are independent one-host servers, so the
  controller renders chip resources + topology nodeSelectors per replica —
  scaling out serving means more independent slices, not a bigger gang;
* model loading prefers the GCS artifact path (gcsfuse volume straight from
  the bucket) and falls back to the reference's baked-image init-container
  loader for local/NFS-built images.
"""

from __future__ import annotations

from typing import Optional

from ..api import common as c
from ..core import meta as m
from ..core.apiserver import AlreadyExists, APIServer, Conflict, NotFound
from ..core.manager import Reconciler, Request, Result
from ..tpu import placement as pl
from .models import (DEFAULT_MODEL_PATH_IN_IMAGE, IMAGE_BUILD_SUCCEEDED,
                     MODEL_PATH_ENV)

FRAMEWORK_TF_SERVING = "TFServing"
FRAMEWORK_TRITON = "Triton"
FRAMEWORK_JAX = "JAXServing"

SERVING_API_VERSION = "serving.kubedl.io/v1alpha1"

#: Morphling-style chosen config (serving/autoconfig.py
#: ``MultiConfigResult.to_dict()["best"]`` JSON: batch/quantize/
#: speculativeK/kvBlock/poolBlocks); rendered into every predictor
#: container's env
ANNOTATION_AUTOCONFIG = "serving.kubedl.io/autoconfig"

_ISTIO_GATEWAY = "kubedl-serving-gateway"


def predictor_name(inf: dict, predictor: dict) -> str:
    """``{inference}-{predictor}`` (reference utils.go:25-27)."""
    return f"{m.name(inf)}-{predictor.get('name', '')}"


def predictor_host(inf: dict, predictor: dict) -> str:
    return f"{predictor_name(inf, predictor)}.{m.namespace(inf)}.svc"


def predictor_labels(inf: dict, predictor: dict) -> dict:
    return {c.LABEL_INFERENCE_NAME: m.name(inf),
            c.LABEL_PREDICTOR_NAME: predictor.get("name", "")}


# ---------------------------------------------------------------------------
# Framework setters (reference controllers/serving/framework/)
# ---------------------------------------------------------------------------

def _set_tf_serving(template: dict, mv: Optional[dict], model_path: str) -> None:
    """TFServing: MODEL_BASE_PATH/MODEL_NAME env (tfserving.go:42-55). The
    stock entrypoint loads ${MODEL_BASE_PATH}/${MODEL_NAME}, which equals
    model_path because the default path's last segment is modelName."""
    base = model_path.rsplit("/", 1)[0] if "/" in model_path else model_path
    for ct in m.get_in(template, "spec", "containers", default=[]) or []:
        pl.upsert_env(ct, "MODEL_BASE_PATH", base)
        if mv is not None:
            pl.upsert_env(ct, "MODEL_NAME",
                          m.get_in(mv, "spec", "modelName", default=""))


def _set_triton(template: dict, mv: Optional[dict], model_path: str) -> None:
    """Triton loads a model *repository* directory."""
    repo = model_path.rsplit("/", 1)[0] if "/" in model_path else model_path
    for ct in m.get_in(template, "spec", "containers", default=[]) or []:
        args = ct.setdefault("args", [])
        if not any(a.startswith("--model-repository") for a in args):
            args.append(f"--model-repository={repo}")


def _set_jax_serving(template: dict, mv: Optional[dict], model_path: str) -> None:
    """TPU-native predictor: ``python -m kubedl_tpu.serving`` reading the
    model artifacts from $KUBEDL_MODEL_PATH and the Morphling-chosen
    config from the KUBEDL_SERVING_* env (serving/__main__.py)."""
    for ct in m.get_in(template, "spec", "containers", default=[]) or []:
        pl.upsert_env(ct, "PJRT_DEVICE", "TPU")
        if mv is not None:
            pl.upsert_env(ct, "KUBEDL_MODEL_NAME",
                          m.get_in(mv, "spec", "modelName", default=""))
        if model_path:
            pl.upsert_env(ct, MODEL_PATH_ENV, model_path)
        # the Services render _DEFAULT_PORTS[JAXServing]; the entrypoint
        # must bind the same port or every predict gets conn-refused
        pl.upsert_env(ct, "KUBEDL_SERVING_PORT",
                      _DEFAULT_PORTS[FRAMEWORK_JAX])
        if not ct.get("command") and not ct.get("args"):
            ct["command"] = ["python", "-m", "kubedl_tpu.serving"]


FRAMEWORK_SETTERS = {
    FRAMEWORK_TF_SERVING: _set_tf_serving,
    FRAMEWORK_TRITON: _set_triton,
    FRAMEWORK_JAX: _set_jax_serving,
}

_DEFAULT_PORTS = {
    FRAMEWORK_TF_SERVING: 8080,
    FRAMEWORK_TRITON: 8000,
    FRAMEWORK_JAX: 8000,
}


def compute_traffic_ratios(predictors: list) -> dict:
    """Normalize trafficWeight over predictors to percentages summing to 100
    (reference inference_controller.go:339+). Unweighted specs split evenly;
    remainders go to the first predictors."""
    if not predictors:
        return {}
    weights = [max(0, int(p.get("trafficWeight") or 0)) for p in predictors]
    total = sum(weights)
    if total == 0:
        weights = [1] * len(predictors)
        total = len(predictors)
    pct = [w * 100 // total for w in weights]
    for i in range(100 - sum(pct)):
        pct[i % len(pct)] += 1
    return {p.get("name", ""): pc for p, pc in zip(predictors, pct)}


class InferenceReconciler(Reconciler):
    """Inference → per-predictor Deployment+Service (+ weighted routes)
    (reference ``inference_controller.go:93-145``)."""

    kind = "Inference"
    owns = ("Deployment", "Service", "VirtualService",
            "HorizontalPodAutoscaler")

    def __init__(self, api: APIServer, recorder=None):
        self.api = api
        self.recorder = recorder

    def reconcile(self, req: Request) -> Optional[Result]:
        inf = self.api.try_get(self.kind, req.namespace, req.name)
        if inf is None or m.is_deleting(inf):
            return None

        predictors = m.get_in(inf, "spec", "predictors", default=[]) or []
        status = {"inferenceEndpoint": f"{m.name(inf)}.{req.namespace}.svc",
                  "predictorStatuses": []}

        self._sync_entry_service(inf, predictors)

        requeue = False
        ready = []  # predictors with a live Deployment behind them
        for predictor in predictors:
            try:
                ps = self._sync_predictor(inf, predictor)
            except ValueError as e:
                # permanent spec error (e.g. multi-host tpuPolicy): surface
                # it in status instead of retry-looping forever
                status["failureMessage"] = str(e)
                if self.recorder is not None:
                    self.recorder.event(inf, "Warning", "InvalidInference",
                                        str(e))
                inf["status"] = status
                try:
                    self.api.update_status(inf)
                except (Conflict, NotFound):
                    pass
                return None
            if ps is None:
                requeue = True
                continue
            ready.append((predictor, ps))
            status["predictorStatuses"].append(ps)

        # traffic only ever routes to deployed predictors — a canary still
        # waiting on its image build must not receive (and blackhole) weight
        if len(ready) > 1:
            ratios = compute_traffic_ratios([p for p, _ in ready])
            for predictor, ps in ready:
                ps["trafficPercent"] = ratios.get(predictor.get("name", ""), 0)
            self._sync_traffic_split(inf, [p for p, _ in ready], ratios)
        else:
            # single live predictor: weighted routes would only blackhole
            try:
                self.api.delete("VirtualService", req.namespace, req.name)
            except NotFound:
                pass

        self._prune_removed_predictors(inf, predictors)

        if inf.get("status") != status:
            inf["status"] = status
            try:
                self.api.update_status(inf)
            except (Conflict, NotFound):
                return Result(requeue=True)
        return Result(requeue_after=2.0) if requeue else None

    # ------------------------------------------------------------------

    def _sync_entry_service(self, inf: dict, predictors: list) -> None:
        """Stable user-facing entry Service selecting all predictors of the
        inference (reference inference_controller.go:280-338)."""
        if self.api.try_get("Service", m.namespace(inf), m.name(inf)):
            return
        port = _DEFAULT_PORTS.get(m.get_in(inf, "spec", "framework",
                                           default=""), 8080)
        svc = m.new_obj("v1", "Service", m.name(inf), m.namespace(inf))
        svc["spec"] = {
            "selector": {c.LABEL_INFERENCE_NAME: m.name(inf)},
            "ports": [{"name": "serving", "port": port,
                       "targetPort": port}],
        }
        m.set_controller_ref(svc, inf)
        try:
            self.api.create(svc)
        except AlreadyExists:
            pass

    def _sync_predictor(self, inf: dict, predictor: dict) -> Optional[dict]:
        """Returns the predictor status, or None while gated on the model
        image build (reference inference_controller.go:150-205)."""
        ns = m.namespace(inf)
        mv = None
        if predictor.get("modelVersion"):
            mv = self.api.try_get("ModelVersion", ns, predictor["modelVersion"])
            if mv is None or m.get_in(mv, "status", "imageBuildPhase") \
                    != IMAGE_BUILD_SUCCEEDED:
                return None  # not built yet -> requeue

        name = predictor_name(inf, predictor)
        desired = self._render_deploy_spec(inf, predictor, mv)
        deploy = self.api.try_get("Deployment", ns, name)
        if deploy is None:
            deploy = self._create_predictor_deploy(inf, predictor, desired)
        else:
            if predictor.get("autoScale"):
                # the HPA owns the replica count: adopting the live value
                # keeps this diff from stomping every scale decision
                desired["replicas"] = m.get_in(
                    deploy, "spec", "replicas",
                    default=desired["replicas"])
            if deploy["spec"] != desired:
                # propagate every spec change (template, model version,
                # replicas), not just the replica count
                deploy["spec"] = desired
                try:
                    deploy = self.api.update(deploy)
                except (Conflict, NotFound):
                    pass
        self._ensure_predictor_service(inf, predictor)
        self._sync_autoscaler(inf, predictor)
        return {
            "name": predictor.get("name", ""),
            "replicas": int(m.get_in(deploy, "status", "replicas", default=0)),
            "readyReplicas": int(m.get_in(deploy, "status", "readyReplicas",
                                          default=0)),
            "endpoint": predictor_host(inf, predictor),
        }

    def _render_deploy_spec(self, inf: dict, predictor: dict,
                            mv: Optional[dict]) -> dict:
        import copy as _copy
        template = _copy.deepcopy(predictor.get("template", {}) or {})
        model_path = predictor.get("modelPath") or ""

        if mv is not None:
            if not model_path:
                # last segment must be the model name: TFServing resolves
                # ${MODEL_BASE_PATH}/${MODEL_NAME}
                model_name = (m.get_in(mv, "spec", "modelName", default="")
                              or m.name(mv))
                model_path = f"{DEFAULT_MODEL_PATH_IN_IMAGE}/{model_name}"
            storage = m.get_in(mv, "spec", "storage", default={}) or {}
            if storage.get("gcs"):
                # serve straight off the bucket: no image pull of artifacts
                from .models import provider_for
                gcs_storage = {"gcs": {**storage["gcs"],
                                       "mountPath": model_path}}
                provider_for(gcs_storage).add_model_volume(template, gcs_storage)
            else:
                self._add_model_loader(template, mv, model_path)
            for ct in m.get_in(template, "spec", "containers",
                               default=[]) or []:
                pl.upsert_env(ct, MODEL_PATH_ENV, model_path)

        setter = FRAMEWORK_SETTERS.get(
            m.get_in(inf, "spec", "framework", default=""))
        if setter is not None:
            setter(template, mv, model_path)

        self._apply_autoconfig(inf, template)
        self._apply_tpu_placement(inf, template)

        lbls = predictor_labels(inf, predictor)
        tmeta = template.setdefault("metadata", {})
        tmeta["labels"] = {**(tmeta.get("labels") or {}), **lbls}
        return {
            "replicas": int(predictor.get("replicas") or 1),
            "selector": {"matchLabels": dict(lbls)},
            "template": template,
            "strategy": {"type": "RollingUpdate"},
        }

    def _sync_autoscaler(self, inf: dict, predictor: dict) -> None:
        """``autoScale`` on a predictor renders a real autoscaling/v2
        HPA targeting the predictor Deployment. The reference merely
        stores an ObjectReference to an externally managed autoscaler
        (``apis/serving/v1alpha1/inference_types.go:114-118``); here the
        operator owns the child end to end — removing ``autoScale``
        deletes the HPA, and the Deployment diff adopts the live replica
        count so the two controllers never fight."""
        ns = m.namespace(inf)
        name = predictor_name(inf, predictor)
        spec = predictor.get("autoScale")
        existing = self.api.try_get("HorizontalPodAutoscaler", ns, name)
        if not spec:
            if existing is not None:
                try:
                    self.api.delete("HorizontalPodAutoscaler", ns, name)
                except NotFound:
                    pass
            return
        min_r = int(spec.get("minReplicas") or 1)
        max_r = int(spec.get("maxReplicas") or 0)
        if max_r < max(min_r, 1):
            if self.recorder is not None:
                self.recorder.event(
                    inf, "Warning", "InvalidAutoScale",
                    f"predictor {predictor.get('name', '')}: maxReplicas "
                    f"{max_r} < minReplicas {min_r}; autoscaler removed")
            if existing is not None:
                # a stale HPA would keep scaling with the OLD bounds —
                # worse than no autoscaler while the spec is invalid
                try:
                    self.api.delete("HorizontalPodAutoscaler", ns, name)
                except NotFound:
                    pass
            return
        desired = {
            "scaleTargetRef": {"apiVersion": "apps/v1",
                               "kind": "Deployment", "name": name},
            "minReplicas": min_r,
            "maxReplicas": max_r,
            "metrics": spec.get("metrics") or [{
                "type": "Resource",
                "resource": {"name": "cpu", "target": {
                    "type": "Utilization",
                    "averageUtilization": int(
                        spec.get("targetCPUUtilization") or 80)}}}],
        }
        if existing is None:
            hpa = m.new_obj("autoscaling/v2", "HorizontalPodAutoscaler",
                            name, ns)
            m.labels(hpa).update(predictor_labels(inf, predictor))
            hpa["spec"] = desired
            m.set_controller_ref(hpa, inf)
            try:
                self.api.create(hpa)
            except AlreadyExists:
                pass
        elif existing["spec"] != desired:
            existing["spec"] = desired
            try:
                self.api.update(existing)
            except (Conflict, NotFound):
                pass

    def _create_predictor_deploy(self, inf: dict, predictor: dict,
                                 spec: dict) -> dict:
        deploy = m.new_obj("apps/v1", "Deployment",
                           predictor_name(inf, predictor), m.namespace(inf))
        m.labels(deploy).update(predictor_labels(inf, predictor))
        deploy["spec"] = spec
        m.set_controller_ref(deploy, inf)
        try:
            deploy = self.api.create(deploy)
            if self.recorder is not None:
                self.recorder.event(
                    inf, "Normal", "PredictorDeploymentCreated",
                    f"Deployment {m.name(deploy)} for predictor created, "
                    f"replicas: {deploy['spec']['replicas']}")
        except AlreadyExists:
            deploy = self.api.get("Deployment", m.namespace(inf),
                                  predictor_name(inf, predictor))
        return deploy

    def _apply_autoconfig(self, inf: dict, template: dict) -> None:
        """Render the autoconfig annotation's chosen serving config into
        predictor env (the write-back half of the Morphling loop; the
        search half is ``serving/autoconfig.autoconfigure_multi``, run
        offline or by a prober job against a staging predictor). The env
        keys mirror ``serving.autoconfig.Candidate.to_env`` — kept
        literal here so the operator process never imports the compute
        stack (jax) just to copy three strings."""
        import json as _json
        raw = m.annotations(inf).get(ANNOTATION_AUTOCONFIG, "")
        if not raw:
            return
        try:
            chosen = _json.loads(raw)
            if not isinstance(chosen, dict):
                raise ValueError("not a JSON object")
            spec_k = int(chosen.get("speculativeK", 0) or 0)
            draft = str(chosen.get("draftPath") or "")
            if spec_k > 0 and not draft:
                # a speculative candidate is only servable with a draft
                # model; without one the entrypoint would CrashLoop —
                # degrade to the non-speculative config and say so
                if self.recorder is not None:
                    self.recorder.event(
                        inf, "Warning", "AutoconfigNoDraft",
                        "speculativeK set without draftPath; serving "
                        "without speculative decoding")
                spec_k = 0
            env = {
                "KUBEDL_SERVING_LANES":
                    str(int(chosen.get("batch", 1) or 1)),
                "KUBEDL_SERVING_QUANTIZE": str(chosen.get("quantize") or ""),
                "KUBEDL_SERVING_SPEC_K": str(spec_k),
                # paged-KV geometry (0 = engine defaults): dropping
                # these would silently lose the pool overcommit the
                # candidate was chosen for (and its HBM-budget fit)
                "KUBEDL_SERVING_KV_BLOCK":
                    str(int(chosen.get("kvBlock", 0) or 0)),
                "KUBEDL_SERVING_POOL_BLOCKS":
                    str(int(chosen.get("poolBlocks", 0) or 0)),
            }
            if spec_k > 0:
                env["KUBEDL_SERVING_DRAFT_PATH"] = draft
        except (ValueError, TypeError):
            # bad values (e.g. {"batch": "fast"}) must degrade to a
            # warning event, not a reconcile retry-loop
            if self.recorder is not None:
                self.recorder.event(inf, "Warning", "BadAutoconfig",
                                    "unparseable autoconfig annotation")
            return
        for ct in m.get_in(template, "spec", "containers",
                           default=[]) or []:
            for k, v in env.items():
                pl.upsert_env(ct, k, v)

    def _add_model_loader(self, template: dict, mv: dict,
                          model_path: str) -> None:
        """Init container copying artifacts out of the baked model image
        into a shared emptyDir (reference model.go:27-34, predictor.go:54-85)."""
        spec = template.setdefault("spec", {})
        vols = spec.setdefault("volumes", [])
        if not any(v.get("name") == "kubedl-model-loader" for v in vols):
            vols.append({"name": "kubedl-model-loader", "emptyDir": {}})
        inits = spec.setdefault("initContainers", [])
        if not any(i.get("name") == "kubedl-model-loader" for i in inits):
            inits.append({
                "name": "kubedl-model-loader",
                "image": m.get_in(mv, "status", "image", default=""),
                "command": ["/bin/sh", "-c",
                            f"cp -r {DEFAULT_MODEL_PATH_IN_IMAGE}/* "
                            f"/mnt/kubedl-model/"],
                "volumeMounts": [{"name": "kubedl-model-loader",
                                  "mountPath": "/mnt/kubedl-model/"}],
            })
        for ct in spec.get("containers", []) or []:
            mounts = ct.setdefault("volumeMounts", [])
            if not any(vm.get("name") == "kubedl-model-loader"
                       for vm in mounts):
                mounts.append({"name": "kubedl-model-loader",
                               "mountPath": model_path})

    def _apply_tpu_placement(self, inf: dict, template: dict) -> None:
        """Single-host TPU serving slices: chips + topology nodeSelector per
        replica. Multi-host slices are a training shape; serving scales by
        adding replicas (more independent slices), so reject them loudly."""
        policy = m.get_in(inf, "spec", "tpuPolicy")
        if not policy:
            return
        from ..controllers.interface import TPUPolicy
        spec = TPUPolicy.from_spec(policy).resolve()
        if spec.num_hosts != 1:
            raise ValueError(
                f"inference tpuPolicy must be a single-host slice, got "
                f"{spec.accelerator_type} ({spec.num_hosts} hosts); scale "
                f"serving with predictor replicas instead")
        pod_spec = template.setdefault("spec", {})
        sel = pod_spec.setdefault("nodeSelector", {})
        sel.setdefault(pl.NODE_SELECTOR_ACCELERATOR, spec.gke_accelerator)
        sel.setdefault(pl.NODE_SELECTOR_TOPOLOGY, spec.topology_str)
        for ct in pod_spec.get("containers", []) or []:
            res = ct.setdefault("resources", {})
            for kk in ("limits", "requests"):
                res.setdefault(kk, {})
                res[kk][c.RESOURCE_TPU] = str(spec.chips_per_host)
            pl.upsert_env(ct, "PJRT_DEVICE", "TPU")

    def _ensure_predictor_service(self, inf: dict, predictor: dict) -> None:
        ns = m.namespace(inf)
        name = predictor_name(inf, predictor)
        if self.api.try_get("Service", ns, name):
            return
        port = _DEFAULT_PORTS.get(m.get_in(inf, "spec", "framework",
                                           default=""), 8080)
        svc = m.new_obj("v1", "Service", name, ns)
        svc["spec"] = {
            "selector": predictor_labels(inf, predictor),
            "ports": [{"name": "serving", "port": port, "targetPort": port}],
        }
        m.set_controller_ref(svc, inf)
        try:
            self.api.create(svc)
        except AlreadyExists:
            pass

    def _sync_traffic_split(self, inf: dict, predictors: list,
                            ratios: dict) -> None:
        """Weighted canary routes (reference inference_controller.go:216-259
        renders an Istio VirtualService; same shape here)."""
        vs_spec = {
            "hosts": [f"{m.name(inf)}.*"],
            "gateways": [_ISTIO_GATEWAY],
            "http": [{
                "name": p.get("name", ""),
                "route": [{
                    "destination": {"host": predictor_host(inf, p)},
                    "weight": ratios.get(p.get("name", ""), 0),
                }],
            } for p in predictors],
        }
        existing = self.api.try_get("VirtualService", m.namespace(inf),
                                    m.name(inf))
        if existing is None:
            vs = m.new_obj("networking.istio.io/v1beta1", "VirtualService",
                           m.name(inf), m.namespace(inf), spec=vs_spec)
            m.set_controller_ref(vs, inf)
            try:
                self.api.create(vs)
            except AlreadyExists:
                pass
        elif existing.get("spec") != vs_spec:
            existing["spec"] = vs_spec
            try:
                self.api.update(existing)
            except (Conflict, NotFound):
                pass

    def _prune_removed_predictors(self, inf: dict, predictors: list) -> None:
        """Drop Deployments/Services/HPAs for predictors removed from
        the spec."""
        ns = m.namespace(inf)
        want = {predictor_name(inf, p) for p in predictors} | {m.name(inf)}
        for kind in ("Deployment", "Service", "HorizontalPodAutoscaler"):
            for obj in self.api.list(kind, ns):
                if not m.is_controlled_by(obj, inf):
                    continue
                if m.name(obj) not in want:
                    try:
                        self.api.delete(kind, ns, m.name(obj))
                    except NotFound:
                        pass
