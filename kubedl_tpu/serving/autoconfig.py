"""Morphling-style serving auto-configuration.

The reference README points serving users at Morphling ("auto-configuration
for ML model serving", ACM SoCC 2021, ``README.md:33-35``) — a search over
a multi-dimensional serving-config space that maximizes throughput under
SLOs. This is the TPU-native, in-process version, searching the knobs the
in-tree serving stack actually has:

* **lane count / batch** — continuous-batching lanes (HBM for cache rows);
* **int8 / int4 weight quantization** — halves (or quarters) weight
  bandwidth, changes outputs (excluded when the SLO pins quality; pass
  ``quantize_opts=(None, "int8", "int4")`` to search all three);
* **speculative decoding draft length k** — trades draft FLOPs for
  target-pass amortization; greedy-identical to the serving engine's own
  outputs, so it is quality-safe;

under a **p99 per-token latency SLO** and a **time-to-first-token SLO**
(TTFT is what streaming clients feel; serving/server.py streams tokens,
so the first event lands one prefill after the request).

Probes run against live engines (one compile + a short measured run per
candidate). Used two ways: offline (pick flags before rollout) and via the
Inference CR annotation ``serving.kubedl.io/autoconfig`` — the chosen
config renders into the predictor env (``platform/serving.py``).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from .engine import InferenceEngine


@dataclass
class AutoConfigResult:
    best_batch: int
    measurements: list = field(default_factory=list)
    slo_ms: float = 0.0

    def to_dict(self) -> dict:
        return {"bestBatch": self.best_batch, "sloMs": self.slo_ms,
                "measurements": self.measurements}


def autoconfigure(engine: InferenceEngine,
                  batch_candidates: Sequence[int] = (1, 2, 4, 8, 16),
                  prompt_len: int = 128, new_tokens: int = 16,
                  latency_slo_ms: Optional[float] = None) -> AutoConfigResult:
    """Single-dimension (batch) search against a live engine; the
    original API, kept for offline probing of one engine instance. See
    :func:`autoconfigure_multi` for the full config space."""
    measurements = []
    best, best_tps = 0, -1.0
    prev_tps = -1.0
    for batch in batch_candidates:
        probe = engine.score_throughput(batch, prompt_len, new_tokens)
        measurements.append(probe)
        tps = probe["decode_tokens_per_s"]
        ok = (latency_slo_ms is None
              or probe["latency_per_token_ms"] <= latency_slo_ms)
        if ok and tps > best_tps:
            best, best_tps = batch, tps
        if prev_tps > 0 and tps < prev_tps * 0.9:
            break  # past saturation
        prev_tps = tps
    if best == 0:  # nothing met the SLO: smallest batch is closest
        best = batch_candidates[0]
    return AutoConfigResult(best_batch=best, measurements=measurements,
                            slo_ms=latency_slo_ms or 0.0)


# ---------------------------------------------------------------------------
# multi-dimensional search (VERDICT r3 next #6)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Candidate:
    """One point in the serving-config space."""
    batch: int = 1                    # continuous-batching lanes
    quantize: Optional[str] = None    # target weights: None|"int8"|"int4"
    speculative_k: int = 0            # 0 = off; >0 = draft lookahead
    #: paged-KV block size in tokens (0 = engine default); the pool is
    #: the HBM knob — smaller pools admit fewer concurrent prompts,
    #: bigger ones trade weight/activation headroom for cache
    kv_block: int = 0
    #: usable pool blocks (0 = lanes * ceil(max_len/block), the
    #: dense-capacity default — no overcommit)
    pool_blocks: int = 0

    def to_env(self) -> dict:
        """Env contract the predictor container reads at startup."""
        return {
            "KUBEDL_SERVING_LANES": str(self.batch),
            "KUBEDL_SERVING_QUANTIZE": self.quantize or "",
            "KUBEDL_SERVING_SPEC_K": str(self.speculative_k),
            "KUBEDL_SERVING_KV_BLOCK": str(self.kv_block),
            "KUBEDL_SERVING_POOL_BLOCKS": str(self.pool_blocks),
        }


def kv_cache_bytes(config, cand: Candidate, max_len: int) -> int:
    """The candidate's KV-cache HBM footprint. Paged serving is sized in
    BLOCKS, not ``lanes * max_len``: the pool (plus its one garbage
    block) is the allocation, however many lanes share it — that is the
    whole point of paging, lanes stop being an HBM commitment. Dense
    sizing (kv_block == 0 with no pool) falls out as the
    no-overcommit case."""
    from .batching import fit_block
    from .engine import kv_bytes_per_token
    block = fit_block(cand.kv_block or 64, max_len)
    bpl = max_len // block
    blocks = (cand.pool_blocks or cand.batch * bpl) + 1
    return blocks * block * kv_bytes_per_token(config)


@dataclass(frozen=True)
class ServingSLO:
    """Constraints the chosen config must honor.

    ``pinned_quality`` forbids target-weight quantization (int8 changes
    sampled outputs). Speculative decoding stays allowed: greedy
    acceptance is token-identical to the target engine's own decode."""
    p99_latency_ms: Optional[float] = None   # per generated token
    ttft_ms: Optional[float] = None          # time to first token
    pinned_quality: bool = False

    def allows(self, cand: Candidate) -> bool:
        return not (self.pinned_quality and cand.quantize)

    def met_by(self, probe: dict) -> bool:
        if self.p99_latency_ms is not None and \
                probe["p99_latency_ms"] > self.p99_latency_ms:
            return False
        if self.ttft_ms is not None and probe["ttft_ms"] > self.ttft_ms:
            return False
        return True

    def violation(self, probe: dict) -> float:
        """Relative overshoot, for picking the least-bad config when
        nothing satisfies the SLO."""
        v = 0.0
        if self.p99_latency_ms:
            v += max(0.0, probe["p99_latency_ms"] / self.p99_latency_ms - 1)
        if self.ttft_ms:
            v += max(0.0, probe["ttft_ms"] / self.ttft_ms - 1)
        return v


@dataclass
class MultiConfigResult:
    best: Candidate
    best_probe: dict
    measurements: list = field(default_factory=list)
    slo: Optional[ServingSLO] = None

    def to_dict(self) -> dict:
        return {"best": {"batch": self.best.batch,
                         "quantize": self.best.quantize,
                         "speculativeK": self.best.speculative_k,
                         "kvBlock": self.best.kv_block,
                         "poolBlocks": self.best.pool_blocks},
                "probe": self.best_probe,
                "measurements": self.measurements}

    def to_env(self) -> dict:
        return self.best.to_env()


def probe_candidate(model, cand: Candidate, prompt_len: int = 64,
                    new_tokens: int = 16, max_len: int = 0,
                    draft=None, repeats: int = 3) -> Optional[dict]:
    """Measure one candidate on live engines.

    Three SLO-relevant numbers, each isolated from the others so the
    search compares what clients actually feel:

    * ``ttft_ms`` — ONE request's prefill + first token (what a
      streaming client waits before its first SSE event), never a whole
      batch of sequential prefills;
    * ``p50/p99_latency_ms`` — steady-state decode time per token,
      obtained by DIFFERENCING a short and a long run of the same batch
      (both pay identical prefills, so the prefill cost cancels instead
      of biasing large batches);
    * ``decode_tokens_per_s`` — batch / best per-token time (all lanes
      decode one token per tick).

    Returns None when the candidate is unbuildable (speculative without
    a draft model)."""
    import numpy as np

    if new_tokens < 4:
        # the short/long differencing needs hi > lo by a real margin:
        # with new_tokens <= lo both runs are identical and the "decode
        # time" is clamped timing noise (absurd tps, ~0 latency)
        raise ValueError("probe_candidate needs new_tokens >= 4")
    cfg, params = model
    max_len = max_len or prompt_len + new_tokens + 8
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, prompt_len).tolist()

    from .batching import ContinuousBatchingEngine
    kv = {}
    if cand.kv_block:
        kv["kv_block"] = cand.kv_block
    if cand.pool_blocks:
        kv["pool_blocks"] = cand.pool_blocks
    if cand.speculative_k > 0:
        if draft is None:
            return None  # speculative points need a draft model
        # the production shape: speculative decoding ON the
        # continuous-batching lanes, so the draft-k dimension is measured
        # with concurrent lanes — exactly what the predictor deploys
        eng = ContinuousBatchingEngine(
            cfg, params, lanes=cand.batch, max_len=max_len,
            quantize=cand.quantize, draft_config=draft[0],
            draft_params=draft[1], spec_k=cand.speculative_k, **kv)
    else:
        eng = ContinuousBatchingEngine(cfg, params, lanes=cand.batch,
                                       max_len=max_len,
                                       quantize=cand.quantize, **kv)

    def gen(n):
        return eng.run([(prompt, n)] * cand.batch)

    def gen_one(n):
        return eng.run([(prompt, n)])

    lo, hi = min(2, new_tokens), new_tokens
    gen_one(1)                     # compile prefill + first decode shape
    gen(lo)                        # compile the steady decode tick
    t0 = time.perf_counter()
    gen_one(1)
    ttft = time.perf_counter() - t0

    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        gen(lo)
        d_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        gen(hi)
        d_hi = time.perf_counter() - t0
        # same batch, same prefills: the difference is pure decode
        samples.append(max(d_hi - d_lo, 1e-9) / max(hi - lo, 1))
    tps = cand.batch / min(samples)
    return {
        "batch": cand.batch, "quantize": cand.quantize or "",
        "speculative_k": cand.speculative_k,
        "kv_block": cand.kv_block, "pool_blocks": cand.pool_blocks,
        "decode_tokens_per_s": round(tps, 2),
        "p50_latency_ms": round(
            1000 * sorted(samples)[len(samples) // 2], 3),
        "p99_latency_ms": round(1000 * max(samples), 3),
        "ttft_ms": round(1000 * ttft, 3),
    }


def autoconfigure_multi(
        model=None, draft=None,
        batches: Sequence[int] = (1, 2, 4, 8),
        quantize_opts: Sequence[Optional[str]] = (None, "int8"),
        spec_ks: Sequence[int] = (0, 4),
        kv_blocks: Sequence[int] = (0,),
        prompt_len: int = 64, new_tokens: int = 16,
        slo: Optional[ServingSLO] = None,
        measure: Optional[Callable[[Candidate], Optional[dict]]] = None,
        hbm_budget_bytes: Optional[int] = None,
        max_len: int = 0,
) -> MultiConfigResult:
    """Search {batch x int8 x speculative-k x kv-block} under the SLO.

    ``measure`` defaults to :func:`probe_candidate` over live engines
    built from ``model``/``draft``; tests (and remote probers) may inject
    their own. Within each (quantize, k, block) family the batch
    dimension keeps Morphling's unimodal early-stop: once throughput
    drops well below the family's best, bigger batches only add latency.
    ``hbm_budget_bytes`` prunes candidates whose KV footprint exceeds
    the cache budget BEFORE probing — and the footprint is the
    block-pool model (:func:`kv_cache_bytes`), not ``lanes * max_len``:
    under paging, big lane counts stay searchable as long as the pool
    fits, which is exactly where the paged engine wins. Selection: the
    highest-throughput candidate meeting the SLO; if none do, the
    least-violating one (Morphling's nearest-feasible fallback)."""
    slo = slo or ServingSLO()
    if hbm_budget_bytes is not None and model is None:
        # the budget prunes via kv_cache_bytes(model[0], ...): without a
        # model config it would be silently ignored and over-budget
        # candidates could win — refuse loudly instead
        raise ValueError(
            "hbm_budget_bytes needs a (config, params) model to price "
            "candidates (pass model= even with a custom measure fn)")
    if measure is None:
        if model is None:
            raise ValueError("need a (config, params) model or a measure fn")
        measure = lambda c: probe_candidate(        # noqa: E731
            model, c, prompt_len=prompt_len, new_tokens=new_tokens,
            max_len=max_len, draft=draft)
    budget_len = max_len or prompt_len + new_tokens + 8

    measurements = []
    best: Optional[Candidate] = None
    best_probe: Optional[dict] = None
    fallback, fb_probe, fb_viol = None, None, math.inf
    for q in quantize_opts:
        for k in spec_ks:
            for blk in kv_blocks:
                family_best = -1.0
                for b in batches:
                    cand = Candidate(batch=b, quantize=q, speculative_k=k,
                                     kv_block=blk)
                    if not slo.allows(cand):
                        continue
                    if hbm_budget_bytes is not None \
                            and kv_cache_bytes(model[0], cand,
                                               budget_len) > hbm_budget_bytes:
                        continue   # cache alone busts the HBM budget
                    probe = measure(cand)
                    if probe is None:
                        continue   # unbuildable (no draft, multi-lane k)
                    measurements.append(probe)
                    tps = probe["decode_tokens_per_s"]
                    if slo.met_by(probe):
                        if best_probe is None or \
                                tps > best_probe["decode_tokens_per_s"]:
                            best, best_probe = cand, probe
                    else:
                        v = slo.violation(probe)
                        if v < fb_viol:
                            fallback, fb_probe, fb_viol = cand, probe, v
                    if family_best > 0 and tps < family_best * 0.9:
                        break   # past saturation in this family
                    family_best = max(family_best, tps)
    if best is None:
        # nothing met the SLO: surface the least-bad config rather than
        # guessing (the caller sees the probe and the violation)
        best, best_probe = fallback, fb_probe
    if best is None:
        raise ValueError("no buildable candidate in the search space")
    return MultiConfigResult(best=best, best_probe=best_probe,
                             measurements=measurements, slo=slo)
