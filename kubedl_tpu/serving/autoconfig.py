"""Morphling-style serving auto-configuration.

The reference README points serving users at Morphling ("auto-configuration
for ML model serving", ACM SoCC 2021, ``README.md:33-35``) — a search over
serving configs that maximizes throughput under a latency SLO. This is the
TPU-native, in-process version: probe candidate batch sizes against the
live engine (each probe costs one compile + a short measured run) and pick
the largest-throughput config whose per-token latency meets the SLO.

Used two ways: offline (pick flags before rollout) and by the Inference
controller's predictor annotation ``kubedl.io/autoconfig`` (batch size is
written back into the predictor's env).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from .engine import InferenceEngine


@dataclass
class AutoConfigResult:
    best_batch: int
    measurements: list = field(default_factory=list)
    slo_ms: float = 0.0

    def to_dict(self) -> dict:
        return {"bestBatch": self.best_batch, "sloMs": self.slo_ms,
                "measurements": self.measurements}


def autoconfigure(engine: InferenceEngine,
                  batch_candidates: Sequence[int] = (1, 2, 4, 8, 16),
                  prompt_len: int = 128, new_tokens: int = 16,
                  latency_slo_ms: Optional[float] = None) -> AutoConfigResult:
    """Probe each batch size; return the throughput-max config under the
    SLO (or overall max when no SLO). Stops early when throughput drops —
    decode is bandwidth-bound, so past saturation bigger batches only add
    latency (the same unimodal assumption Morphling's search exploits)."""
    measurements = []
    best, best_tps = 0, -1.0
    prev_tps = -1.0
    for batch in batch_candidates:
        probe = engine.score_throughput(batch, prompt_len, new_tokens)
        measurements.append(probe)
        tps = probe["decode_tokens_per_s"]
        ok = (latency_slo_ms is None
              or probe["latency_per_token_ms"] <= latency_slo_ms)
        if ok and tps > best_tps:
            best, best_tps = batch, tps
        if prev_tps > 0 and tps < prev_tps * 0.9:
            break  # past saturation
        prev_tps = tps
    if best == 0:  # nothing met the SLO: smallest batch is closest
        best = batch_candidates[0]
    return AutoConfigResult(best_batch=best, measurements=measurements,
                            slo_ms=latency_slo_ms or 0.0)
