"""KV-cache generation engine.

TPU-shaped autoregressive decoding:

* **static shapes** — prompts are right-padded into fixed buckets, the KV
  cache is a fixed ``[layers, batch, max_len, kv_heads, hd]`` block, and
  the decode step is one jitted function reused for every token: no
  per-length recompiles;
* **donated cache** — the cache is donated into each step so XLA updates
  it in place in HBM (decode is bandwidth-bound; copying the cache would
  double traffic);
* **prefill/decode split** — prefill runs the prompt chunk through the
  same cache-aware forward (``kubedl_tpu.models.llama.forward_step``),
  decode feeds one token back per step;
* greedy or temperature/top-k/top-p sampling, per-request stop handling
  on the host (control flow stays out of the compiled step).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama


@dataclass(frozen=True)
class GenerateConfig:
    max_len: int = 1024            # cache capacity (prompt + generated)
    temperature: float = 0.0       # 0 = greedy
    top_k: int = 0                 # 0 = full softmax when sampling
    top_p: float = 1.0             # nucleus sampling mass (1.0 = off)
    eos_id: int = -1               # -1 = never stop early
    #: multi-token stop sequences (host-side suffix match after each
    #: generated token; the matched suffix stays in the output)
    stop_sequences: tuple = ()


def hit_stop(tokens: list, gen: "GenerateConfig") -> bool:
    """True when the generated tokens end in eos or any stop sequence —
    the ONE stop rule shared by the static and continuous engines."""
    if not tokens:
        return False
    if gen.eos_id >= 0 and tokens[-1] == gen.eos_id:
        return True
    for seq in gen.stop_sequences:
        seq = list(seq)
        if seq and tokens[-len(seq):] == seq:
            return True
    return False


def resolve_family(config):
    """Model family module for a config: every family exposes the same
    forward_step/init_cache contract (llama/gemma share LlamaConfig;
    MoEConfig routes through the sparse stack)."""
    from ..models import moe
    return moe if isinstance(config, moe.MoEConfig) else llama


def spec_accept(drafts, dprobs, tprobs, rng):
    """The Leviathan et al. speculative accept/resample rule, factored
    out so its distribution guarantee is unit-testable without a model.
    Shared by the single-sequence SpeculativeEngine and the per-lane
    speculative path of the continuous-batching engine.

    ``drafts``: k proposed tokens; ``dprobs``/``tprobs``: the draft's /
    target's FILTERED probability vectors per slot (tprobs has k+1
    entries — the last is the bonus slot). Returns ``(n_accepted,
    next_token)`` where next_token is the resample on rejection or the
    bonus sample on full acceptance. The marginal distribution of each
    emitted token provably equals the target's."""
    for i, x in enumerate(drafts):
        if rng.random() >= min(1.0, float(tprobs[i][x])
                               / max(float(dprobs[i][x]), 1e-20)):
            resid = np.maximum(np.asarray(tprobs[i])
                               - np.asarray(dprobs[i]), 0.0)
            s = resid.sum()
            p = resid / s if s > 0 else np.asarray(tprobs[i])
            return i, int(rng.choice(len(p), p=p))
    return len(drafts), int(rng.choice(len(tprobs[-1]),
                                       p=np.asarray(tprobs[-1])))


@dataclass
class SpecStats:
    """Lifetime draft proposal/acceptance accounting — the speculative
    tuning signal, surfaced via the predictor's /metrics."""
    proposed: int = 0
    accepted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


def maybe_quantize(params: dict, quantize):
    """Apply a serving quantization mode ('int8', 'int4', or None) to a
    param tree."""
    if quantize in ("int8", "int4"):
        # weight-only: int8 halves weight HBM + bandwidth, int4 (packed
        # nibbles, group scales) halves it again; decode is
        # bandwidth-bound so these are the cheap serving speedups
        from ..ops.quant import quantize_params
        return quantize_params(params, mode=quantize)
    if quantize:
        raise ValueError(f"unknown quantize mode {quantize!r}")
    return params


@jax.jit
def token_logprobs(logits, tokens):
    """log p(token) under the FULL softmax of ``logits`` [b, vocab] for
    the chosen ``tokens`` [b] — reported per generated token when the
    client asks for logprobs (always the unfiltered distribution, so the
    numbers are comparable across sampling settings)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]


@partial(jax.jit, static_argnums=(2, 3, 4))
def sample_logits(logits, key, temperature, top_k, top_p=1.0):
    """Greedy (temperature<=0) or temperature/top-k/top-p sampling — the
    ONE sampler shared by the static and continuous engines. top-p keeps
    the smallest set of tokens whose probability mass reaches ``top_p``
    (nucleus sampling), applied after temperature and top-k."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the mass BEFORE them is < top_p; the nucleus
        # ALWAYS includes the top token (even for top_p <= 0, which would
        # otherwise empty the set and degrade to uniform-over-vocab)
        keep_sorted = ((cum - probs) < top_p).at[..., 0].set(True)
        # threshold = smallest kept logit; everything below is cut
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def filtered_probs(logits, temperature: float, top_k: int = 0,
                   top_p: float = 1.0):
    """Host-side (numpy) probability vector after the SAME
    temperature/top-k/top-p filtering as :func:`sample_logits` — the
    speculative-sampling accept rule needs explicit p(token) for both
    draft and target, which the jitted sampler never materializes. Keep
    the two in sync."""
    import numpy as np

    x = np.asarray(logits, np.float64) / max(temperature, 1e-6)
    if top_k > 0:
        # tie semantics deliberately match sample_logits / sample_logits_many:
        # both cut with `value < kth`, so every token TIED with the k-th
        # logit stays in the set on all three samplers (ADVICE r4 review:
        # lax.top_k only supplies the threshold there, never the cut)
        kth = np.sort(x)[-top_k]
        x = np.where(x < kth, -np.inf, x)
    if top_p < 1.0:
        order = np.argsort(x)[::-1]
        p_sorted = np.exp(x[order] - x[order[0]])
        p_sorted = p_sorted / p_sorted.sum()
        cum = np.cumsum(p_sorted)
        keep_sorted = (cum - p_sorted) < top_p
        keep_sorted[0] = True          # the nucleus never empties
        cutoff = x[order][keep_sorted].min()
        x = np.where(x < cutoff, -np.inf, x)
    x = x - x.max()
    p = np.exp(x)
    return p / p.sum()


@jax.jit
def sample_logits_many(logits, key, temps, top_ks, top_ps):
    """Vectorized per-row sampler: ``logits [n, V]`` with PER-ROW
    temperature/top-k/top-p (the continuous engine's lanes each carry
    their own request's sampling params). Rows with ``temps <= 0`` are
    greedy. Same math as :func:`sample_logits` per row; top-k uses a
    rank cut on the sorted logits so the k may differ per row inside
    one jitted call."""
    n, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]
    sorted_l = jnp.take_along_axis(scaled, order, axis=-1)
    # top-k: cut everything below the k-th sorted logit (k=0: keep all)
    idx = jnp.clip(top_ks - 1, 0, v - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_l, idx[:, None], axis=-1)
    scaled = jnp.where((top_ks[:, None] > 0) & (scaled < kth),
                       -1e30, scaled)
    # top-p on the (possibly top-k-cut) logits. No second sort: masking
    # ranks >= k in the ALREADY-sorted array reproduces sort(cut logits)
    # descending — except for exact float ties AT the k-th logit, where
    # the strict value cut above keeps the ties but the rank mask drops
    # them from the nucleus mass (a measure-zero divergence accepted for
    # halving the per-token sort cost).
    ranks = jnp.arange(v)[None, :]
    sorted_l = jnp.where((top_ks[:, None] > 0) & (ranks >= top_ks[:, None]),
                         -1e30, sorted_l)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = ((cum - probs) < top_ps[:, None]).at[:, 0].set(True)
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_l, jnp.inf),
                     axis=-1, keepdims=True)
    scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, sampled)


def kv_bytes_per_token(config, dtype_bytes: Optional[int] = None) -> int:
    """HBM bytes one cached token costs across all layers (K and V) —
    the unit both the dense slab (``lanes * max_len`` tokens) and the
    paged pool (``num_blocks * block`` tokens) are sized in. The serving
    auto-configurator's memory model and ``bench_serving_paged.py``
    budget with this."""
    if dtype_bytes is None:
        dtype_bytes = jnp.dtype(config.dtype).itemsize
    return 2 * config.n_layers * config.n_kv_heads * config.hd * dtype_bytes


def shard_for_serving(config, params, cache, mesh):
    """Place a param tree + KV cache for model-parallel serving over a
    local mesh (tp over the chips of ONE host — a v5e-8 serving VM).
    Params follow the family's logical specs (heads/mlp on tp); the
    cache shards its kv-head axis when it divides tp, else replicates
    (MQA). GSPMD then inserts the serving collectives inside the same
    jitted step — no engine code changes, just operand placement.

    Works unchanged for BOTH cache layouts: the dense slab ``[layers,
    lanes, max_len, kv_heads, hd]`` and the paged block pool ``[layers,
    num_blocks, block, kv_heads, hd]`` carry kv-heads on the same axis,
    so one spec shards either — the pool's block axis stays replicated
    (every chip holds every block's shard of its kv-heads, and the
    block-table gather is local)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import tree_shardings

    family = resolve_family(config)
    if params is not None:
        p_shard = tree_shardings(mesh, family.param_specs(config))
        params = jax.tree.map(jax.device_put, params, p_shard)
    tp = mesh.shape.get("tp", 1)
    kv_axis = "tp" if config.n_kv_heads % tp == 0 else None
    c_shard = NamedSharding(mesh, P(None, None, None, kv_axis, None))
    cache = jax.tree.map(lambda x: jax.device_put(x, c_shard), cache)
    return params, cache


def init_mesh_serving(config, params, quantize, mesh):
    """The ONE param-preparation path both engines share: validates the
    (mesh, quantize) combination, then either quantizes (no mesh) or
    shards params for serving, returning ``(params, place_cache)`` where
    ``place_cache`` re-places a fresh KV cache (identity without a
    mesh). The unsupported mesh+quantize pair rejects BEFORE any
    quantization pass runs."""
    if mesh is None:
        return maybe_quantize(params, quantize), (lambda cache: cache)
    if quantize:
        raise ValueError(
            "mesh-parallel serving does not compose with weight "
            "quantization yet")
    params, _ = shard_for_serving(config, params, {}, mesh)

    def place_cache(cache):
        _, cache = shard_for_serving(config, None, cache, mesh)
        return cache

    return params, place_cache


@lru_cache(maxsize=32)
def _rollout_fn(config, max_new: int):
    """Build (and cache) the jitted whole-generation greedy decode for a
    config: prefill + a ``lax.fori_loop`` of single-token steps — ONE
    device call per generation. jit re-specializes per (batch,
    prompt_len) shape; the config is hashable (frozen dataclass) so the
    compiled callable is reused across calls."""
    family = resolve_family(config)

    @jax.jit
    def run(params, tokens):
        b, plen = tokens.shape
        cache = family.init_cache(config, b, plen + max_new)
        logits, cache = family.forward_step(config, params, tokens, cache,
                                            jnp.int32(0))
        out = jnp.zeros((b, max_new), jnp.int32)
        out = out.at[:, 0].set(jnp.argmax(logits, axis=-1).astype(jnp.int32))

        def body(i, carry):
            out, cache = carry
            tok = jax.lax.dynamic_slice_in_dim(out, i - 1, 1, axis=1)
            logits, cache = family.forward_step(
                config, params, tok, cache, plen + i - 1)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
            return (jax.lax.dynamic_update_slice_in_dim(out, nxt, i, axis=1),
                    cache)

        out, _ = jax.lax.fori_loop(1, max_new, body, (out, cache))
        return out

    return run


def greedy_rollout(config, params, prompts, max_new: int):
    """Whole-generation greedy decode in ONE jitted device call: prefill
    plus an on-device token loop, no host round trip per token.

    ``prompts`` is a [batch, prompt_len] int32 array (fixed length — pad
    or pack upstream); returns generated ids [batch, max_new]. No eos /
    stop-sequence handling: the loop always runs ``max_new`` steps (stop
    scanning needs the host). The serving engines sample on the host per
    token (per-request sampling params, streaming, stop sequences); this
    is the batch-completion fast path — and the honest way to measure
    decode throughput when the chip sits behind a high-latency link,
    where per-token dispatch would otherwise dominate."""
    if max_new < 1:
        raise ValueError("max_new must be >= 1")
    tokens = jnp.asarray(prompts, jnp.int32)
    if tokens.ndim != 2:
        raise ValueError("greedy_rollout needs a [batch, prompt_len] array")
    return _rollout_fn(config, int(max_new))(params, tokens)


class InferenceEngine:
    """One loaded model + its compiled prefill/decode steps.

    ``mesh`` (optional): a LOCAL device mesh for tensor-parallel serving
    — params shard by their logical specs, the cache by kv-heads, and
    XLA inserts the collectives inside the same jitted step. Not
    composable with weight quantization (quantized leaves carry their
    own scale trees; shard-then-quantize is future work)."""

    def __init__(self, config: llama.LlamaConfig, params: dict,
                 gen: Optional[GenerateConfig] = None,
                 quantize: Optional[str] = None, mesh=None, tracer=None):
        from ..trace import NOOP_TRACER
        self.config = config
        self.gen = gen or GenerateConfig()
        self.mesh = mesh
        #: span recorder (docs/tracing.md): per-generate prefill/decode
        #: spans; the shared disabled tracer by default
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.params, self._place_cache = init_mesh_serving(
            config, params, quantize, mesh)

        model_cfg = self.config
        self._family = family = resolve_family(config)

        @partial(jax.jit, donate_argnums=(1,))
        def _step(params, cache, tokens, start_pos, valid):
            return family.forward_step(model_cfg, params, tokens, cache,
                                       start_pos, valid)

        self._step = _step
        self._sample = sample_logits

    # -- public API -------------------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int,
                 seed: int = 0, return_logprobs: bool = False) -> list:
        """Batch-generate continuations. ``prompts`` are token-id lists;
        returns one list of generated ids per prompt (stops at eos or any
        configured stop sequence — see ``hit_stop``), or (ids, logprobs)
        pairs with ``return_logprobs`` (full-softmax log p of each
        generated token).

        Ragged batches are **left-padded**: every row's last real token sits
        at the bucket end, so one shared decode position works for the whole
        batch, pads are excluded from attention via the validity mask, and —
        because RoPE is relative — the per-row position shift is exact, not
        an approximation."""
        gen = self.gen
        b = len(prompts)
        prompt_len = max(max(len(p) for p in prompts), 1)
        total = prompt_len + max_new_tokens
        if total > gen.max_len:
            raise ValueError(
                f"prompt {prompt_len} + new {max_new_tokens} tokens exceed "
                f"cache capacity {gen.max_len}")

        toks = np.zeros((b, prompt_len), np.int32)
        pad = np.zeros((b,), np.int32)
        for i, p in enumerate(prompts):
            pad[i] = prompt_len - len(p)
            toks[i, pad[i]:] = p
        # cache slot p is live for row i iff p >= pad[i] (generated tokens
        # land at p >= prompt_len, live for every row) — static all decode
        valid = jnp.asarray(
            np.arange(gen.max_len)[None, :] >= pad[:, None])

        tr = self.tracer if self.tracer.enabled else None
        trace_id = root_id = None
        t_start = 0.0
        if tr is not None:
            trace_id, root_id = tr.new_trace_id(), tr.new_span_id()
            t_start = tr.clock()
        cache = self._place_cache(
            self._family.init_cache(self.config, b, gen.max_len))
        logits, cache = self._step(self.params, cache, jnp.asarray(toks),
                                   jnp.int32(0), valid)
        if tr is not None:
            t_prefill = tr.clock()
            tr.record("inference.prefill", t_start, t_prefill,
                      trace_id=trace_id, parent_id=root_id,
                      component="serving",
                      attributes={"batch": b, "promptTokens": prompt_len})
        key = jax.random.PRNGKey(seed)
        out: list[list[int]] = [[] for _ in range(b)]
        lps: list[list[float]] = [[] for _ in range(b)]
        done = np.zeros((b,), bool)
        cur = np.asarray(
            self._sample(logits, key, gen.temperature, gen.top_k, gen.top_p))
        cur_lp = (np.asarray(token_logprobs(logits, jnp.asarray(cur)))
                  if return_logprobs else None)
        pos = int(prompt_len)
        for _ in range(max_new_tokens):
            for i in range(b):
                if not done[i]:
                    out[i].append(int(cur[i]))
                    if return_logprobs:
                        lps[i].append(float(cur_lp[i]))
                    if hit_stop(out[i], gen):
                        done[i] = True
            if done.all() or pos + 1 > gen.max_len:
                break
            key, sub = jax.random.split(key)
            logits, cache = self._step(self.params, cache,
                                       jnp.asarray(cur)[:, None],
                                       jnp.int32(pos), valid)
            cur = np.asarray(
                self._sample(logits, sub, gen.temperature, gen.top_k, gen.top_p))
            if return_logprobs:
                cur_lp = np.asarray(token_logprobs(logits, jnp.asarray(cur)))
            pos += 1
        if tr is not None:
            t_end = tr.clock()
            generated = sum(len(o) for o in out)
            tr.record("inference.decode", t_prefill, t_end,
                      trace_id=trace_id, parent_id=root_id,
                      component="serving",
                      attributes={"tokens": generated})
            tr.record("inference.generate", t_start, t_end,
                      trace_id=trace_id, span_id=root_id,
                      component="serving",
                      attributes={"batch": b, "tokens": generated})
        if return_logprobs:
            return [(o, lp) for o, lp in zip(out, lps)]
        return out

    def score_throughput(self, batch: int, prompt_len: int,
                         new_tokens: int = 16, seed: int = 0) -> dict:
        """Measure prefill + decode rates for an (batch, prompt) shape —
        the probe the auto-configurator drives."""
        import time
        rng = np.random.default_rng(seed)
        prompts = rng.integers(1, self.config.vocab_size,
                               (batch, prompt_len)).tolist()
        t0 = time.perf_counter()
        self.generate(prompts, 1, seed)   # includes compile on first shape
        t_prefill = time.perf_counter() - t0
        # warmed prefill + first token = what a streaming client waits
        # for before its first SSE event (server.py streams per token)
        t0 = time.perf_counter()
        self.generate(prompts, 1, seed)
        ttft = time.perf_counter() - t0
        t0 = time.perf_counter()
        self.generate(prompts, new_tokens, seed)
        dt = time.perf_counter() - t0
        decode_tps = batch * new_tokens / dt
        return {"batch": batch, "prompt_len": prompt_len,
                "prefill_s": round(t_prefill, 4),
                "ttft_ms": round(1000 * ttft, 3),
                "decode_tokens_per_s": round(decode_tps, 2),
                "latency_per_token_ms": round(1000 * dt / new_tokens, 3)}
