"""TPU-native inference runtime.

The payload the Inference CRD deploys (reference
``controllers/serving/framework/tfserving.go`` points predictors at
TFServing/Triton images; here the predictor image IS this runtime):
a KV-cache generation engine over the llama-family models, an HTTP
prediction server, and a Morphling-style serving auto-configurator
(reference ``README.md:33-35``).
"""

from .autoconfig import (AutoConfigResult, Candidate, MultiConfigResult,
                         ServingSLO, autoconfigure, autoconfigure_multi)
from .engine import GenerateConfig, InferenceEngine
from .server import InferenceServer, ServerConfig

__all__ = ["AutoConfigResult", "autoconfigure", "autoconfigure_multi",
           "Candidate", "MultiConfigResult", "ServingSLO",
           "GenerateConfig", "InferenceEngine", "InferenceServer",
           "ServerConfig"]
