"""TPU-native inference runtime.

The payload the Inference CRD deploys (reference
``controllers/serving/framework/tfserving.go`` points predictors at
TFServing/Triton images; here the predictor image IS this runtime):
a KV-cache generation engine over the llama-family models, an HTTP
prediction server, and a Morphling-style serving auto-configurator
(reference ``README.md:33-35``).
"""

from .autoconfig import AutoConfigResult, autoconfigure
from .engine import GenerateConfig, InferenceEngine
from .server import InferenceServer, ServerConfig

__all__ = ["AutoConfigResult", "autoconfigure", "GenerateConfig",
           "InferenceEngine", "InferenceServer", "ServerConfig"]
