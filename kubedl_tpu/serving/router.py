"""Prefix-cache-aware request routing with per-tenant fairness.

The fleet's placement layer (docs/serving_fleet.md): a request whose
prompt starts with a registered shared prefix should land on the replica
ALREADY holding that prefix's pool blocks — the refcounted
:class:`~kubedl_tpu.serving.batching.BlockPool` makes residency a pure
host-side read (``engine.prefix_residency``), so placement costs no
device work. Two guards keep affinity honest:

* **router-driven registration**: a declared prefix the chosen replica
  has never seen is registered there on first placement (the engine's
  least-recently-hit eviction means this can never wedge a warm
  replica's full prefix cache);
* **per-tenant fairness**, reusing the Queue API's tenant routing
  (``api/queue.QueueSpec.tenants`` — the same attribution the slice
  scheduler routes jobs by): when the preferred replica is hot (its
  queue is backed up) and one tenant's queue already holds its fair
  share of that replica's outstanding work, the placement spills to the
  next-best replica instead of letting the hot tenant monopolize the
  prefix-warm one.

:class:`RandomRouter` is the control arm the routing leg of
``bench_serving_fleet.py`` compares against: identical traffic,
identical router-driven registration, placement by seeded uniform draw.
"""

from __future__ import annotations

import hashlib
import random
from typing import Optional, Sequence

from ..api.queue import DEFAULT_QUEUE


def _prefix_home(prefix, n: int) -> int:
    """Stable home replica for a cold prefix: a consistent hash of its
    tokens over the active set, so the fleet's prefix caches partition
    the catalog instead of every replica churning through all of it."""
    digest = hashlib.sha256(
        ",".join(str(int(t)) for t in prefix).encode()).digest()
    return int.from_bytes(digest[:8], "big") % n


def _model_home(model: str, n: int) -> int:
    """Stable home replica for a cold MODEL (adapter id): the same
    consistent-hash partitioning as cold prefixes, applied to the
    adapter catalog — each replica's pool holds a stable slice of the
    catalog instead of every replica faulting through all of it
    (docs/multimodel.md)."""
    digest = hashlib.sha256(model.encode()).digest()
    return int.from_bytes(digest[:8], "big") % n


class RandomRouter:
    """Uniform placement over non-draining replicas (the baseline)."""

    def __init__(self, fleet, seed: int = 0, max_prefixes: int = 8,
                 metrics=None, cache_residency: bool = True):
        self.fleet = fleet
        self.rng = random.Random(f"{seed}:router")
        #: per-replica prefix-cache cap for router-driven registration
        self.max_prefixes = int(max_prefixes)
        self.metrics = metrics
        #: probe residency from per-replica snapshots cached on the
        #: engine's residency_epoch instead of taking each engine's
        #: scheduler lock on every probe: a submit is O(1) pool reads
        #: amortized, and placement decisions are IDENTICAL to the
        #: uncached path (the snapshot walk mirrors
        #: _match_prefix_blocks; pinned by a test on the routing leg)
        self.cache_residency = bool(cache_residency)
        self._res_cache: dict = {}   # replica name -> snapshot tuple
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.tenant_spills = 0
        self.routed: dict = {}           # replica name -> placements

    # -- cached residency --------------------------------------------------

    def _snapshot(self, rep):
        eng = rep.engine
        cached = self._res_cache.get(rep.name)
        if cached is None or cached[0] != eng.residency_epoch:
            # epoch moved (prefix registered/evicted, adapter
            # faulted/evicted, engine recovered) — or first sight of
            # this replica: take one locked snapshot, then every probe
            # until the next change is a pure host-side walk
            cached = eng.residency_snapshot()
            self._res_cache[rep.name] = cached
        return cached

    def _residency(self, rep, probe, model: str = "") -> int:
        """``engine.prefix_residency`` through the snapshot cache (or
        live, when caching is off / the engine predates snapshots)."""
        eng = rep.engine
        if not self.cache_residency or \
                not hasattr(eng, "residency_snapshot"):
            if model:
                return eng.prefix_residency(probe, model=model)
            return eng.prefix_residency(probe)
        _, prefixes, _, kv_block = self._snapshot(rep)
        probe_t = tuple(int(t) for t in probe)
        n = len(probe_t)
        for pmodel, key, nblocks in prefixes:   # longest-first, like
            if pmodel == model and n >= len(key) \
                    and probe_t[:len(key)] == key:  # _match_prefix_blocks
                return min(nblocks, (n - 1) // kv_block)
        return 0

    def _adapter_resident(self, rep, model: str) -> bool:
        eng = rep.engine
        if not self.cache_residency or \
                not hasattr(eng, "residency_snapshot"):
            fn = getattr(eng, "adapter_resident", None)
            return bool(fn(model)) if fn is not None else False
        return model in self._snapshot(rep)[2]

    # -- placement --------------------------------------------------------

    def select(self, prompt: Sequence[int], tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None,
               model: Optional[str] = None):
        reps = self._candidates(version)
        return reps[self.rng.randrange(len(reps))]

    def _candidates(self, version: Optional[int]) -> list:
        """Active replicas, optionally pinned to one policy version —
        the rollout path's guarantee that every completion in a batch
        came from the SAME weights (docs/rl.md: a mixed-version batch
        has no well-defined behavior policy)."""
        reps = self.fleet.active()
        if version is not None:
            reps = [r for r in reps
                    if getattr(r, "policy_version", 0) == version]
            if not reps:
                raise RuntimeError(
                    f"no active replica serving policy version "
                    f"{version} (mid-publish, or the version was "
                    "already rolled past)")
        if not reps:
            raise RuntimeError("no active serving replica (fleet empty "
                               "or fully draining)")
        return reps

    def _ensure_prefix(self, rep, prefix, model: str = "") -> None:
        # model-scoped both ways: the warm-check and the registration
        # key on (model, tokens) — model kwargs only when scoped, so
        # engines/stubs that predate multi-model keep working
        if model:
            if not rep.engine.has_prefix(prefix, model=model):
                rep.engine.register_prefix(list(prefix),
                                           max_prefixes=self.max_prefixes,
                                           model=model)
        elif not rep.engine.has_prefix(prefix):
            rep.engine.register_prefix(list(prefix),
                                       max_prefixes=self.max_prefixes)

    def _account(self, rep, prefix, model: str = "") -> None:
        self.routed[rep.name] = self.routed.get(rep.name, 0) + 1
        if prefix is not None:
            if self._residency(rep, prefix, model) > 0:
                self.prefix_hits += 1
                if self.metrics is not None:
                    self.metrics.router_prefix_hits.inc()
            else:
                self.prefix_misses += 1
                if self.metrics is not None:
                    self.metrics.router_prefix_misses.inc()

    def submit(self, prompt: Sequence[int], max_new: int,
               tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None,
               model: Optional[str] = None, **kw):
        """Place + submit one request; returns ``(Request, replica)``.
        ``prefix`` is the client-declared shared prefix (system prompt)
        — the placement signal and the router-driven registration
        unit. ``version`` pins placement to replicas advertising that
        policy version (the rollout tenant's same-weights guarantee).
        ``model`` is the adapter id for multi-model fleets
        (docs/multimodel.md): it scopes the prefix work and rides down
        to ``engine.submit`` so admission gates on residency."""
        model = model or ""
        rep = self.select(prompt, tenant=tenant, prefix=prefix,
                          version=version, model=model)
        self._account(rep, prefix, model)
        if prefix is not None:
            self._ensure_prefix(rep, prefix, model)
        if model:
            kw = dict(kw, model=model)
        req = rep.engine.submit(prompt, max_new, **kw)
        self._note_submitted(rep, tenant, req)
        return req, rep

    def _note_submitted(self, rep, tenant, req) -> None:
        """Fairness bookkeeping hook (no-op for the random baseline)."""

    def stats(self) -> dict:
        total = self.prefix_hits + self.prefix_misses
        return {
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": round(self.prefix_hits / total, 4)
            if total else None,
            "tenant_spills": self.tenant_spills,
            "routed": {k: self.routed[k] for k in sorted(self.routed)},
        }


class PrefixAwareRouter(RandomRouter):
    """Place on the replica already holding the request's shared prefix
    blocks; fairness spills a hot tenant off the warm replica."""

    def __init__(self, fleet, seed: int = 0, max_prefixes: int = 8,
                 queues: Sequence = (), hot_queue_depth: int = 4,
                 metrics=None, cache_residency: bool = True,
                 adapter_affinity: bool = True):
        super().__init__(fleet, seed=seed, max_prefixes=max_prefixes,
                         metrics=metrics, cache_residency=cache_residency)
        #: multi-model placement (docs/multimodel.md): prefer replicas
        #: where the request's adapter is already resident; a cold
        #: model gets a consistent-hash home. Off = adapter-BLIND
        #: routing (the bench_multimodel comparison arm): the model
        #: still rides to the engine, but placement ignores it.
        self.adapter_affinity = bool(adapter_affinity)
        #: tenant -> queue name, from the Queue API's tenant lists (the
        #: slice scheduler's exact routing rule, docs/scheduling.md);
        #: unrouted tenants land on the implicit default queue
        self._tenant_queue: dict = {}
        for q in queues:
            for t in getattr(q, "tenants", ()) or ():
                self._tenant_queue.setdefault(t, q.name)
        #: replica hotness bar: at or past this queue depth the replica
        #: is contended and fairness applies
        self.hot_queue_depth = int(hot_queue_depth)
        #: (replica name, queue) -> live Requests (pruned lazily on
        #: reads, and swept every ``_SWEEP_EVERY`` submits so a
        #: long-lived server below the hotness bar — where _over_share
        #: never reads — cannot grow this without bound, and keys of
        #: reaped replicas don't live forever)
        self._outstanding: dict = {}
        self._submits_since_sweep = 0

    def queue_for(self, tenant: Optional[str]) -> str:
        if not tenant:
            return DEFAULT_QUEUE
        return self._tenant_queue.get(tenant, DEFAULT_QUEUE)

    # -- fairness bookkeeping --------------------------------------------

    def _live(self, rep_name: str, queue: str) -> int:
        reqs = self._outstanding.get((rep_name, queue))
        if not reqs:
            return 0
        live = [r for r in reqs if not r.done.is_set()]
        self._outstanding[(rep_name, queue)] = live
        return len(live)

    _SWEEP_EVERY = 256

    def _note_submitted(self, rep, tenant, req) -> None:
        key = (rep.name, self.queue_for(tenant))
        reqs = self._outstanding.setdefault(key, [])
        if len(reqs) >= 8:
            self._outstanding[key] = reqs = [
                r for r in reqs if not r.done.is_set()]
        reqs.append(req)
        self._submits_since_sweep += 1
        if self._submits_since_sweep >= self._SWEEP_EVERY:
            self._submits_since_sweep = 0
            live_names = {r.name for r in self.fleet.replicas}
            self._outstanding = {
                k: live for k, v in self._outstanding.items()
                if k[0] in live_names
                and (live := [r for r in v if not r.done.is_set()])}
            # reaped replicas' residency snapshots go with them (the
            # reap side of snapshot invalidation; epoch mismatches
            # handle every registration/eviction on live replicas)
            self._res_cache = {name: snap for name, snap
                               in self._res_cache.items()
                               if name in live_names}

    def _over_share(self, rep, queue: str) -> bool:
        """Would this queue exceed its fair share of ``rep``'s
        outstanding work? Share = replica lanes split evenly over the
        queues currently holding work there (at least one lane each)."""
        holders = {q for (name, q), reqs in self._outstanding.items()
                   if name == rep.name and self._live(name, q) > 0}
        holders.add(queue)
        share = max(rep.engine.lanes // len(holders), 1)
        return self._live(rep.name, queue) >= share

    # -- placement --------------------------------------------------------

    def select(self, prompt: Sequence[int], tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None,
               model: Optional[str] = None):
        reps = self._candidates(version)
        probe = prefix if prefix is not None else prompt
        model = (model or "") if self.adapter_affinity else ""
        # adapter residency DOMINATES prefix residency: adapter weight
        # pages are the heavier thing to move (a fault allocates pages
        # and may evict another model), and a prefix can be registered
        # cheaply wherever the adapter lives — never the reverse
        scored = [(1 if model and self._adapter_resident(rep, model)
                   else 0,
                   self._residency(rep, probe, model),
                   -rep.engine.queue_depth, -i, rep)
                  for i, rep in enumerate(reps)]
        scored.sort(reverse=True)   # adapter desc, residency desc,
        best = scored[0][4]         # depth asc, FIFO
        if model and scored[0][0] == 0:
            # model resident nowhere: give it a stable consistent-hash
            # home so the fleet PARTITIONS the catalog — each replica's
            # pool converges on its slice of the models instead of
            # every replica churning through all of them
            best = reps[_model_home(model, len(reps))]
        elif not model and scored[0][1] == 0 and prefix is not None:
            # (model requests skip prefix homing: wherever the adapter
            # lives — or was just homed — is where the prefix belongs)
            # nowhere warm: give the prefix a stable home so its NEXT
            # requests find it resident (and other prefixes' homes stay
            # unpolluted) instead of piling every cold prefix onto the
            # emptiest replica
            best = reps[_prefix_home(prefix, len(reps))]
        queue = self.queue_for(tenant)
        if len(scored) > 1 and best.engine.queue_depth \
                >= self.hot_queue_depth and self._over_share(best, queue):
            # the warm replica is contended AND this tenant's queue
            # already holds its share of it: spill to the least-loaded
            # other replica instead of monopolizing the prefix-warm one
            others = sorted(((rep.engine.queue_depth, i, rep)
                             for i, (_, _, _, _, rep) in enumerate(scored)
                             if rep is not best))
            self.tenant_spills += 1
            if self.metrics is not None:
                self.metrics.router_tenant_spills.inc(queue=queue)
            return others[0][2]
        return best
