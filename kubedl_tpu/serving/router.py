"""Prefix-cache-aware request routing with per-tenant fairness.

The fleet's placement layer (docs/serving_fleet.md): a request whose
prompt starts with a registered shared prefix should land on the replica
ALREADY holding that prefix's pool blocks — the refcounted
:class:`~kubedl_tpu.serving.batching.BlockPool` makes residency a pure
host-side read (``engine.prefix_residency``), so placement costs no
device work. Two guards keep affinity honest:

* **router-driven registration**: a declared prefix the chosen replica
  has never seen is registered there on first placement (the engine's
  least-recently-hit eviction means this can never wedge a warm
  replica's full prefix cache);
* **per-tenant fairness**, reusing the Queue API's tenant routing
  (``api/queue.QueueSpec.tenants`` — the same attribution the slice
  scheduler routes jobs by): when the preferred replica is hot (its
  queue is backed up) and one tenant's queue already holds its fair
  share of that replica's outstanding work, the placement spills to the
  next-best replica instead of letting the hot tenant monopolize the
  prefix-warm one.

:class:`RandomRouter` is the control arm the routing leg of
``bench_serving_fleet.py`` compares against: identical traffic,
identical router-driven registration, placement by seeded uniform draw.
"""

from __future__ import annotations

import hashlib
import random
from typing import Optional, Sequence

from ..api.queue import DEFAULT_QUEUE


def _prefix_home(prefix, n: int) -> int:
    """Stable home replica for a cold prefix: a consistent hash of its
    tokens over the active set, so the fleet's prefix caches partition
    the catalog instead of every replica churning through all of it."""
    digest = hashlib.sha256(
        ",".join(str(int(t)) for t in prefix).encode()).digest()
    return int.from_bytes(digest[:8], "big") % n


class RandomRouter:
    """Uniform placement over non-draining replicas (the baseline)."""

    def __init__(self, fleet, seed: int = 0, max_prefixes: int = 8,
                 metrics=None):
        self.fleet = fleet
        self.rng = random.Random(f"{seed}:router")
        #: per-replica prefix-cache cap for router-driven registration
        self.max_prefixes = int(max_prefixes)
        self.metrics = metrics
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.tenant_spills = 0
        self.routed: dict = {}           # replica name -> placements

    # -- placement --------------------------------------------------------

    def select(self, prompt: Sequence[int], tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None):
        reps = self._candidates(version)
        return reps[self.rng.randrange(len(reps))]

    def _candidates(self, version: Optional[int]) -> list:
        """Active replicas, optionally pinned to one policy version —
        the rollout path's guarantee that every completion in a batch
        came from the SAME weights (docs/rl.md: a mixed-version batch
        has no well-defined behavior policy)."""
        reps = self.fleet.active()
        if version is not None:
            reps = [r for r in reps
                    if getattr(r, "policy_version", 0) == version]
            if not reps:
                raise RuntimeError(
                    f"no active replica serving policy version "
                    f"{version} (mid-publish, or the version was "
                    "already rolled past)")
        if not reps:
            raise RuntimeError("no active serving replica (fleet empty "
                               "or fully draining)")
        return reps

    def _ensure_prefix(self, rep, prefix) -> None:
        if not rep.engine.has_prefix(prefix):
            rep.engine.register_prefix(list(prefix),
                                       max_prefixes=self.max_prefixes)

    def _account(self, rep, prefix) -> None:
        self.routed[rep.name] = self.routed.get(rep.name, 0) + 1
        if prefix is not None:
            if rep.engine.prefix_residency(prefix) > 0:
                self.prefix_hits += 1
                if self.metrics is not None:
                    self.metrics.router_prefix_hits.inc()
            else:
                self.prefix_misses += 1
                if self.metrics is not None:
                    self.metrics.router_prefix_misses.inc()

    def submit(self, prompt: Sequence[int], max_new: int,
               tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None, **kw):
        """Place + submit one request; returns ``(Request, replica)``.
        ``prefix`` is the client-declared shared prefix (system prompt)
        — the placement signal and the router-driven registration
        unit. ``version`` pins placement to replicas advertising that
        policy version (the rollout tenant's same-weights guarantee)."""
        rep = self.select(prompt, tenant=tenant, prefix=prefix,
                          version=version)
        self._account(rep, prefix)
        if prefix is not None:
            self._ensure_prefix(rep, prefix)
        req = rep.engine.submit(prompt, max_new, **kw)
        self._note_submitted(rep, tenant, req)
        return req, rep

    def _note_submitted(self, rep, tenant, req) -> None:
        """Fairness bookkeeping hook (no-op for the random baseline)."""

    def stats(self) -> dict:
        total = self.prefix_hits + self.prefix_misses
        return {
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": round(self.prefix_hits / total, 4)
            if total else None,
            "tenant_spills": self.tenant_spills,
            "routed": {k: self.routed[k] for k in sorted(self.routed)},
        }


class PrefixAwareRouter(RandomRouter):
    """Place on the replica already holding the request's shared prefix
    blocks; fairness spills a hot tenant off the warm replica."""

    def __init__(self, fleet, seed: int = 0, max_prefixes: int = 8,
                 queues: Sequence = (), hot_queue_depth: int = 4,
                 metrics=None):
        super().__init__(fleet, seed=seed, max_prefixes=max_prefixes,
                         metrics=metrics)
        #: tenant -> queue name, from the Queue API's tenant lists (the
        #: slice scheduler's exact routing rule, docs/scheduling.md);
        #: unrouted tenants land on the implicit default queue
        self._tenant_queue: dict = {}
        for q in queues:
            for t in getattr(q, "tenants", ()) or ():
                self._tenant_queue.setdefault(t, q.name)
        #: replica hotness bar: at or past this queue depth the replica
        #: is contended and fairness applies
        self.hot_queue_depth = int(hot_queue_depth)
        #: (replica name, queue) -> live Requests (pruned lazily on
        #: reads, and swept every ``_SWEEP_EVERY`` submits so a
        #: long-lived server below the hotness bar — where _over_share
        #: never reads — cannot grow this without bound, and keys of
        #: reaped replicas don't live forever)
        self._outstanding: dict = {}
        self._submits_since_sweep = 0

    def queue_for(self, tenant: Optional[str]) -> str:
        if not tenant:
            return DEFAULT_QUEUE
        return self._tenant_queue.get(tenant, DEFAULT_QUEUE)

    # -- fairness bookkeeping --------------------------------------------

    def _live(self, rep_name: str, queue: str) -> int:
        reqs = self._outstanding.get((rep_name, queue))
        if not reqs:
            return 0
        live = [r for r in reqs if not r.done.is_set()]
        self._outstanding[(rep_name, queue)] = live
        return len(live)

    _SWEEP_EVERY = 256

    def _note_submitted(self, rep, tenant, req) -> None:
        key = (rep.name, self.queue_for(tenant))
        reqs = self._outstanding.setdefault(key, [])
        if len(reqs) >= 8:
            self._outstanding[key] = reqs = [
                r for r in reqs if not r.done.is_set()]
        reqs.append(req)
        self._submits_since_sweep += 1
        if self._submits_since_sweep >= self._SWEEP_EVERY:
            self._submits_since_sweep = 0
            live_names = {r.name for r in self.fleet.replicas}
            self._outstanding = {
                k: live for k, v in self._outstanding.items()
                if k[0] in live_names
                and (live := [r for r in v if not r.done.is_set()])}

    def _over_share(self, rep, queue: str) -> bool:
        """Would this queue exceed its fair share of ``rep``'s
        outstanding work? Share = replica lanes split evenly over the
        queues currently holding work there (at least one lane each)."""
        holders = {q for (name, q), reqs in self._outstanding.items()
                   if name == rep.name and self._live(name, q) > 0}
        holders.add(queue)
        share = max(rep.engine.lanes // len(holders), 1)
        return self._live(rep.name, queue) >= share

    # -- placement --------------------------------------------------------

    def select(self, prompt: Sequence[int], tenant: Optional[str] = None,
               prefix: Optional[Sequence[int]] = None,
               version: Optional[int] = None):
        reps = self._candidates(version)
        probe = prefix if prefix is not None else prompt
        scored = [(rep.engine.prefix_residency(probe),
                   -rep.engine.queue_depth, -i, rep)
                  for i, rep in enumerate(reps)]
        scored.sort(reverse=True)        # residency desc, depth asc, FIFO
        best = scored[0][3]
        if scored[0][0] == 0 and prefix is not None:
            # nowhere warm: give the prefix a stable home so its NEXT
            # requests find it resident (and other prefixes' homes stay
            # unpolluted) instead of piling every cold prefix onto the
            # emptiest replica
            best = reps[_prefix_home(prefix, len(reps))]
        queue = self.queue_for(tenant)
        if len(scored) > 1 and best.engine.queue_depth \
                >= self.hot_queue_depth and self._over_share(best, queue):
            # the warm replica is contended AND this tenant's queue
            # already holds its share of it: spill to the least-loaded
            # other replica instead of monopolizing the prefix-warm one
            others = sorted(((rep.engine.queue_depth, i, rep)
                             for i, (_, _, _, rep) in enumerate(scored)
                             if rep is not best))
            self.tenant_spills += 1
            if self.metrics is not None:
                self.metrics.router_tenant_spills.inc(queue=queue)
            return others[0][2]
        return best
