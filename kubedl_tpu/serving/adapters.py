"""Multi-model serving: LoRA adapter catalog + paged weight residency.

A production fleet serves many fine-tuned variants of one base model,
not one model per replica (docs/multimodel.md). The scarce resource is
replica HBM, and the policy question is which adapters stay resident
where. This module answers it with the SAME machinery the KV cache
already uses:

* an :class:`AdapterCatalog` — the fleet-wide registry of adapters
  (pure specs: model id + how many pool pages its LoRA weights occupy);
* an :class:`AdapterResidency` per engine — adapter weight pages
  allocate from the engine's refcounted
  :class:`~kubedl_tpu.serving.batching.BlockPool`, exactly like KV
  blocks: a load PINS the pages (refcount 1), every admitted request
  increfs them for the life of its lane, and an eviction decrefs only
  the pin — in-flight requests finish on the departing adapter and the
  pages return to the pool when the last lane drains (the
  ``register_prefix`` eviction contract, applied to weights).

Eviction follows the prefix cache's hardened rules verbatim: at
``max_resident`` the LEAST-RECENTLY-HIT unpinned adapter is evicted;
``pinned=`` adapters are exempt; only an all-pinned catalog still
rejects. The LoRA math itself lives in :mod:`kubedl_tpu.ops.lora`
(``mm_lora``); residency is host-side accounting — greedy token
outputs are identical across adapters by construction, which is what
keeps the replay and bench legs bit-for-bit deterministic.

Everything here mutates under the owning engine's ``_sched_lock`` (the
engine calls in from admission / free / recover paths); the catalog
itself is immutable-after-setup shared state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class AdapterSpec:
    """One adapter's fleet-wide description (a pure value).

    ``pages`` is how many pool blocks the adapter's LoRA weights pin
    while resident — the HBM currency shared with KV blocks. ``rank``
    is the LoRA rank (``ops/lora.py``); it drives ``pages`` for real
    weights but is carried only for reporting here."""
    model: str
    pages: int = 1
    rank: int = 8

    def __post_init__(self):
        if not self.model:
            raise ValueError("adapter model id must be non-empty")
        if self.pages < 1:
            raise ValueError(
                f"adapter {self.model}: pages must be >= 1, got "
                f"{self.pages}")


class AdapterCatalog:
    """Fleet-wide adapter registry.

    One catalog is shared by every replica's engine (read-only after
    setup, like the base params); each engine keeps its OWN
    :class:`AdapterResidency` — which adapters are resident is a
    per-replica decision the router exploits (docs/multimodel.md
    "router homing")."""

    def __init__(self, base_model: str = "base"):
        #: the base model's id; requests carrying it (or no model at
        #: all) need no adapter — the pre-multi-model path, unchanged
        self.base_model = base_model
        self._specs: dict[str, AdapterSpec] = {}

    def register(self, spec: AdapterSpec) -> AdapterSpec:
        if spec.model == self.base_model:
            raise ValueError(
                f"{spec.model!r} is the base model, not an adapter")
        self._specs[spec.model] = spec
        return spec

    def spec(self, model: str) -> Optional[AdapterSpec]:
        return self._specs.get(model)

    def models(self) -> list:
        return sorted(self._specs)

    def normalize(self, model: Optional[str]) -> str:
        """Canonical model id: "" for the base model (however named)."""
        if not model or model == self.base_model:
            return ""
        return model

    def __len__(self) -> int:
        return len(self._specs)

    def __contains__(self, model: str) -> bool:
        return model in self._specs


@dataclass
class _Resident:
    """One adapter resident on one engine: its pinned pool pages and
    how many in-flight requests currently hold increfs on them."""
    spec: AdapterSpec
    pages: tuple = ()
    pinned: bool = False
    active: int = 0


class AdapterResidency:
    """Per-engine adapter residency over the engine's block pool.

    Every method is called with the engine's ``_sched_lock`` held (the
    same discipline as the prefix cache — admission, frees, and
    recovery already run under it), so there is no lock here."""

    def __init__(self, catalog: AdapterCatalog, pool,
                 max_resident: Optional[int] = None):
        self.catalog = catalog
        self._pool = pool
        #: resident-adapter count cap (None = bounded by the pool only);
        #: the multi-model analog of ``max_prefixes``
        self.max_resident = max_resident
        self._resident: dict[str, _Resident] = {}
        #: admission-time hit ordinals — the least-recently-hit order
        #: evictions follow (the ``register_prefix`` LRU, verbatim)
        self._hits: dict[str, int] = {}
        self._hit_clock = 0
        #: lifetime cold fault-ins per model (the router-quality signal
        #: kubedl_serving_adapter_faults_total exposes)
        self.faults: dict[str, int] = {}
        self.evictions = 0
        self.loads = 0
        #: bumped on EVERY residency change (load, fault-in, eviction,
        #: rebuild) — the engine mirrors it into ``residency_epoch`` so
        #: the router's cached snapshots invalidate precisely, even
        #: when an eviction happened without a successful fault
        self.version = 0
        #: most pool blocks ever pinned by adapter weights at once (the
        #: bench's HBM-budget number)
        self.peak_pages = 0

    # -- reads -------------------------------------------------------------

    def is_resident(self, model: str) -> bool:
        return model in self._resident

    def resident_models(self) -> list:
        return sorted(self._resident)

    def resident_pages(self) -> int:
        return sum(len(r.pages) for r in self._resident.values())

    def faults_total(self) -> int:
        return sum(self.faults.values())

    def active_of(self, model: str) -> int:
        r = self._resident.get(model)
        return r.active if r is not None else 0

    def status(self) -> dict:
        """Console/pool_stats snapshot (caller holds the engine lock)."""
        return {
            "resident": self.resident_models(),
            "pinned": sorted(m for m, r in self._resident.items()
                             if r.pinned),
            "pages": self.resident_pages(),
            "peak_pages": self.peak_pages,
            "active": {m: r.active for m, r in
                       sorted(self._resident.items()) if r.active},
            "faults": dict(sorted(self.faults.items())),
            "evictions": self.evictions,
            "loads": self.loads,
        }

    # -- residency mutations (engine lock held) ----------------------------

    def _record_hit(self, model: str) -> None:
        self._hit_clock += 1
        self._hits[model] = self._hit_clock

    def _evict_lru(self) -> bool:
        """Evict the least-recently-hit unpinned adapter: the PIN's
        refcount drops; lanes still decoding on it keep the pages alive
        until they finish (refcounts drain to zero — the prefix
        contract). False when every resident adapter is pinned."""
        victims = [m for m, r in self._resident.items() if not r.pinned]
        if not victims:
            return False
        victim = min(victims, key=lambda m: (self._hits.get(m, 0), m))
        ent = self._resident.pop(victim)
        if ent.pages:
            self._pool.decref(ent.pages)
        self._hits.pop(victim, None)
        self.evictions += 1
        self.version += 1
        return True

    def _make_room(self, pages_needed: int) -> Optional[list]:
        """Allocate ``pages_needed`` pin pages, evicting LRU unpinned
        adapters while the cap or the pool blocks the allocation.
        None when no legal eviction can make it fit (the caller
        decides: admission waits, an explicit load raises)."""
        while self.max_resident is not None and \
                len(self._resident) >= self.max_resident:
            if not self._evict_lru():
                raise ValueError(
                    f"adapter limit {self.max_resident} reached and "
                    "every resident adapter is pinned (each adapter "
                    "pins weight pages in HBM)")
        while True:
            got = self._pool.alloc(pages_needed)
            if got is not None:
                return got
            # pool dry: shed idle unpinned adapters (their pages free
            # immediately — nothing increfs an idle pin) until it fits
            if not self._evict_lru():
                return None

    def load(self, model: str, pinned: bool = False) -> None:
        """Explicit operator load (the ``register_prefix`` analog):
        pins the adapter's pages; idempotent re-load refreshes the
        pin flag and the hit clock without net-new pages."""
        spec = self.catalog.spec(model)
        if spec is None:
            raise ValueError(f"unknown adapter {model!r} (not in the "
                             "catalog)")
        ent = self._resident.get(model)
        if ent is not None:
            ent.pinned = bool(pinned)
            self._record_hit(model)
            return
        got = self._make_room(spec.pages)
        if got is None:
            raise ValueError(
                f"KV pool exhausted: adapter {model} needs {spec.pages} "
                f"weight pages, {self._pool.free_count} free")
        self._resident[model] = _Resident(spec=spec, pages=tuple(got),
                                          pinned=bool(pinned))
        self._record_hit(model)
        self.loads += 1
        self.version += 1
        self.peak_pages = max(self.peak_pages, self.resident_pages())

    def ensure(self, model: str):
        """Admission-side residency: ``(pages, faulted)`` with the
        adapter resident on return, or ``(None, False)`` when no legal
        eviction can make room (the admission gate treats that like a
        dry pool: the head waits). A cold adapter FAULTS IN here —
        counted per model — before the request's first tick."""
        ent = self._resident.get(model)
        if ent is not None:
            return ent.pages, False
        spec = self.catalog.spec(model)
        if spec is None:
            raise ValueError(f"unknown adapter {model!r} (not in the "
                             "catalog)")
        got = self._make_room(spec.pages)
        if got is None:
            return None, False
        self._resident[model] = _Resident(spec=spec, pages=tuple(got))
        # seed the hit clock at fault-in (the prefix cache's rule):
        # a just-faulted adapter must rank by fault recency, never tie
        # at 0 where churn could evict it before its request attaches
        self._record_hit(model)
        self.faults[model] = self.faults.get(model, 0) + 1
        self.loads += 1
        self.version += 1
        self.peak_pages = max(self.peak_pages, self.resident_pages())
        return self._resident[model].pages, True

    def attach(self, model: str) -> list:
        """Bind one admitted request to the resident adapter: incref
        the weight pages (the lane's share) and count it active. The
        caller stores the returned blocks on the lane and hands them
        back through :meth:`release` exactly once."""
        ent = self._resident[model]
        self._pool.incref(ent.pages)
        ent.active += 1
        self._record_hit(model)
        return list(ent.pages)

    def release(self, model: str, blocks) -> None:
        """Drop one request's share of the adapter pages (lane
        finished / cancelled / preempted). Safe after the adapter was
        evicted mid-flight: the blocks list is the lane's own incref,
        and the active count only tracks still-resident entries."""
        if blocks:
            self._pool.decref(blocks)
        ent = self._resident.get(model)
        if ent is not None and ent.active > 0:
            ent.active -= 1

    def rebuild(self, pool) -> None:
        """Re-pin every resident adapter into a FRESH pool after the
        engine recovered from a failed step (the ``_recover_locked``
        path: the old pool was donated into the dead computation, and
        every lane incref died with it — active counts restart at 0).
        Cannot fail: the fresh pool has at least as much room as when
        the adapters first loaded."""
        self._pool = pool
        for ent in self._resident.values():
            ent.pages = tuple(pool.alloc(len(ent.pages) or
                                         ent.spec.pages))
            ent.active = 0
        self.version += 1


__all__ = ["AdapterSpec", "AdapterCatalog", "AdapterResidency"]
