"""Inference HTTP server: the predictor container's process.

The reference's Inference controller points predictor Deployments at
TFServing/Triton images (``controllers/serving/framework/tfserving.go``);
kubedl-tpu predictors run this server instead. API shape follows the
TFServing REST convention the console/tooling already speak:

* ``POST /v1/models/{name}:predict`` — body
  ``{"instances": [{"prompt_tokens": [...], "max_tokens": N}]}`` →
  ``{"predictions": [{"tokens": [...]}]}``; instances in one request are
  batched into a single generate call (static-shape bucket). When the
  server has a tokenizer (``$KUBEDL_TOKENIZER``), an instance may say
  ``{"text": "..."}`` or ``{"messages": [{"role": ..., "content": ...},
  ...]}`` (chat-templated for instruct checkpoints) instead of
  ``prompt_tokens``, and every prediction gains a decoded ``"text"``
  field — end-to-end text serving;
* ``POST /v1/models/{name}:predict`` with ``"stream": true`` (single
  instance) — Server-Sent Events: one ``data: {"token": id}`` event per
  generated token as it decodes (time-to-first-token = one prefill, not
  the whole generation), then a final ``data: {"done": true, "tokens":
  [...]}`` summary event. Rides the continuous-batching engine's
  per-token lane output (``Request.stream``); on the static engine the
  tokens are emitted after the batch completes (degraded but correct);
* ``POST /v1/models/{name}:registerPrefix`` — body
  ``{"prefix_tokens": [...]}``: prefill a shared system prompt once; later
  prompts starting with it load the cached KV block and prefill only the
  suffix (continuous-batching engine only);
* ``GET /v1/models/{name}`` — model status (readiness probe target);
* ``GET /metrics`` — Prometheus exposition (request counts/latency, TTFT,
  generated-token totals), same registry format the operator exports;
* ``GET /healthz`` — liveness.

With a tokenizer configured the server also speaks the **OpenAI
convention** — the de-facto client standard — adapted onto the same
engine paths (identical validation, metrics, and lane scheduling):

* ``POST /v1/completions`` — ``prompt`` as a string, list of strings, or
  token-id array; ``n``, ``max_tokens``, ``temperature``/``top_p``,
  ``stop`` (host-side text match), ``stream`` (SSE chunks terminated by
  ``data: [DONE]``);
* ``POST /v1/chat/completions`` — ``messages`` rendered through the
  tokenizer's chat template (``tokenizer.render_chat``), buffered or
  streaming delta chunks;
* ``POST /v1/embeddings`` — masked mean-pool of the final hidden
  states, L2-normalized (decoder-as-embedder);
* ``GET /v1/models`` — model listing.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..metrics.registry import Registry
from .engine import InferenceEngine


@dataclass
class ServerConfig:
    model_name: str = "model"
    host: str = "0.0.0.0"
    port: int = 8501               # TFServing's REST port
    max_batch: int = 16
    max_new_tokens: int = 256
    #: continuous-batching mode: bound on one request's wall time so a
    #: stopped/never-started engine surfaces as a JSON 500, not a hang
    request_timeout_s: float = 600.0
    #: cap on distinct registered prefixes — each holds a per-layer KV
    #: block in HBM and the engine never evicts, so an uncapped route
    #: would let clients OOM the device
    max_prefixes: int = 8
    #: optional text codec (``kubedl_tpu.tokenizer``): enables "text"
    #: instances and decoded "text" in predictions/stream events
    tokenizer: Optional[object] = None
    #: periodic stats hook (docs/telemetry.md): called on every metrics
    #: refresh with ``{"decode_tokens_per_s": ...}`` measured from the
    #: token counter since the last refresh. Operator-side embeddings
    #: pass ``FleetTelemetry.observe_serving_stats`` partially applied
    #: with (model, pool), closing the Gavel-currency loop from serving
    #: into the ThroughputProfileStore the placement scorer reads.
    stats_hook: Optional[object] = None


class InferenceServer:
    def __init__(self, engine: InferenceEngine,
                 config: Optional[ServerConfig] = None):
        self.engine = engine
        self.config = config or ServerConfig()
        # one generate at a time: the TPU is serial anyway, and interleaved
        # donated caches would alias
        self._gen_lock = threading.Lock()
        # itertools.count: next() is a single C call, safe under
        # ThreadingHTTPServer's concurrent handlers without a lock
        import itertools
        self._openai_ids = itertools.count(1)
        self._created = int(time.time())   # OpenAI model-object field
        self._embed_fn = None        # lazily-built jitted embedder
        self.metrics = Registry()
        self._m_requests = self.metrics.counter(
            "kubedl_serving_requests_total",
            "Prediction requests by mode and outcome",
            labels=("mode", "status"))
        self._m_tokens = self.metrics.counter(
            "kubedl_serving_generated_tokens_total",
            "Tokens generated across all requests")
        self._m_latency = self.metrics.histogram(
            "kubedl_serving_request_seconds",
            "Wall time per prediction request", labels=("mode",),
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60))
        self._m_ttft = self.metrics.histogram(
            "kubedl_serving_ttft_seconds",
            "Time to first streamed token",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10))
        self._m_kv = None
        if hasattr(engine, "pool_stats"):
            # continuous-batching predictors: paged KV pool occupancy,
            # prefix-sharing ratio and preemption counter on the scrape
            # page (dense mode still reports peak active lanes)
            from ..metrics.registry import PagedKVMetrics
            self._m_kv = PagedKVMetrics(self.metrics)
        self._m_spec = None
        self._m_spec_lane = None
        if hasattr(engine, "stats") and \
                hasattr(engine.stats, "acceptance_rate"):
            # speculative predictors: draft quality on the scrape page
            self._m_spec = (
                self.metrics.gauge("kubedl_serving_spec_proposed_total",
                                   "Draft tokens proposed"),
                self.metrics.gauge("kubedl_serving_spec_accepted_total",
                                   "Draft tokens accepted"),
                self.metrics.gauge("kubedl_serving_spec_acceptance_rate",
                                   "Lifetime draft acceptance rate"))
            if hasattr(engine, "lane_stats"):
                # the continuous engine's per-lane acceptance: a lane
                # whose requests draft poorly shows up here, not just in
                # the lifetime aggregate
                self._m_spec_lane = self.metrics.gauge(
                    "kubedl_serving_spec_lane_acceptance_rate",
                    "Draft acceptance rate per continuous-batching lane",
                    labels=("lane",))

        self._stats_last = (time.monotonic(), 0.0)

        def _refresh_engine_metrics():
            if self.config.stats_hook is not None:
                now_m = time.monotonic()
                tokens = self._m_tokens.value()
                last_t, last_tok = self._stats_last
                dt, dtok = now_m - last_t, tokens - last_tok
                if dt > 0 and dtok > 0:
                    self._stats_last = (now_m, tokens)
                    try:
                        self.config.stats_hook(
                            {"decode_tokens_per_s": dtok / dt})
                    except Exception as e:  # noqa: BLE001 — telemetry
                        # must never take the serving path down with it
                        logging.getLogger("kubedl.serving").warning(
                            "stats hook failed: %s", e)
            if self._m_kv is not None:
                self._m_kv.refresh(engine.pool_stats())
            if self._m_spec is not None:
                st = engine.stats
                self._m_spec[0].set(st.proposed)
                self._m_spec[1].set(st.accepted)
                self._m_spec[2].set(st.acceptance_rate)
            if self._m_spec_lane is not None:
                for i, ls in enumerate(engine.lane_stats):
                    self._m_spec_lane.set(ls.acceptance_rate,
                                          lane=str(i))
        self.refresh_engine_metrics = _refresh_engine_metrics
        server = self

        class Handler(_Handler):
            server_ref = server

        self._httpd = ThreadingHTTPServer(
            (self.config.host, self.config.port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self.config.host if self.config.host != "0.0.0.0" else "127.0.0.1"
        return f"http://{host}:{self.port}"

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="kubedl-inference", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- request handling --------------------------------------------------

    def _parse_instance(self, inst: dict) -> tuple:
        """(prompt, cap, want_logprobs, sampling) — the ONE validation/
        coercion rule for buffered and streaming predicts alike.
        ``sampling`` holds optional per-request temperature/top_k/top_p
        overrides (continuous-batching engines apply them per lane)."""
        toks = inst.get("prompt_tokens")
        if toks is None and ("text" in inst or "messages" in inst):
            tok = self.config.tokenizer
            if tok is None:
                raise ValueError(
                    "this predictor has no tokenizer (set "
                    "$KUBEDL_TOKENIZER); send prompt_tokens instead")
            if "messages" in inst:
                from ..tokenizer import render_chat
                toks = render_chat(tok, inst["messages"])
            else:
                if not isinstance(inst["text"], str) or not inst["text"]:
                    raise ValueError("text must be a non-empty string")
                from ..tokenizer import encode_prompt
                toks = encode_prompt(tok, inst["text"])
        if not isinstance(toks, list) or not toks:
            raise ValueError("each instance needs prompt_tokens or text")
        prompt = [int(t) for t in toks]
        cap = min(int(inst.get("max_tokens", 16)),
                  self.config.max_new_tokens)
        sampling = {}
        if "temperature" in inst:
            sampling["temperature"] = float(inst["temperature"])
        if "top_k" in inst:
            sampling["top_k"] = int(inst["top_k"])
        if "top_p" in inst:
            sampling["top_p"] = float(inst["top_p"])
        return prompt, cap, bool(inst.get("logprobs")), sampling

    def predict(self, body: dict) -> dict:
        instances = body.get("instances") or []
        if not instances:
            raise ValueError("no instances")
        if len(instances) > self.config.max_batch:
            raise ValueError(
                f"batch {len(instances)} exceeds max_batch "
                f"{self.config.max_batch}")
        prompts, caps, want_lp, samplings = [], [], [], []
        for inst in instances:
            p, cap, lp, sampling = self._parse_instance(inst)
            prompts.append(p)
            caps.append(cap)
            want_lp.append(lp)
            samplings.append(sampling)
        if hasattr(self.engine, "submit"):
            # continuous-batching engine: each instance rides its own lane
            # (its background loop serializes device work — no lock), so a
            # short request is never held back to the longest one's length.
            # Validate ALL instances before submitting any — a bad late
            # instance must 400 without burning lanes on discarded output.
            for p, cap, s in zip(prompts, caps, samplings):
                self.engine.validate(p, cap)
                self.engine.validate_sampling(**s)
            reqs = [self.engine.submit(p, cap, logprobs=lp, **s)
                    for p, cap, lp, s in zip(prompts, caps, want_lp,
                                             samplings)]
            timeout = self.config.request_timeout_s
            preds = []
            try:
                for r, lp in zip(reqs, want_lp):
                    pred = {"tokens": r.result(timeout=timeout)}
                    if lp:
                        pred["logprobs"] = r.logprobs
                    preds.append(pred)
            except BaseException:
                # a timed-out (or aborted) buffered batch must not keep
                # burning lanes: every request still decoding would run
                # to its full cap into discarded output (ADVICE r4) —
                # cancel them before surfacing the error
                for r in reqs:
                    if not r.done.is_set():
                        r.cancel()
                raise
            finally:
                # tokens already generated by earlier requests in the
                # batch are real device work even when a later request
                # times out — account for the snapshot either way
                self._m_tokens.inc(sum(len(r.tokens) for r in reqs))
            return {"predictions": self._decorate_text(preds)}
        # static engine: decode to the longest request in one lockstep
        # batch, trim per instance to its own cap. Its sampler is
        # engine-wide — per-instance overrides need the lane engine.
        if any(samplings):
            raise ValueError(
                "per-request sampling params need the continuous-"
                "batching engine (this predictor runs the static one)")
        wl = any(want_lp)
        with self._gen_lock:
            outs = self.engine.generate(prompts, max(caps),
                                        return_logprobs=wl)
        preds = []
        for o, cap, lp in zip(outs, caps, want_lp):
            toks, lps = o if wl else (o, None)
            pred = {"tokens": toks[:cap]}
            if lp:
                pred["logprobs"] = lps[:cap]
            preds.append(pred)
        self._m_tokens.inc(sum(len(p["tokens"]) for p in preds))
        return {"predictions": self._decorate_text(preds)}

    def _decorate_text(self, preds: list) -> list:
        if self.config.tokenizer is not None:
            for p in preds:
                p["text"] = self.config.tokenizer.decode(p["tokens"])
        return preds

    def _with_text_events(self, events):
        """Add incremental ``"text"`` deltas to stream events (and the
        full decode to the final summary) when a tokenizer is configured.
        Token events whose bytes are mid-UTF-8-sequence carry an empty
        delta; the missing text arrives with the completing token."""
        from ..tokenizer import StreamDecoder
        dec = StreamDecoder(self.config.tokenizer)
        for ev in events:
            if "token" in ev:
                ev["text"] = dec.push(ev["token"])
            elif ev.get("done"):
                # full re-decode, not the decoder's held-back tail: the
                # summary must equal decode(tokens) exactly
                ev["text"] = self.config.tokenizer.decode(ev["tokens"])
            yield ev

    def predict_stream(self, body: dict):
        """Yield SSE event dicts for a single-instance streaming request.

        Validation errors raise BEFORE the first yield (the handler can
        still send a 400); anything after the first event is reported as
        a terminal ``{"error": ...}`` event on the open stream."""
        instances = body.get("instances") or []
        if len(instances) != 1:
            raise ValueError("stream mode takes exactly one instance")
        prompt, cap, want_lp, sampling = self._parse_instance(instances[0])

        if hasattr(self.engine, "submit"):
            self.engine.validate(prompt, cap)
            self.engine.validate_sampling(**sampling)   # before the 200

            def events():
                t0 = time.perf_counter()
                req = self.engine.submit(prompt, cap, logprobs=want_lp,
                                         **sampling)
                out, lps = [], []
                try:
                    # per-token bound: a stalled engine surfaces as an
                    # error event, not a silently frozen stream
                    for tok, lp in req.stream(
                            timeout=self.config.request_timeout_s):
                        if not out:
                            self._m_ttft.observe(time.perf_counter() - t0)
                        out.append(tok)
                        # per token, not on completion: an aborted stream
                        # must still account for what it already served
                        self._m_tokens.inc()
                        ev = {"token": tok}
                        if lp is not None:
                            ev["logprob"] = lp
                            lps.append(lp)
                        yield ev
                finally:
                    # abandoned stream (client disconnect, stop-string
                    # early exit): free the lane instead of decoding the
                    # remaining cap into the void
                    if not req.done.is_set():
                        req.cancel()
                final = {"done": True, "tokens": out}
                if want_lp:
                    final["logprobs"] = lps
                yield final
            return (events() if self.config.tokenizer is None
                    else self._with_text_events(events()))

        # static engine: no incremental lane output — generate fully,
        # then emit token events (correctness-compatible fallback)
        if sampling:
            raise ValueError(
                "per-request sampling params need the continuous-"
                "batching engine (this predictor runs the static one)")

        def events_static():
            t0 = time.perf_counter()
            with self._gen_lock:
                outs = self.engine.generate([prompt], cap,
                                            return_logprobs=want_lp)
            toks_out, lps = outs[0] if want_lp else (outs[0], None)
            toks_out = toks_out[:cap]
            # post-hoc streaming: the first token arrives only after the
            # whole batch generated — the honest TTFT for this engine
            if toks_out:
                self._m_ttft.observe(time.perf_counter() - t0)
            self._m_tokens.inc(len(toks_out))
            for i, tok in enumerate(toks_out):
                ev = {"token": tok}
                if want_lp:
                    ev["logprob"] = lps[i]
                yield ev
            final = {"done": True, "tokens": toks_out}
            if want_lp:
                final["logprobs"] = lps[:cap]
            yield final
        return (events_static() if self.config.tokenizer is None
                else self._with_text_events(events_static()))

    # -- OpenAI-convention adapters ---------------------------------------

    def _openai_tok(self):
        tok = self.config.tokenizer
        if tok is None:
            raise ValueError(
                "OpenAI routes need a tokenizer (set $KUBEDL_TOKENIZER "
                "or ship tokenizer assets with the model)")
        return tok

    def _openai_parse(self, body: dict, chat: bool):
        """(prompt id lists, cap, sampling, stop strings) — the one
        request-to-instances rule for buffered and streaming flavors."""
        tok = self._openai_tok()
        from ..tokenizer import encode_prompt, render_chat
        if chat:
            prompts = [render_chat(tok, body.get("messages"))]
        else:
            p = body.get("prompt")
            if isinstance(p, str):
                prompts = [encode_prompt(tok, p)]
            elif isinstance(p, list) and p and \
                    all(isinstance(t, int) for t in p):
                prompts = [p]                      # token-id array form
            elif isinstance(p, list) and p and \
                    all(isinstance(s, str) for s in p):
                prompts = [encode_prompt(tok, s) for s in p]
            else:
                raise ValueError(
                    "prompt must be a string, list of strings, or "
                    "token-id array")
        n = int(body.get("n", 1))
        if n < 1:
            raise ValueError("n must be >= 1")
        cap = min(int(body.get("max_tokens", 16)),
                  self.config.max_new_tokens)
        sampling = {}
        if "temperature" in body:
            sampling["temperature"] = float(body["temperature"])
        if "top_p" in body:
            sampling["top_p"] = float(body["top_p"])
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        if not (isinstance(stop, list)
                and all(isinstance(s, str) and s for s in stop)):
            raise ValueError("stop must be a string or list of strings")
        return prompts, n, cap, sampling, stop

    @staticmethod
    def _apply_stop(text: str, stop: list):
        """(text truncated at the earliest stop match, matched?)."""
        cut = min((text.index(s) for s in stop if s in text),
                  default=None)
        return (text, False) if cut is None else (text[:cut], True)

    def _openai_id(self, prefix: str) -> str:
        return f"{prefix}-{next(self._openai_ids)}"

    def openai_models(self) -> dict:
        return {"object": "list", "data": [{
            "id": self.config.model_name, "object": "model",
            "created": self._created, "owned_by": "kubedl-tpu"}]}

    def openai_completions(self, body: dict, chat: bool) -> dict:
        prompts, n, cap, sampling, stop = self._openai_parse(body, chat)
        want_lp = bool(body.get("logprobs"))
        res = self.predict({"instances": [
            {"prompt_tokens": p, "max_tokens": cap, "logprobs": want_lp,
             **sampling}
            for p in prompts for _ in range(n)]})
        created = int(time.time())
        tok = self.config.tokenizer
        choices = []
        completion_tokens = 0
        for i, pred in enumerate(res["predictions"]):
            toks = pred["tokens"]
            completion_tokens += len(toks)
            text, matched = self._apply_stop(pred["text"], stop)
            finish = "stop" if matched or len(toks) < cap else "length"
            if matched and want_lp:
                # align logprobs with the truncated text: keep the
                # shortest token prefix whose decode already contains a
                # stop match (clients zip logprobs.tokens against text)
                for j in range(1, len(toks) + 1):
                    if self._apply_stop(tok.decode(toks[:j]), stop)[1]:
                        toks = toks[:j]
                        pred = {**pred,
                                "logprobs": pred["logprobs"][:j]}
                        break
            echo = (not chat) and bool(body.get("echo"))
            prompt_ids = prompts[i // max(n, 1)] if echo else []
            lp = None
            if want_lp:
                pieces = [tok.decode([t]) for t in toks]
                if echo:
                    # OpenAI echo contract: prompt tokens appear in the
                    # logprobs zip too, with null logprobs (we do not
                    # re-score the prompt)
                    pieces = [tok.decode([t])
                              for t in prompt_ids] + pieces
                    pred = {**pred, "logprobs":
                            [None] * len(prompt_ids)
                            + list(pred["logprobs"])}
                if chat:
                    # chat flavor: logprobs.content entries
                    lp = {"content": [
                        {"token": s, "logprob": float(v)}
                        for s, v in zip(pieces, pred["logprobs"])]}
                else:
                    lp = {"tokens": pieces,
                          "token_logprobs": [None if v is None
                                             else float(v)
                                             for v in pred["logprobs"]],
                          "top_logprobs": None, "text_offset": None}
            if chat:
                choices.append({"index": i, "finish_reason": finish,
                                "logprobs": lp,
                                "message": {"role": "assistant",
                                            "content": text}})
            else:
                if echo:
                    # OpenAI echo: the prompt text precedes the
                    # completion (distinct prompts repeat every n)
                    text = tok.decode(prompt_ids) + text
                choices.append({"index": i, "finish_reason": finish,
                                "text": text, "logprobs": lp})
        # each distinct prompt counts once, regardless of n (the OpenAI
        # usage contract clients build cost accounting on)
        prompt_tokens = sum(len(p) for p in prompts)
        return {
            "id": self._openai_id("chatcmpl" if chat else "cmpl"),
            "object": "chat.completion" if chat else "text_completion",
            "created": created, "model": self.config.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": completion_tokens,
                      "total_tokens": prompt_tokens + completion_tokens},
        }

    def openai_embeddings(self, body: dict) -> dict:
        """``POST /v1/embeddings``: masked mean-pool of the model's final
        hidden states, L2-normalized — the standard decoder-as-embedder
        recipe. One jitted forward per (rows, padded-length) bucket;
        serialized with generation on the device."""
        tok = self._openai_tok()
        from ..tokenizer import encode_prompt
        inp = body.get("input")
        if isinstance(inp, str):
            texts = [inp]
        elif isinstance(inp, list) and inp and \
                all(isinstance(s, str) for s in inp):
            texts = inp
        else:
            raise ValueError("input must be a string or list of strings")
        if len(texts) > self.config.max_batch:
            raise ValueError(f"batch {len(texts)} exceeds max_batch "
                             f"{self.config.max_batch}")
        ids = [encode_prompt(tok, t) for t in texts]

        import jax
        import jax.numpy as jnp
        import numpy as np

        from .engine import resolve_family
        eng = self.engine
        # every engine kind exposes config/params (the speculative
        # adapter forwards its TARGET model's — ADVICE r4: embeddings on
        # a speculative predictor used to 500 with AttributeError)
        config, params = eng.config, eng.params
        family = resolve_family(config)
        longest = max(len(r) for r in ids)
        pad_to = min(-(-longest // 128) * 128,
                     getattr(config, "max_seq_len", 2048))
        if longest > pad_to:
            raise ValueError(
                f"input of {longest} tokens exceeds the model context "
                f"{pad_to}")
        if self._embed_fn is None:
            def embed(params, tokens, nreal):
                out = family.forward_hidden(config, params, tokens)
                x = out[0] if isinstance(out, tuple) else out  # moe aux
                mask = (jnp.arange(x.shape[1])[None, :]
                        < nreal[:, None]).astype(jnp.float32)
                pooled = jnp.sum(x.astype(jnp.float32) * mask[..., None],
                                 axis=1) / jnp.maximum(
                    jnp.sum(mask, axis=1, keepdims=True), 1.0)
                return pooled / jnp.maximum(
                    jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
            # jit caches per input SHAPE; row counts are bucketed below
            # so compiles are bounded by length buckets, not by every
            # distinct client batch size
            self._embed_fn = jax.jit(embed)
        rows = 1
        while rows < len(ids):
            rows *= 2
        toks = np.zeros((rows, pad_to), np.int32)
        for i, r in enumerate(ids):
            toks[i, :len(r)] = r
        nreal = np.zeros((rows,), np.int32)
        nreal[:len(ids)] = [len(r) for r in ids]
        with self._gen_lock:
            vecs = np.asarray(self._embed_fn(
                params, jnp.asarray(toks),
                jnp.asarray(nreal)))[:len(ids)]
        n_tok = int(nreal.sum())
        return {
            "object": "list", "model": self.config.model_name,
            "data": [{"object": "embedding", "index": i,
                      "embedding": [float(v) for v in vec]}
                     for i, vec in enumerate(vecs)],
            "usage": {"prompt_tokens": n_tok, "total_tokens": n_tok},
        }

    def openai_stream(self, body: dict, chat: bool):
        """SSE chunk generator (validates before the first yield).
        Yields dicts (JSON chunks) and finally the raw ``[DONE]``
        sentinel string."""
        prompts, n, cap, sampling, stop = self._openai_parse(body, chat)
        if len(prompts) != 1 or n != 1:
            raise ValueError("stream mode takes one prompt with n=1")
        events = self.predict_stream({"instances": [
            {"prompt_tokens": prompts[0], "max_tokens": cap,
             **sampling}]})
        rid = self._openai_id("chatcmpl" if chat else "cmpl")
        created = int(time.time())
        obj = "chat.completion.chunk" if chat else "text_completion"

        def chunk(piece=None, finish=None, role=None):
            if chat:
                delta = {}
                if role is not None:
                    delta["role"] = role
                if piece:
                    delta["content"] = piece
                choice = {"index": 0, "delta": delta,
                          "finish_reason": finish}
            else:
                choice = {"index": 0, "text": piece or "",
                          "finish_reason": finish}
            return {"id": rid, "object": obj, "created": created,
                    "model": self.config.model_name, "choices": [choice]}

        def gen():
            if chat:
                yield chunk(role="assistant")
            elif body.get("echo"):
                # OpenAI streams the echoed prompt before the deltas
                yield chunk(piece=self.config.tokenizer.decode(
                    prompts[0]))
            # hold back enough text that a stop string split across
            # token boundaries is still caught before it reaches the
            # client
            holdback = max((len(s) for s in stop), default=1) - 1
            pending = ""
            seen = ""       # all text received, incl. still-pending
            finish = None
            n_out = 0
            for ev in events:
                if "token" in ev:
                    n_out += 1
                    piece = ev.get("text", "")
                elif ev.get("done"):
                    # bytes the incremental decoder held back (a
                    # generation cut mid-UTF-8-character) only appear in
                    # the summary's full decode — emit the missing tail
                    piece = ev.get("text", "")[len(seen):]
                else:
                    continue
                seen += piece
                pending += piece
                cut, matched = self._apply_stop(pending, stop)
                if matched:
                    if cut:
                        yield chunk(piece=cut)
                    finish = "stop"
                    # closing `events` (GeneratorExit -> its finally)
                    # cancels the lane, so the device stops decoding
                    # tokens nobody will read
                    events.close()
                    break
                emit = (pending[:-holdback] if holdback
                        and len(pending) > holdback else
                        ("" if holdback else pending))
                if emit:
                    yield chunk(piece=emit)
                    pending = pending[len(emit):]
            if finish is None:
                if pending:
                    yield chunk(piece=pending)
                finish = "stop" if n_out < cap else "length"
            yield chunk(finish=finish)
            yield "[DONE]"
        return gen()

    def register_prefix(self, body: dict) -> dict:
        """Stash a shared prompt prefix's KV block (continuous-batching
        engines only — the static engine has no shared cache to load)."""
        toks = body.get("prefix_tokens")
        if not isinstance(toks, list) or not toks:
            raise ValueError("prefix_tokens is required")
        if not hasattr(self.engine, "register_prefix"):
            raise ValueError(
                "this engine does not support prefix caching")
        # the engine enforces the cap under its own lock (atomic with
        # the store; idempotent re-registration of a stored prefix
        # passes; over the cap the least-recently-hit unpinned prefix
        # is evicted — only an all-pinned cache still rejects).
        # `pinned` exempts THIS prefix from that eviction
        # (docs/serving_fleet.md: operator-pinned system prompts
        # survive router-driven registration churn). `model` scopes the
        # prefix to one adapter (docs/multimodel.md): two models'
        # identical token prefixes must never alias each other's KV
        # blocks — omitted, the prefix belongs to the base model and
        # existing callers are untouched.
        kw = {}
        model = str(body.get("model") or "")
        if model:
            if not getattr(self.engine, "multi_model", False):
                raise ValueError(
                    f"model {model!r} requested but this engine serves "
                    "only its base model (no adapter catalog configured)")
            model = self.engine.validate_model(model)
            if model:
                kw["model"] = model
        self.engine.register_prefix([int(t) for t in toks],
                                    max_prefixes=self.config.max_prefixes,
                                    pinned=bool(body.get("pinned")), **kw)
        out = {"registered": len(toks)}
        if model:
            out["model"] = model
        return out

    def status(self) -> dict:
        return {"model_version_status": [{
            "version": "1", "state": "AVAILABLE",
            "status": {"error_code": "OK", "error_message": ""}}]}


class _Handler(BaseHTTPRequestHandler):
    server_ref: InferenceServer = None
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _respond(self, status: int, payload: dict):
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _respond_sse(self, events) -> str:
        """Stream ``data: {json}`` events with chunked framing (we speak
        raw HTTP/1.1 here, so the chunk lengths are written by hand).
        Errors after the first byte can't change the status line — they
        become a terminal error event instead. Returns "ok", "error"
        (mid-stream server failure), or "cancelled" (client went away) —
        the caller's metrics need the real outcome, and client aborts
        must not inflate the server error rate."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(payload) -> None:
            # raw strings pass through unquoted (the OpenAI convention
            # terminates streams with the literal `data: [DONE]`)
            body = (payload if isinstance(payload, str)
                    else json.dumps(payload))
            data = f"data: {body}\n\n".encode()
            self.wfile.write(f"{len(data):x}\r\n".encode()
                             + data + b"\r\n")
            self.wfile.flush()

        outcome = "ok"
        try:
            for ev in events:
                chunk(ev)
        except (BrokenPipeError, ConnectionResetError):
            # a client hitting Stop is normal, not a server fault
            return "cancelled"
        except Exception as e:  # noqa: BLE001 — surface on the stream
            outcome = "error"
            logging.getLogger("kubedl_tpu.serving").exception(
                "stream failed")
            try:
                chunk({"error": f"{type(e).__name__}: {e}"})
            except OSError:
                return "error"
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except OSError:
            return "cancelled" if outcome == "ok" else outcome
        return outcome

    def do_GET(self):
        cfg = self.server_ref.config
        if self.path == "/healthz":
            self._respond(200, {"status": "ok"})
        elif self.path == "/metrics":
            from ..metrics.http import write_exposition
            self.server_ref.refresh_engine_metrics()
            write_exposition(self, self.server_ref.metrics)
        elif self.path == "/v1/models":
            self._respond(200, self.server_ref.openai_models())
        elif self.path == f"/v1/models/{cfg.model_name}":
            # TFServing-convention status (readiness probes) AND the
            # OpenAI retrieve shape in one payload — both client kinds
            # read only their own fields
            self._respond(200, {
                **self.server_ref.status(),
                "id": cfg.model_name, "object": "model",
                "created": self.server_ref._created,
                "owned_by": "kubedl-tpu"})
        else:
            self._respond(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv = self.server_ref
        cfg = srv.config
        is_prefix = self.path == f"/v1/models/{cfg.model_name}:registerPrefix"
        is_chat = self.path == "/v1/chat/completions"
        is_cmpl = self.path == "/v1/completions"
        is_embed = self.path == "/v1/embeddings"
        if self.path != f"/v1/models/{cfg.model_name}:predict" \
                and not (is_prefix or is_chat or is_cmpl or is_embed):
            self._respond(404, {"error": f"no route {self.path}"})
            return
        t0 = time.perf_counter()
        mode = ("prefix" if is_prefix else "chat" if is_chat
                else "completions" if is_cmpl
                else "embeddings" if is_embed else "predict")
        outcome = "ok"
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            if is_prefix:
                self._respond(200, srv.register_prefix(body))
            elif is_embed:
                self._respond(200, srv.openai_embeddings(body))
            elif is_chat or is_cmpl:
                if body.get("stream"):
                    outcome = self._respond_sse(
                        srv.openai_stream(body, chat=is_chat))
                else:
                    self._respond(200,
                                  srv.openai_completions(body,
                                                         chat=is_chat))
            elif body.get("stream"):
                mode = "stream"
                # validation happens before the first event, so a bad
                # request still gets a clean 400 status; mid-stream
                # failures are swallowed into a terminal error event, so
                # the returned outcome feeds the metrics
                outcome = self._respond_sse(srv.predict_stream(body))
            else:
                self._respond(200, srv.predict(body))
        except (ValueError, KeyError, TypeError) as e:
            srv._m_requests.inc(mode=mode, status="error")
            if is_chat or is_cmpl or is_embed:
                # the envelope OpenAI SDKs parse (error.message/.type)
                self._respond(400, {"error": {
                    "message": str(e), "type": "invalid_request_error",
                    "param": None, "code": None}})
            else:
                self._respond(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — a crashed predict must
            # surface as a JSON 500, not a dropped connection (ADVICE r1)
            srv._m_requests.inc(mode=mode, status="error")
            logging.getLogger("kubedl_tpu.serving").exception("predict failed")
            msg = f"{type(e).__name__}: {e}"
            self._respond(500, {"error": {
                "message": msg, "type": "server_error",
                "param": None, "code": None}}
                if (is_chat or is_cmpl or is_embed) else {"error": msg})
        else:
            srv._m_requests.inc(mode=mode, status=outcome)
            if outcome == "ok":
                srv._m_latency.observe(time.perf_counter() - t0, mode=mode)
