"""Speculative decoding: draft-model proposals verified by the target.

Decode is HBM-bandwidth-bound — one target forward per token streams all
weights for one token of progress. Speculative decoding (Leviathan et al.,
2023) has a small draft model propose ``k`` tokens autoregressively, then
the target verifies all of them in ONE chunk forward (weights streamed
once for up to ``k+1`` tokens of progress). Greedy acceptance makes the
output **provably identical** to the target's own greedy decoding — the
draft only changes speed, never content (pinned by test).

TPU-shaped details:

* verification is a single ``forward_step`` with a static chunk shape
  ``[1, k+1]`` (the last accepted token + the k drafts) and
  ``all_logits`` — one compile, reused every round;
* rejected draft positions need no cache surgery: rewinding is just
  moving the position pointer back, because stale cache slots beyond the
  pointer are causally masked until the next write lands on them (the
  same overwrite-before-attend argument the continuous-batching lanes
  rely on);
* both models keep ordinary donated caches; the draft can be an int8
  engine (``quantize="int8"``) for extra bandwidth headroom.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .batching import _bucket
# spec_accept / SpecStats moved to engine.py (the continuous-batching
# engine's per-lane speculative path shares them; importing from here
# would be circular) — re-exported for compatibility
from .engine import (GenerateConfig, SpecStats, filtered_probs,  # noqa: F401
                     hit_stop, maybe_quantize, resolve_family, spec_accept)


class SpeculativeServingAdapter:
    """Presents a SpeculativeEngine through the static-engine serving
    contract (``generate(prompts, max_new)``), so the HTTP predictor can
    serve the autoconfig's speculative candidates. Sequences decode one
    at a time (the engine is single-lane); logprobs are not available on
    the speculative path."""

    def __init__(self, engine: "SpeculativeEngine",
                 gen: Optional["GenerateConfig"] = None):
        self.engine = engine
        self.gen = gen
        #: lifetime acceptance accounting, surfaced via the predictor's
        #: /metrics (draft quality is THE speculative tuning signal)
        self.stats = SpecStats()

    @property
    def config(self):
        """The TARGET model's config — the serving contract the other
        engines expose; lets model-introspecting routes (embeddings)
        work unchanged on a speculative predictor."""
        return self.engine.tc

    @property
    def params(self):
        """The TARGET model's params (the draft only affects decode
        speed, never representations)."""
        return self.engine.tp

    def generate(self, prompts, max_new_tokens: int,
                 seed: int = 0, return_logprobs: bool = False):
        if return_logprobs:
            raise ValueError(
                "logprobs are not available on the speculative path")
        return [self.engine.generate(p, max_new_tokens, gen=self.gen,
                                     seed=seed + i, stats=self.stats)
                for i, p in enumerate(prompts)]

    def stop(self) -> None:
        pass  # nothing running in the background


class SpeculativeEngine:
    """Greedy speculative generation for one sequence at a time.

    ``target``/``draft`` are (config, params) pairs over the SAME
    vocabulary; ``k`` is the draft lookahead. Output is token-identical to
    plain greedy decoding with the target alone."""

    def __init__(self, target_config, target_params, draft_config,
                 draft_params, k: int = 4, max_len: int = 1024,
                 quantize_draft: Optional[str] = None):
        if target_config.vocab_size != draft_config.vocab_size:
            raise ValueError("target and draft must share a vocabulary")
        self.tc, self.tp = target_config, target_params
        self.dc = draft_config
        self.dp = maybe_quantize(draft_params, quantize_draft)
        self.k = k
        self.max_len = max_len
        self.tfam = resolve_family(target_config)
        self.dfam = resolve_family(draft_config)
        tc, dc, tfam, dfam = self.tc, self.dc, self.tfam, self.dfam

        def make_prefill(cfg, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _prefill(params, cache, tokens, plen):
                # tokens right-padded to a power-of-two bucket (no
                # per-length recompiles); last_pos reads the real last
                # token's logits and the pad writes are causally invisible
                # until overwritten
                valid = (jnp.arange(cache["k"].shape[2]) < plen)[None, :]
                logits, cache = fam.forward_step(cfg, params, tokens, cache,
                                                 jnp.int32(0), valid=valid,
                                                 last_pos=plen - 1)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache
            return _prefill

        def make_step(cfg, fam, all_logits=False):
            @partial(jax.jit, donate_argnums=(1,))
            def _step(params, cache, tokens, start):
                logits, cache = fam.forward_step(cfg, params, tokens, cache,
                                                 start, all_logits=all_logits)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache
            return _step

        self._t_prefill = make_prefill(tc, tfam)
        self._d_prefill = make_prefill(dc, dfam)
        # verify: chunk [1, k+1], logits for every position (greedy targets)
        self._t_verify = make_step(tc, tfam, all_logits=True)
        self._t_step = make_step(tc, tfam)
        self._d_step = make_step(dc, dfam)

        def make_prefill_logits(cfg, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _prefill(params, cache, tokens, plen):
                valid = (jnp.arange(cache["k"].shape[2]) < plen)[None, :]
                logits, cache = fam.forward_step(
                    cfg, params, tokens, cache, jnp.int32(0), valid=valid,
                    last_pos=plen - 1)
                return logits.astype(jnp.float32), cache
            return _prefill

        def make_step_logits(cfg, fam, all_logits=False):
            @partial(jax.jit, donate_argnums=(1,))
            def _step(params, cache, tokens, start):
                logits, cache = fam.forward_step(cfg, params, tokens,
                                                 cache, start,
                                                 all_logits=all_logits)
                return logits.astype(jnp.float32), cache
            return _step

        # sampled path (speculative SAMPLING): the accept rule needs the
        # raw distributions, not argmaxes — built eagerly but compiled
        # lazily by jit, so greedy-only deployments never pay for them
        self._t_prefill_logits = make_prefill_logits(tc, tfam)
        self._t_verify_logits = make_step_logits(tc, tfam,
                                                 all_logits=True)
        self._t_step_logits = make_step_logits(tc, tfam)
        self._d_step_logits = make_step_logits(dc, dfam)
        self._reset_caches()

    def _reset_caches(self) -> None:
        """(Re)allocate the engine-held caches. Called at init and after a
        failure mid-generate (an exception between a donating call and the
        reassignment can leave a consumed buffer behind)."""
        self._t_cache = self.tfam.init_cache(self.tc, 1, self.max_len)
        self._d_cache = self.dfam.init_cache(self.dc, 1, self.max_len)

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 stats: Optional[SpecStats] = None,
                 gen: Optional[GenerateConfig] = None,
                 seed: int = 0) -> list:
        """Continuation of ``prompt``. Greedy (``gen.temperature <= 0``,
        the default): token-identical to the target's own greedy decode,
        fewer target passes. Sampled (``temperature > 0``): speculative
        SAMPLING — the accept/resample rule (``spec_accept``) makes
        every emitted token's marginal distribution exactly the
        target's filtered distribution; ``seed`` pins the draw.

        ``gen`` carries eos_id/stop_sequences; the shared ``hit_stop``
        rule is applied to every emitted token (a verified chunk is
        truncated at the first stop), so outputs stay identical to the
        static/continuous engines' decode contract."""
        prompt = list(prompt) or [0]
        plen = len(prompt)
        if plen + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {plen} + new {max_new_tokens} exceeds "
                f"cache capacity {self.max_len}")
        try:
            if gen is not None and gen.temperature > 0.0:
                return self._generate_sampled(prompt, plen,
                                              max_new_tokens, stats, gen,
                                              np.random.default_rng(seed))
            return self._generate(prompt, plen, max_new_tokens, stats, gen)
        except BaseException:
            # ANY abort (including KeyboardInterrupt) between a donating
            # call and its reassignment can leave a consumed buffer on
            # self — restore invariants before propagating
            self._reset_caches()
            raise

    def _generate_sampled(self, prompt, plen, max_new_tokens, stats, gen,
                          rng):
        """Speculative sampling round loop — same cache/position
        bookkeeping as the greedy ``_generate`` (the verify chunk is
        written once, rejected slots stay causally invisible after the
        pointer rewind); only token selection differs: the draft SAMPLES
        its proposals, and ``spec_accept`` keeps/replaces them so the
        output distribution is exactly the target's."""
        k = self.k
        probs = partial(filtered_probs, temperature=gen.temperature,
                        top_k=gen.top_k, top_p=gen.top_p)

        win = max([1] + [len(s) for s in gen.stop_sequences])

        def stop_len(out, start):
            for i in range(start, len(out)):
                if hit_stop(out[max(0, i + 1 - win):i + 1], gen):
                    return i + 1
            return None

        t_cache, d_cache = self._t_cache, self._d_cache
        bucket = min(_bucket(plen), self.max_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = prompt
        toks = jnp.asarray(toks)
        t_logits, t_cache = self._t_prefill_logits(self.tp, t_cache, toks,
                                                   jnp.int32(plen))
        p0 = probs(np.asarray(t_logits)[0])
        y = int(rng.choice(len(p0), p=p0))
        _, d_cache = self._d_prefill(self.dp, d_cache, toks,
                                     jnp.int32(plen))
        out = [y]
        cut = stop_len(out, 0)
        if cut is not None:
            self._t_cache, self._d_cache = t_cache, d_cache
            return out[:min(cut, max_new_tokens)]
        pos = plen
        while (max_new_tokens - len(out) >= 2
               and pos + k + 1 < self.max_len):
            drafts, dprobs = [], []
            cur = y
            for i in range(k):
                d_logits, d_cache = self._d_step_logits(
                    self.dp, d_cache,
                    jnp.asarray([[cur]], jnp.int32), jnp.int32(pos + i))
                dp = probs(np.asarray(d_logits)[0])
                cur = int(rng.choice(len(dp), p=dp))
                drafts.append(cur)
                dprobs.append(dp)
            chunk = jnp.asarray([[y] + drafts], jnp.int32)
            t_logits, t_cache = self._t_verify_logits(
                self.tp, t_cache, chunk, jnp.int32(pos))
            tprobs = [probs(row) for row in np.asarray(t_logits)[0]]
            accepted, nxt = spec_accept(drafts, dprobs, tprobs, rng)
            if stats is not None:
                stats.proposed += k
                stats.accepted += accepted
            emitted = list(drafts[:accepted]) + [nxt]
            before = len(out)
            out.extend(emitted)
            cut = stop_len(out, before)
            if cut is not None:
                self._t_cache, self._d_cache = t_cache, d_cache
                return out[:min(cut, max_new_tokens)]
            if accepted == k:
                # the k-th draft joined the sequence but never entered
                # the draft cache (same backfill as the greedy loop)
                _, d_cache = self._d_step(
                    self.dp, d_cache,
                    jnp.asarray([[drafts[-1]]], jnp.int32),
                    jnp.int32(pos + k))
            pos += accepted + 1
            y = emitted[-1]
        while len(out) < max_new_tokens and pos + 1 < self.max_len:
            t_logits, t_cache = self._t_step_logits(
                self.tp, t_cache, jnp.asarray([[y]], jnp.int32),
                jnp.int32(pos))
            pt = probs(np.asarray(t_logits)[0])
            y = int(rng.choice(len(pt), p=pt))
            out.append(y)
            pos += 1
            cut = stop_len(out, len(out) - 1)
            if cut is not None:
                break
        self._t_cache, self._d_cache = t_cache, d_cache
        return out[:max_new_tokens]

    def _generate(self, prompt, plen, max_new_tokens, stats, gen=None):
        k = self.k

        # longest suffix hit_stop can match: eos (1) or any stop sequence
        win = 1 if gen is None else max(
            [1] + [len(s) for s in gen.stop_sequences])

        def stop_len(out, start):
            """Length to truncate ``out`` to if a stop lands in
            ``out[start:]`` (the suffix rule must see every token, not
            just the last of a verified chunk); None = no stop. Only the
            trailing ``win`` tokens per position are sliced, keeping the
            scan O(win) per token instead of O(len(out))."""
            if gen is None:
                return None
            for i in range(start, len(out)):
                if hit_stop(out[max(0, i + 1 - win):i + 1], gen):
                    return i + 1
            return None
        # engine-held caches, rewritten in place every call (stale slots
        # from a previous request are causally invisible: the fresh
        # prefill's masks start over at position 0)
        t_cache, d_cache = self._t_cache, self._d_cache

        bucket = min(_bucket(plen), self.max_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = prompt
        toks = jnp.asarray(toks)
        nxt, t_cache = self._t_prefill(self.tp, t_cache, toks,
                                       jnp.int32(plen))
        y = int(nxt[0])                              # first target token
        # draft prefills the same prompt; only its cache matters
        _, d_cache = self._d_prefill(self.dp, d_cache, toks, jnp.int32(plen))

        out = [y]
        cut = stop_len(out, 0)
        if cut is not None:
            self._t_cache, self._d_cache = t_cache, d_cache
            # min(): never emit past the budget — the static engine stops
            # at max_new_tokens without ever seeing a later stop token
            return out[:min(cut, max_new_tokens)]
        pos = plen            # tokens verified into both caches so far
        # a round only pays off when >= 2 tokens are still wanted (it
        # costs k draft steps + one verify); the single-token tail below
        # finishes the last one. NOTE: a round near the budget can still
        # propose more than remains — SpecStats counts those trimmed
        # proposals, so measure acceptance with max_new >> k
        while (max_new_tokens - len(out) >= 2
               and pos + k + 1 < self.max_len):
            # 1) draft proposes k tokens autoregressively from y
            drafts = []
            cur = y
            for i in range(k):
                nxt, d_cache = self._d_step(
                    self.dp, d_cache,
                    jnp.asarray([[cur]], jnp.int32), jnp.int32(pos + i))
                cur = int(nxt[0])
                drafts.append(cur)
            # 2) target verifies the whole chunk [y, d1..dk] at once:
            #    targets[i] is the greedy token for slot pos+i+1, each
            #    conditioned on the drafts before it
            chunk = jnp.asarray([[y] + drafts], jnp.int32)
            targets, t_cache = self._t_verify(self.tp, t_cache, chunk,
                                              jnp.int32(pos))
            targets = np.asarray(targets)[0]          # [k + 1]
            # 3) greedy acceptance: drafts[i] survives iff it equals the
            #    target's own choice; the first mismatch is replaced by
            #    the target token (always emitted — so a fully accepted
            #    round yields k + 1 tokens from one target pass)
            accepted = 0
            while accepted < k and drafts[accepted] == targets[accepted]:
                accepted += 1
            if stats is not None:
                stats.proposed += k
                stats.accepted += accepted
            emitted = list(drafts[:accepted]) + [int(targets[accepted])]
            before = len(out)
            out.extend(emitted)
            cut = stop_len(out, before)
            if cut is not None:
                # a stop landed inside the verified chunk: both caches
                # already hold the full chunk, but stale slots past any
                # future pos are causally invisible, so truncating the
                # host-side output is sufficient
                self._t_cache, self._d_cache = t_cache, d_cache
                # a full round can overshoot the budget by up to k+1;
                # a stop past max_new_tokens is one the static engine
                # never generates, so the budget wins
                return out[:min(cut, max_new_tokens)]
            if accepted == k:
                # fully accepted: d_k is now part of the sequence (slot
                # pos+k) but the draft cache never ingested it (it was
                # only ever an output) — backfill so future drafts aren't
                # conditioned on a stale slot
                _, d_cache = self._d_step(
                    self.dp, d_cache, jnp.asarray([[drafts[-1]]], jnp.int32),
                    jnp.int32(pos + k))
            # 4) rewind: both caches hold the verified chunk; stale slots
            #    past the new pos are causally invisible until overwritten
            pos += accepted + 1
            y = int(targets[accepted])
        # near cache capacity the k+1 verify chunk no longer fits: finish
        # the tail with plain single-token target decodes so the output
        # stays exactly the target's greedy decode (never shorter)
        while len(out) < max_new_tokens and pos + 1 < self.max_len:
            nxt, t_cache = self._t_step(
                self.tp, t_cache, jnp.asarray([[y]], jnp.int32),
                jnp.int32(pos))
            y = int(nxt[0])
            out.append(y)
            pos += 1
            cut = stop_len(out, len(out) - 1)
            if cut is not None:
                break
        self._t_cache, self._d_cache = t_cache, d_cache
        return out[:max_new_tokens]
