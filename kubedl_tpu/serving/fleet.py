"""Serving fleet: N continuous-batching replicas behind one front door.

One :class:`~kubedl_tpu.serving.batching.ContinuousBatchingEngine`
serves one model replica; a production fleet runs many and needs three
things in front of them (docs/serving_fleet.md):

* a **fleet** object owning replica lifecycle — add on scale-up, DRAIN
  on scale-down (new placements stop, in-flight streams and the
  replica's own queue finish; streams are never dropped), reap once
  idle;
* a **router** placing each request (``serving/router.py``:
  prefix-cache-aware placement with per-tenant fairness);
* an **autoscaler** closing the loop from measured signals
  (``controllers/servingfleet.py``: SLO burn-rate verdicts + the
  engines' free-block/queue-depth health gauges).

The fleet is engine-substrate-only: it never touches the control plane.
The operator exposes its status through the console
(``/api/v1/serving/fleet``) and its health through
:class:`~kubedl_tpu.metrics.registry.ServingFleetMetrics`, both gated on
``--enable-serving-fleet`` / the ``ServingFleet`` feature gate.
"""

from __future__ import annotations

from typing import Callable, Optional


class ServingReplica:
    """One engine + its fleet bookkeeping."""

    __slots__ = ("name", "engine", "draining", "policy_version",
                 "weight_swap")

    def __init__(self, name: str, engine, policy_version: int = 0):
        self.name = name
        self.engine = engine
        self.draining = False
        #: the ONE policy version this replica serves (docs/rl.md): a
        #: weight publish flips it atomically only after the new params
        #: are fully installed — a replica never advertises a version
        #: it cannot serve, and the router can pin placements to one
        self.policy_version = policy_version
        #: True while a publisher holds this replica mid-swap; guards
        #: :meth:`ServingFleet.cancel_drain` from un-draining a replica
        #: whose weights are torn (satellite: drain/publish composition)
        self.weight_swap = False

    def health(self) -> dict:
        h = self.engine.health()
        h["replica"] = self.name
        h["draining"] = self.draining
        h["policy_version"] = self.policy_version
        return h

    def idle(self) -> bool:
        """No queued work, no in-flight lane (safe to reap: ``stop()``
        on an idle engine cancels nothing)."""
        h = self.engine.health()
        return (h["queue_depth"] == 0 and h["active_lanes"] == 0
                and h["parked_lanes"] == 0)


class ServingFleet:
    """Replica lifecycle + health rollup.

    ``engine_factory(index)`` builds one engine per replica (closing
    over shared read-only params; each engine owns its cache/pool).
    Replica names are stable (``replica-<ordinal>``) and never reused —
    metric series and drain logs stay unambiguous across scale cycles.
    """

    def __init__(self, engine_factory: Callable[[int], object],
                 replicas: int = 1, metrics=None,
                 name_prefix: str = "replica"):
        self._factory = engine_factory
        self._prefix = name_prefix
        self._ordinal = 0
        self.metrics = metrics
        self.replicas: list[ServingReplica] = []
        #: drained replicas removed so far (names, in reap order)
        self.reaped: list[str] = []
        #: counters carried over from reaped replicas (their engines
        #: are gone; fleet-lifetime rollups must not lose them)
        self.reaped_handoffs = 0
        self.reaped_prefill_tokens = 0
        self.reaped_adapter_faults = 0
        for _ in range(max(int(replicas), 1)):
            self.add_replica()

    # -- lifecycle --------------------------------------------------------

    def add_replica(self) -> ServingReplica:
        name = f"{self._prefix}-{self._ordinal}"
        engine = self._factory(self._ordinal)
        self._ordinal += 1
        rep = ServingReplica(name, engine)
        self.replicas.append(rep)
        return rep

    def begin_drain(self, name: Optional[str] = None) \
            -> Optional[ServingReplica]:
        """Mark one replica draining (the youngest non-draining one by
        default): the router stops placing onto it, its own queue and
        lanes run to completion, and :meth:`reap` removes it once idle.
        Returns the replica, or None when nothing is drainable."""
        if name is not None:
            rep = next((r for r in self.replicas if r.name == name), None)
        else:
            rep = next((r for r in reversed(self.replicas)
                        if not r.draining), None)
        if rep is None or rep.draining:
            return None
        rep.draining = True
        return rep

    def cancel_drain(self) -> Optional[ServingReplica]:
        """Un-drain the youngest draining replica (pressure returned
        before its streams finished): its engine never stopped, so
        marking it active restores capacity instantly — strictly better
        than paying a fresh replica's spin-up while one is standing
        right there. A replica mid-weight-swap is SKIPPED: the
        publisher drained it to install new params, and handing it back
        to the router before the swap commits would serve a torn
        version (docs/rl.md "publish between drains"). Returns the
        replica, or None when nothing is (safely) un-drainable."""
        rep = next((r for r in reversed(self.replicas)
                    if r.draining and not r.weight_swap), None)
        if rep is None:
            return None
        rep.draining = False
        return rep

    def reap(self) -> list:
        """Remove every draining replica that has gone idle (its engine
        stopped — nothing in flight, so no stream is cancelled). A
        replica mid-weight-swap is exempt: drained-and-idle is exactly
        the publish window, and the publisher hands it back (or the
        autoscaler reaps it on a later pass if it stays draining).
        Returns the reaped names."""
        done = [r for r in self.replicas
                if r.draining and not r.weight_swap and r.idle()]
        for rep in done:
            rep.engine.stop()
            self.replicas.remove(rep)
            self.reaped.append(rep.name)
            self.reaped_handoffs += rep.engine.handoffs
            self.reaped_prefill_tokens += rep.engine.prefill_tokens_total
            faults = getattr(rep.engine, "adapter_status", None)
            faults = faults()["faults"] if faults is not None and \
                getattr(rep.engine, "multi_model", False) else None
            if faults:
                self.reaped_adapter_faults += sum(faults.values())
            if self.metrics is not None:
                # flush the final counter delta before the engine's
                # health vanishes from refresh()'s view
                self.metrics.note_reaped(rep.name, rep.engine.handoffs,
                                         adapter_faults=faults)
        return [r.name for r in done]

    # -- reads ------------------------------------------------------------

    def active(self) -> list:
        """Placement candidates: every non-draining replica."""
        return [r for r in self.replicas if not r.draining]

    @property
    def size(self) -> int:
        return len(self.replicas)

    def health(self) -> list:
        return [r.health() for r in self.replicas]

    def busy(self) -> bool:
        """Any replica holding queued or in-flight work."""
        return any(not r.idle() for r in self.replicas)

    def step(self) -> bool:
        """One inline scheduler tick on every replica (sim-clock
        drivers); True while any replica reports work left."""
        busy = False
        for rep in list(self.replicas):
            busy = rep.engine.step() or busy
        return busy

    def refresh_metrics(self) -> None:
        if self.metrics is not None:
            self.metrics.refresh(self)

    def status(self) -> dict:
        """The console's fleet snapshot (docs/serving_fleet.md)."""
        return {
            "replicas": self.size,
            "draining": sum(1 for r in self.replicas if r.draining),
            "reaped": list(self.reaped),
            "health": self.health(),
        }

    def stop(self) -> None:
        """Tear the whole fleet down (tests / process exit); in-flight
        requests are cancelled — scale-down paths use drain+reap."""
        for rep in self.replicas:
            rep.engine.stop()
        self.replicas = []
