"""Predictor container entrypoint: ``python -m kubedl_tpu.serving``.

The process the Inference controller's JAXServing predictors run
(``platform/serving.py`` points ``$KUBEDL_MODEL_PATH`` at the
ModelVersion artifacts and renders the Morphling-chosen config into
env). Honors the autoconfig contract end to end:

* ``KUBEDL_MODEL_PATH``   — ``models/io.py`` artifact directory
* ``KUBEDL_MODEL_NAME``   — REST route name (default: dir basename)
* ``KUBEDL_SERVING_LANES``    — continuous-batching lane count
* ``KUBEDL_SERVING_QUANTIZE`` — "int8", "int4", or ""
* ``KUBEDL_SERVING_SPEC_K``   — >0 enables speculative decoding with the
  draft model at ``KUBEDL_SERVING_DRAFT_PATH``; it rides the
  continuous-batching lanes (every lane drafts k tokens per round, one
  [lanes, k+1] target pass verifies them), so concurrent requests keep
  streaming/cancel/per-request sampling
* ``KUBEDL_SERVING_TP``       — >1: tensor-parallel serving over that
  many LOCAL chips (one host's mesh; params shard by their logical
  specs, the KV cache by kv-heads). Not combinable with QUANTIZE.
* ``KUBEDL_KV_MODE``          — KV layout: "paged" (default; block-pool
  cache, prefix block sharing, watermark preemption), "dense" (per-lane
  slab baseline), or "parity" (both + per-step assertions)
* ``KUBEDL_SERVING_KV_BLOCK`` / ``KUBEDL_SERVING_POOL_BLOCKS`` — paged
  pool geometry: tokens per block and usable block count (0 = engine
  defaults; the pool defaults to dense capacity, shrink it to
  overcommit lanes against real sequence lengths)
* ``KUBEDL_SERVING_PORT``     — default 8501
* ``KUBEDL_SERVING_WARMUP``   — default 1: compile prefill+decode with
  one tiny generation BEFORE the HTTP server binds (readiness then
  means "compiled and serving"); 0 skips
* ``KUBEDL_TOKENIZER``        — "byte", or a local directory of
  HuggingFace tokenizer assets (ship them with the ModelVersion):
  enables ``{"text": ...}`` instances, decoded ``"text"`` in
  predictions and stream events, and generation that stops at the
  tokenizer's EOS. Unset: tokenizer assets found INSIDE the model
  directory load automatically (``models.convert`` copies them there,
  so converted checkpoints serve text with zero extra config); "off"
  disables even that

SIGTERM (pod shutdown) stops the HTTP server, drains the engine, and
exits 0 so rolling predictor updates are graceful.

Offline batch inference (no HTTP): ``python -m kubedl_tpu.serving
--batch-input prompts.jsonl --batch-output out.jsonl`` reads rows
``{"prompt": "text" | [ids], "max_tokens"?: N}``, generates through the
same engine the server would use (lanes, quantization, tokenizer all
honored), writes ``{"prompt", "tokens", "text"?}`` rows in input order,
and exits — bulk generation runs as a plain JAXJob.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading


def build_engine(model_path: str, lanes: int, quantize: str, spec_k: int,
                 draft_path: str = "", max_len: int = 1024, tp: int = 1,
                 eos_id: int = -1, tokenizer_vocab: int = 0,
                 kv_block: int = 0, pool_blocks: int = 0):
    """The ONE env-to-engine mapping (also used by tests): returns a
    started engine honoring the autoconfig candidate. ``kv_block`` /
    ``pool_blocks`` (0 = engine defaults) size the paged KV pool; the
    layout itself is ``$KUBEDL_KV_MODE`` (paged by default)."""
    from ..models.io import load_model
    from .engine import GenerateConfig

    config, params = load_model(model_path)
    if eos_id >= config.vocab_size or tokenizer_vocab > config.vocab_size:
        # a mismatched tokenizer would encode ids past the embedding
        # table and serve garbage with a 200 — refuse at startup
        raise ValueError(
            f"tokenizer (vocab {tokenizer_vocab}, eos {eos_id}) does not "
            f"fit the model vocab ({config.vocab_size}) — wrong "
            "tokenizer for this model?")
    mesh = None
    if tp > 1:
        import jax

        from ..parallel.mesh import MeshConfig, build_mesh
        devices = jax.local_devices()
        if len(devices) < tp:
            raise ValueError(
                f"KUBEDL_SERVING_TP={tp} but only {len(devices)} local "
                "devices")
        mesh = build_mesh(MeshConfig(dp=1, fsdp=1, tp=tp), devices[:tp])
    from .batching import ContinuousBatchingEngine
    kv_kwargs = {}
    if kv_block:
        kv_kwargs["kv_block"] = kv_block
    if pool_blocks:
        kv_kwargs["pool_blocks"] = pool_blocks
    if spec_k > 0:
        if not draft_path:
            raise ValueError("KUBEDL_SERVING_SPEC_K > 0 needs "
                             "KUBEDL_SERVING_DRAFT_PATH")
        # speculative decoding rides the continuous-batching lanes:
        # every lane drafts spec_k tokens per round and ONE [lanes, k+1]
        # target pass verifies them all — concurrent requests keep their
        # streaming/cancel/per-request-sampling semantics. Composes with
        # KUBEDL_SERVING_TP (target AND draft shard over the local mesh).
        dcfg, dparams = load_model(draft_path)
        return ContinuousBatchingEngine(
            config, params, lanes=lanes, max_len=max_len,
            gen=GenerateConfig(max_len=max_len, eos_id=eos_id),
            quantize=quantize or None, draft_config=dcfg,
            draft_params=dparams, spec_k=spec_k, mesh=mesh,
            **kv_kwargs).start()
    return ContinuousBatchingEngine(
        config, params, lanes=lanes, max_len=max_len,
        gen=GenerateConfig(max_len=max_len, eos_id=eos_id),
        quantize=quantize or None, mesh=mesh, **kv_kwargs).start()


def run_batch(engine, tokenizer, in_path: str, out_path: str,
              default_max_tokens: int = 256) -> int:
    """Offline bulk generation: all rows ride the continuous-batching
    lanes concurrently; output preserves input order."""
    import json

    from ..tokenizer import encode_prompt
    log = logging.getLogger("kubedl_tpu.serving")
    rows = []
    with open(in_path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    if not rows:
        raise ValueError(f"no rows in {in_path}")
    prompts = []
    for i, row in enumerate(rows):
        p = row.get("prompt")
        if isinstance(p, str):
            if tokenizer is None:
                raise ValueError(
                    f"row {i}: text prompt needs a tokenizer "
                    "($KUBEDL_TOKENIZER or assets in the model dir)")
            prompts.append(encode_prompt(tokenizer, p))
        elif isinstance(p, list) and p:
            prompts.append([int(t) for t in p])
        else:
            raise ValueError(f"row {i}: prompt must be text or id list")
    caps = [int(r.get("max_tokens", default_max_tokens)) for r in rows]
    if hasattr(engine, "submit"):
        for p, cap in zip(prompts, caps):
            engine.validate(p, cap)
        outs = [r.result() for r in
                [engine.submit(p, cap) for p, cap in zip(prompts, caps)]]
    else:
        # speculative adapter: buffered generate, whole-set batches
        outs = engine.generate(prompts, max(caps))
        outs = [o[:cap] for o, cap in zip(outs, caps)]
    done = 0
    with open(out_path, "w") as f:
        for row, toks in zip(rows, outs):
            out = {"prompt": row["prompt"], "tokens": toks}
            if tokenizer is not None:
                out["text"] = tokenizer.decode(toks)
            f.write(json.dumps(out) + "\n")
            done += 1
            if done % 50 == 0:
                log.info("batch inference: %d/%d rows", done, len(rows))
    log.info("batch inference: wrote %d rows to %s", len(rows), out_path)
    return 0


def main(argv=None) -> int:
    import argparse

    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("kubedl_tpu.serving")
    ap = argparse.ArgumentParser(prog="python -m kubedl_tpu.serving")
    ap.add_argument("--batch-input", help="JSONL prompts for offline "
                    "batch inference (no HTTP server)")
    ap.add_argument("--batch-output", help="JSONL output path")
    args = ap.parse_args(argv)
    if bool(args.batch_input) != bool(args.batch_output):
        ap.error("--batch-input and --batch-output go together")
    model_path = os.environ.get("KUBEDL_MODEL_PATH", "")
    if not model_path:
        log.error("KUBEDL_MODEL_PATH is required")
        return 2
    lanes = int(os.environ.get("KUBEDL_SERVING_LANES", "4") or 4)
    quantize = os.environ.get("KUBEDL_SERVING_QUANTIZE", "")
    spec_k = int(os.environ.get("KUBEDL_SERVING_SPEC_K", "0") or 0)
    draft = os.environ.get("KUBEDL_SERVING_DRAFT_PATH", "")
    max_len = int(os.environ.get("KUBEDL_SERVING_MAX_LEN", "1024") or 1024)
    tp = int(os.environ.get("KUBEDL_SERVING_TP", "1") or 1)
    kv_block = int(os.environ.get("KUBEDL_SERVING_KV_BLOCK", "0") or 0)
    pool_blocks = int(os.environ.get("KUBEDL_SERVING_POOL_BLOCKS", "0")
                      or 0)
    from ..tokenizer import has_tokenizer_assets, load_tokenizer
    tok_spec = os.environ.get("KUBEDL_TOKENIZER", "")
    if not tok_spec and has_tokenizer_assets(model_path):
        # self-contained artifact: models.convert ships the checkpoint's
        # tokenizer alongside the weights
        tok_spec = model_path
    tokenizer = None if tok_spec == "off" else load_tokenizer(tok_spec)

    engine = build_engine(model_path, lanes, quantize, spec_k, draft,
                          max_len, tp=tp,
                          eos_id=(tokenizer.eos_id if tokenizer is not None
                                  else -1),
                          tokenizer_vocab=(tokenizer.vocab_size
                                           if tokenizer is not None else 0),
                          kv_block=kv_block, pool_blocks=pool_blocks)
    if args.batch_input:
        try:
            return run_batch(engine, tokenizer, args.batch_input,
                             args.batch_output,
                             default_max_tokens=int(os.environ.get(
                                 "KUBEDL_SERVING_MAX_NEW", "256") or 256))
        finally:
            engine.stop()
    if os.environ.get("KUBEDL_SERVING_WARMUP", "1") == "1":
        # pay the prefill+decode compiles BEFORE the HTTP server binds:
        # the readiness probe then means "compiled and serving", and the
        # first real request gets real-traffic latency
        import time as _time
        t0 = _time.perf_counter()
        if hasattr(engine, "submit"):
            engine.submit([1], 2).result(timeout=600)
        else:
            engine.generate([[1]], 2)
        log.info("warmup compile done in %.1fs", _time.perf_counter() - t0)
    from .server import InferenceServer, ServerConfig
    server = InferenceServer(engine, ServerConfig(
        # `or`, not a get() default: the controller injects the var even
        # when the ModelVersion has no modelName (empty string)
        model_name=(os.environ.get("KUBEDL_MODEL_NAME")
                    or os.path.basename(model_path.rstrip("/"))
                    or "model"),
        port=int(os.environ.get("KUBEDL_SERVING_PORT", "8501") or 8501),
        tokenizer=tokenizer,
    )).start()
    # log the RESOLVED tokenizer spec: auto-detected in-model assets make
    # the raw env var read 'off' while text serving is on (ADVICE r4)
    log.info("serving %s on %s (lanes=%d quantize=%s tokenizer=%s)",
             model_path, server.url, lanes, quantize or "off",
             tok_spec if tokenizer is not None else "off")

    done = threading.Event()

    def shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    done.wait()
    log.info("draining")
    server.stop()
    engine.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
