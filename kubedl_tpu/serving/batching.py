"""Continuous batching: slot-scheduled decoding over a shared KV cache.

The static engine (``engine.InferenceEngine``) decodes one left-padded
batch in lockstep: every request waits for the whole batch to finish.
This engine keeps a fixed set of ``lanes`` (batch rows of one shared
cache) and schedules requests onto free lanes as they open — the
vLLM-style recipe, shaped for TPU:

* ONE jitted decode step for all lanes per tick, with **per-row
  positions** (``llama.attention_step``'s vector ``start_pos``): no
  re-padding, no recompilation as requests of different lengths come and
  go;
* prefill writes a single lane of the shared cache in place
  (``dynamic_update_slice`` on the lane axis) with prompts right-padded
  into power-of-two buckets — a handful of compiled shapes total;
* dead lanes keep decoding garbage (uniform SPMD — masking happens in the
  scheduler, not the compiled step), and their cache writes land on slots
  that are overwritten before ever becoming attendable;
* scheduling (arrivals, eos, lane reuse) is host-side Python between
  ticks, exactly where dynamic control flow belongs on TPU.

The reference operator serves models via fixed Deployments
(``controllers/serving``); request-level scheduling like this has no
reference analog — TPU-native capability beyond parity.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .engine import GenerateConfig


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


@dataclass
class _Lane:
    request: int = -1          # index into the submit order; -1 = free
    pos: int = 0               # next write position (== tokens so far)
    remaining: int = 0
    done_reason: str = ""


class ContinuousBatchingEngine:
    """Slot-scheduled generation over one shared cache.

    ``run(requests)`` takes ``[(prompt_tokens, max_new_tokens), ...]`` in
    arrival order and returns one generated-id list per request; requests
    are admitted to lanes as earlier ones finish, so a short request never
    waits on a long co-batched one."""

    def __init__(self, config: llama.LlamaConfig, params: dict,
                 lanes: int = 4, max_len: int = 1024,
                 gen: Optional[GenerateConfig] = None,
                 quantize: Optional[str] = None):
        from .engine import maybe_quantize, resolve_family, sample_logits
        self.config = config
        self.family = family = resolve_family(config)
        self.params = maybe_quantize(params, quantize)
        self.lanes = lanes
        self.max_len = max_len
        self.gen = gen or GenerateConfig(max_len=max_len)
        cfg = config

        @partial(jax.jit, donate_argnums=(1,))
        def _decode(params, cache, tokens, positions):
            # tokens [lanes, 1], positions [lanes] — per-row cache writes
            return family.forward_step(cfg, params, tokens, cache,
                                       positions)

        @partial(jax.jit, donate_argnums=(1,))
        def _prefill(params, cache, tokens, lane, plen):
            # tokens [1, bucket] right-padded; lane and plen are TRACED so
            # only the bucket size (a handful of power-of-two shapes)
            # triggers a compile. Returns the real last token's logits.
            # valid marks the real prompt region: attention never sees the
            # right-pad anyway (causal + overwrite-before-attend), but MoE
            # ROUTING must not let pad tokens consume expert capacity.
            row = {k: jax.lax.dynamic_slice_in_dim(v, lane, 1, axis=1)
                   for k, v in cache.items()}
            valid = (jnp.arange(row["k"].shape[2]) < plen)[None, :]
            logits, row = family.forward_step(cfg, params, tokens, row,
                                              jnp.int32(0), valid=valid,
                                              all_logits=True)
            last = jax.lax.dynamic_slice_in_dim(logits, plen - 1, 1,
                                                axis=1)[:, 0]
            cache = {k: jax.lax.dynamic_update_slice_in_dim(
                cache[k], row[k], lane, axis=1) for k in cache}
            return last, cache

        self._decode = _decode
        self._prefill = _prefill
        self._sample = sample_logits

    # -- scheduler --------------------------------------------------------

    def run(self, requests: Sequence[tuple], seed: int = 0) -> list:
        """requests: [(prompt_token_list, max_new_tokens), ...] in arrival
        order. Returns one generated-id list per request."""
        gen = self.gen
        cache = self.family.init_cache(self.config, self.lanes, self.max_len)
        lanes = [_Lane() for _ in range(self.lanes)]
        out: list[list[int]] = [[] for _ in requests]
        queue = list(range(len(requests)))
        key = jax.random.PRNGKey(seed)
        # host mirrors of the device-side decode inputs
        cur = np.zeros((self.lanes, 1), np.int32)
        pos = np.zeros((self.lanes,), np.int32)

        def admit(lane_idx: int, cache):
            req = queue.pop(0)
            prompt, max_new = requests[req]
            if max_new <= 0:
                return cache       # nothing requested: empty output
            prompt = list(prompt) or [0]
            plen = len(prompt)
            if plen + max_new > self.max_len:
                raise ValueError(
                    f"request {req}: prompt {plen} + new {max_new} exceeds "
                    f"cache capacity {self.max_len}")
            bucket = min(_bucket(plen), self.max_len)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :plen] = prompt
            logits, cache = self._prefill(self.params, cache,
                                          jnp.asarray(toks),
                                          jnp.int32(lane_idx),
                                          jnp.int32(plen))
            nonlocal key
            key, sub = jax.random.split(key)
            first = int(self._sample(logits, sub, gen.temperature,
                                     gen.top_k)[0])
            out[req].append(first)
            lane = lanes[lane_idx]
            lane.request, lane.pos = req, plen
            lane.remaining = max_new - 1
            cur[lane_idx, 0] = first
            pos[lane_idx] = plen
            if (lane.remaining <= 0
                    or (gen.eos_id >= 0 and first == gen.eos_id)):
                lane.request = -1      # finished in prefill
            return cache

        while queue or any(l.request >= 0 for l in lanes):
            # fill free lanes from the arrival queue
            for i, lane in enumerate(lanes):
                while queue and lane.request < 0:
                    cache = admit(i, cache)
                    lane = lanes[i]
                if not queue:
                    break
            if not any(l.request >= 0 for l in lanes):
                continue
            # one decode tick for every lane (dead lanes compute garbage)
            logits, cache = self._decode(self.params, cache,
                                         jnp.asarray(cur), jnp.asarray(pos))
            key, sub = jax.random.split(key)
            nxt = np.asarray(self._sample(logits, sub, gen.temperature,
                                          gen.top_k))
            for i, lane in enumerate(lanes):
                if lane.request < 0:
                    continue
                tok = int(nxt[i])
                out[lane.request].append(tok)
                lane.pos += 1
                lane.remaining -= 1
                cur[i, 0] = tok
                pos[i] = lane.pos
                if (lane.remaining <= 0
                        or (gen.eos_id >= 0 and tok == gen.eos_id)
                        or lane.pos + 1 >= self.max_len):
                    lane.request = -1   # lane freed for the next arrival
        return out
