"""Continuous batching: slot-scheduled decoding over a shared KV cache.

The static engine (``engine.InferenceEngine``) decodes one left-padded
batch in lockstep: every request waits for the whole batch to finish.
This engine keeps a fixed set of ``lanes`` (batch rows of one shared
cache) and schedules requests onto free lanes as they open — the
vLLM-style recipe, shaped for TPU:

* ONE jitted decode step for all lanes per tick, with **per-row
  positions** (``llama.attention_step``'s vector ``start_pos``): no
  re-padding, no recompilation as requests of different lengths come and
  go;
* prefill writes a single lane of the shared cache in place
  (``dynamic_update_slice`` on the lane axis) with prompts right-padded
  into power-of-two buckets — a handful of compiled shapes total;
* dead lanes keep decoding garbage (uniform SPMD — masking happens in the
  scheduler, not the compiled step), and their cache writes land on slots
  that are overwritten before ever becoming attendable;
* scheduling (arrivals, eos, lane reuse) is host-side Python between
  ticks, exactly where dynamic control flow belongs on TPU.

The reference operator serves models via fixed Deployments
(``controllers/serving``); request-level scheduling like this has no
reference analog — TPU-native capability beyond parity.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from .engine import (GenerateConfig, hit_stop, sample_logits_many,
                     token_logprobs)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (max(n, 1).bit_length() - 1)


@dataclass
class Request:
    """One in-flight generation; ``done`` fires when ``tokens`` is final
    (or the engine stopped — then ``cancelled`` is set). With
    ``want_logprobs`` each generated token's full-softmax log p lands
    in ``logprobs``.

    Tokens are appended by the scheduler thread as they decode;
    :meth:`stream` consumes them incrementally (the serving layer's SSE
    path rides this), :meth:`result` waits for the final list."""
    prompt: list
    max_new: int
    tokens: list = field(default_factory=list)
    logprobs: list = field(default_factory=list)
    want_logprobs: bool = False
    #: per-request sampling overrides; None = the engine's GenerateConfig
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    cancelled: bool = False
    #: client-requested stop (set via :meth:`cancel`): the scheduler
    #: frees the lane at its next tick; tokens decoded so far remain
    cancel_requested: bool = False
    _cond: threading.Condition = field(default_factory=threading.Condition)

    def cancel(self) -> None:
        """Stop generating for this request (client went away / got what
        it needed). Unlike engine shutdown, ``result()`` still returns
        the tokens decoded so far."""
        self.cancel_requested = True

    def result(self, timeout: Optional[float] = None) -> list:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.cancelled:
            raise RuntimeError("generation cancelled: engine stopped")
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Yield ``(token_id, logprob_or_None)`` as the scheduler emits
        them; returns when generation finishes. ``timeout`` bounds the
        wait for EACH next token (a stalled engine surfaces as
        TimeoutError instead of a silent hang)."""
        sent = 0
        while True:
            with self._cond:
                while len(self.tokens) <= sent and not self.done.is_set():
                    if not self._cond.wait(timeout):
                        raise TimeoutError(
                            "no token within the streaming timeout")
                # snapshot UNDER the lock: _push appends token+logprob as
                # one critical section, so pairs read here are aligned (a
                # lock-free read could see the token before its logprob)
                fresh = []
                while sent < len(self.tokens):
                    lp = (self.logprobs[sent] if self.want_logprobs
                          and sent < len(self.logprobs) else None)
                    fresh.append((self.tokens[sent], lp))
                    sent += 1
                finished = self.done.is_set() and sent >= len(self.tokens)
                cancelled = self.cancelled
            yield from fresh
            if finished:
                if cancelled:
                    raise RuntimeError(
                        "generation cancelled: engine stopped")
                return

    # -- scheduler-side helpers (single writer: the scheduler thread) ----

    def _push(self, tok: int, lp: Optional[float]) -> None:
        with self._cond:
            self.tokens.append(tok)
            if lp is not None:
                self.logprobs.append(lp)
            self._cond.notify_all()

    def _finish(self, cancelled: bool = False) -> None:
        with self._cond:
            self.cancelled = self.cancelled or cancelled
            self.done.set()
            self._cond.notify_all()


@dataclass
class _Lane:
    request: Optional[Request] = None    # None = free
    pos: int = 0               # next write position (== tokens so far)
    remaining: int = 0

    def reset(self) -> None:
        self.request = None
        self.pos = 0
        self.remaining = 0


class ContinuousBatchingEngine:
    """Slot-scheduled generation over one shared cache.

    ``run(requests)`` takes ``[(prompt_tokens, max_new_tokens), ...]`` in
    arrival order and returns one generated-id list per request; requests
    are admitted to lanes as earlier ones finish, so a short request never
    waits on a long co-batched one."""

    def __init__(self, config: llama.LlamaConfig, params: dict,
                 lanes: int = 4, max_len: int = 1024,
                 gen: Optional[GenerateConfig] = None,
                 quantize: Optional[str] = None, seed: int = 0,
                 mesh=None, draft_config=None, draft_params=None,
                 spec_k: int = 0, quantize_draft: Optional[str] = None):
        from .engine import (SpecStats, init_mesh_serving, resolve_family,
                             sample_logits)
        self.config = config
        self.family = family = resolve_family(config)
        self.lanes = lanes
        self.max_len = max_len
        self.gen = gen or GenerateConfig(max_len=max_len)
        self.mesh = mesh
        # tensor-parallel serving over a local mesh (one host's chips):
        # params by logical specs, cache by kv-heads; the jitted steps
        # are unchanged — GSPMD inserts the collectives.
        self.params, self._place_cache = init_mesh_serving(
            config, params, quantize, mesh)
        cfg = config

        # -- speculative decoding per lane (draft model proposes spec_k
        # tokens for EVERY lane, the target verifies all lanes' chunks in
        # one [lanes, k+1] pass) — concurrent speculative serving
        self.spec_k = int(spec_k) if draft_params is not None else 0
        if self.spec_k:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    "target and draft must share a vocabulary")
            self.dcfg = draft_config
            self.dfam = resolve_family(draft_config)
            # the draft rides the same mesh as the target (its params by
            # ITS logical specs, its cache by ITS kv-heads) — spec lanes
            # compose with tensor-parallel serving; draft quantization
            # only without a mesh (same rule as the target)
            self.dparams, self._place_d_cache = init_mesh_serving(
                draft_config, draft_params, quantize_draft, mesh)
            #: aggregate + per-lane acceptance accounting (/metrics)
            self.stats = SpecStats()
            self.lane_stats = [SpecStats() for _ in range(lanes)]

        def make_decode(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _decode(params, cache, tokens, positions):
                # tokens [lanes, 1], positions [lanes] — per-row writes
                return fam.forward_step(cfg_, params, tokens, cache,
                                        positions)
            return _decode

        def make_prefill(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _prefill(params, cache, tokens, lane, start, n_real):
                # tokens [1, bucket] right-padded; lane/start/n_real are
                # TRACED so only the bucket size (a handful of
                # power-of-two shapes) triggers a compile. The chunk
                # lands at ``start`` (0 for a plain prefill; the prefix
                # length when a cached prefix was loaded first). Returns
                # the real last token's logits (last_pos gathers it
                # pre-LM-head: one vocab projection, not bucket of
                # them). valid marks the live cache region: attention
                # never sees the right-pad anyway (causal +
                # overwrite-before-attend), but MoE ROUTING must not let
                # pad tokens consume expert capacity.
                row = {k: jax.lax.dynamic_slice_in_dim(v, lane, 1, axis=1)
                       for k, v in cache.items()}
                valid = (jnp.arange(row["k"].shape[2])
                         < start + n_real)[None, :]
                last, row = fam.forward_step(cfg_, params, tokens, row,
                                             start, valid=valid,
                                             last_pos=n_real - 1)
                cache = {k: jax.lax.dynamic_update_slice_in_dim(
                    cache[k], row[k], lane, axis=1) for k in cache}
                return last, cache
            return _prefill

        _decode = make_decode(cfg, family)
        _prefill = make_prefill(cfg, family)

        @partial(jax.jit, donate_argnums=(1,))
        def _spec_verify(params, cache, tokens, positions):
            # tokens [lanes, k+1] at per-row positions: ONE target pass
            # verifies every lane's draft chunk (all-position logits)
            return family.forward_step(cfg, params, tokens, cache,
                                       positions, all_logits=True)

        @partial(jax.jit)
        def _fill_prefix(params, tokens, plen):
            # build a shared-prefix KV block on a scratch single-lane
            # cache sized to the bucket (stored bucket-padded; garbage
            # beyond plen is causally invisible once loaded into a lane)
            scratch = family.init_cache(cfg, 1, tokens.shape[1])
            valid = (jnp.arange(tokens.shape[1]) < plen)[None, :]
            _, scratch = family.forward_step(cfg, params, tokens, scratch,
                                             jnp.int32(0), valid=valid,
                                             last_pos=plen - 1)
            return scratch

        @partial(jax.jit, donate_argnums=(0,))
        def _load_prefix(cache, stored, lane):
            # copy a stored prefix KV block into one lane's cache rows
            def put(c, s):
                return jax.lax.dynamic_update_slice(
                    c, s.astype(c.dtype),
                    (0, lane) + (0,) * (c.ndim - 2))
            return {k: put(cache[k], stored[k]) for k in cache}

        self._decode = _decode
        self._prefill = _prefill
        self._fill_prefix = _fill_prefix
        self._load_prefix = _load_prefix
        self._prefixes: list = []   # (tokens tuple, stored kv, plen)
        self._sample = sample_logits
        if self.spec_k:
            self._d_decode = make_decode(self.dcfg, self.dfam)
            self._d_prefill = make_prefill(self.dcfg, self.dfam)
            self._spec_verify = _spec_verify
            self._d_cache = self._place_d_cache(
                self.dfam.init_cache(self.dcfg, lanes, max_len))
            #: per-request host rng for the sampled accept rule,
            #: allocated at admission (seed + admission ordinal)
            self._spec_admitted = 0

        # live scheduler state: one shared cache + lane bookkeeping; the
        # host mirrors (cur/pos) feed the per-tick decode call
        self._cache = self._place_cache(
            family.init_cache(config, lanes, max_len))
        self._lane_state = [_Lane() for _ in range(lanes)]
        self._cur = np.zeros((lanes, 1), np.int32)
        self._pos = np.zeros((lanes,), np.int32)
        self._seed = seed
        self._key = jax.random.PRNGKey(seed)
        self._queue: deque[Request] = deque()
        self._cv = threading.Condition()
        #: serializes the whole scheduler step (donated cache + lane
        #: bookkeeping are shared mutable state): inline run() callers and
        #: the background loop can never tick concurrently
        self._sched_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- public API -------------------------------------------------------

    def register_prefix(self, tokens: Sequence[int],
                        max_prefixes: Optional[int] = None) -> None:
        """Prefill a shared prompt prefix ONCE and stash its KV block;
        later requests whose prompts start with it load the block into
        their lane and prefill only the suffix — the standard
        system-prompt optimization. Greedy outputs are unchanged (the
        loaded KV is exactly what the full prefill would have written)."""
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prefix")
        plen = len(tokens)
        if plen >= self.max_len:
            raise ValueError(
                f"prefix {plen} exceeds cache capacity {self.max_len}")
        key = tuple(tokens)
        if max_prefixes is not None and \
                not any(p[0] == key for p in self._prefixes) and \
                len(self._prefixes) >= max_prefixes:
            # optimistic pre-check: a rejected registration must not
            # first burn a full device prefill (the authoritative check
            # below runs under the lock)
            raise ValueError(
                f"prefix limit {max_prefixes} reached "
                "(each prefix pins a KV block in HBM)")
        bucket = min(_bucket(plen), self.max_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = tokens
        stored = self._fill_prefix(self.params, jnp.asarray(toks),
                                   jnp.int32(plen))
        with self._sched_lock:
            # dedup (re-registering replaces) + longest-first ordering so
            # the best match wins during admission; swap in a NEW list so
            # concurrent _match_prefix iterations never see a mid-sort view
            entries = [p for p in self._prefixes if p[0] != key]
            # cap enforced HERE, under the lock: a server-side
            # check-then-call would race concurrent registrations past
            # the limit, and an idempotent re-register (key already
            # stored) must never be rejected — it pins no new HBM
            if max_prefixes is not None and len(entries) >= max_prefixes:
                raise ValueError(
                    f"prefix limit {max_prefixes} reached "
                    "(each prefix pins a KV block in HBM)")
            entries.append((key, stored, plen))
            entries.sort(key=lambda p: -p[2])
            self._prefixes = entries

    @property
    def prefix_count(self) -> int:
        return len(self._prefixes)

    def clear_prefixes(self) -> None:
        """Drop every stored prefix KV block (frees device memory)."""
        with self._sched_lock:
            self._prefixes = []

    def _match_prefix(self, prompt: list):
        for toks, stored, plen in self._prefixes:
            if len(prompt) >= plen and tuple(prompt[:plen]) == toks:
                # keep at least one suffix token so the prefill has a
                # position to read logits from (re-running the prefix's
                # last token overwrites its own slot with identical KV)
                return stored, min(plen, len(prompt) - 1)
        return None, 0

    def validate(self, prompt: Sequence[int], max_new: int) -> None:
        """Raise ValueError if the request can never fit the cache —
        callers batching several submits should validate ALL of them
        first so a bad late request doesn't strand earlier ones."""
        plen = max(len(prompt), 1)
        if plen + max_new > self.max_len:
            raise ValueError(
                f"prompt {plen} + new {max_new} exceeds cache capacity "
                f"{self.max_len}")

    def submit(self, prompt: Sequence[int], max_new: int,
               logprobs: bool = False, temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> Request:
        """Enqueue one generation; returns a Request whose ``result()``
        blocks until finished. Thread-safe. ``temperature``/``top_k``/
        ``top_p`` override the engine's GenerateConfig for THIS request
        only (each lane samples with its own request's params)."""
        self.validate(prompt, max_new)
        sampling = self.validate_sampling(temperature=temperature,
                                          top_k=top_k, top_p=top_p)
        req = Request(prompt=list(prompt), max_new=max_new,
                      want_logprobs=logprobs, **sampling)
        if max_new <= 0:
            req._finish()          # nothing requested: empty output
            return req
        with self._cv:
            if self._stopped:
                raise RuntimeError("engine stopped")
            self._queue.append(req)
            self._cv.notify()
        return req

    def validate_sampling(self, temperature=None, top_k=None,
                          top_p=None) -> dict:
        """Bounds-check per-request sampling overrides in the CALLER's
        thread (a bad value must 400 one request, never reach the
        scheduler loop, where a raise stops the engine and cancels every
        lane). Returns the normalized dict; the server pre-validates
        every instance of a batch with this before submitting any."""
        if temperature is not None:
            temperature = float(temperature)
            if not (0.0 <= temperature < 1e4):
                raise ValueError(f"temperature out of range: {temperature}")
        if top_k is not None:
            top_k = int(top_k)
            if not (0 <= top_k <= self.config.vocab_size):
                raise ValueError(
                    f"top_k out of range [0, {self.config.vocab_size}]: "
                    f"{top_k}")
        if top_p is not None:
            top_p = float(top_p)
            if not (0.0 < top_p <= 1.0):
                raise ValueError(f"top_p out of range (0, 1]: {top_p}")
        return {"temperature": temperature, "top_k": top_k, "top_p": top_p}

    def run(self, requests: Sequence[tuple], seed: Optional[int] = None) -> list:
        """requests: [(prompt_token_list, max_new_tokens), ...] in arrival
        order. Returns one generated-id list per request. Inline when no
        background loop is running; otherwise defers to it."""
        # validate everything up front: a bad late request must not strand
        # earlier ones in the queue
        for prompt, max_new in requests:
            self.validate(prompt, max_new)
        if seed is not None:
            if self._thread is not None:
                raise ValueError(
                    "cannot reseed a running engine (other clients share "
                    "the sampling stream)")
            with self._sched_lock:
                self._key = jax.random.PRNGKey(seed)
                if self.spec_k:
                    # the speculative accept rule draws from per-request
                    # host rngs (seed + admission ordinal): rebase both
                    # or a reseeded sampled run would not reproduce
                    self._seed = seed
                    self._spec_admitted = 0
        reqs = [self.submit(p, n) for p, n in requests]
        if self._thread is None:
            with self._sched_lock:
                try:
                    while self._step_once():
                        pass
                except BaseException:
                    # _prefill/_decode donate self._cache: an abort
                    # mid-step leaves a consumed buffer behind, and the
                    # next inline call would hit a confusing
                    # donated-buffer error. Restore invariants (mirrors
                    # SpeculativeEngine's reset-on-failure) and cancel
                    # in-flight requests so waiters unblock.
                    self._recover_locked()
                    raise
        return [r.result() for r in reqs]

    def _recover_locked(self) -> None:
        """Reinitialize the donated cache + lane state after a failed
        inline step. Caller holds ``_sched_lock`` (``_cancel_all`` cannot
        be used here: it takes the non-reentrant lock itself)."""
        # queue snapshot must hold _cv: submit() appends under _cv only,
        # so clearing under _sched_lock alone could silently drop (and
        # forever block) a concurrently submitted request
        with self._cv:
            abandoned = list(self._queue)
            self._queue.clear()
        for lane in self._lane_state:
            if lane.request is not None:
                abandoned.append(lane.request)
            lane.reset()
        for req in abandoned:
            req._finish(cancelled=True)
        self._cache = self._place_cache(
            self.family.init_cache(self.config, self.lanes, self.max_len))
        if self.spec_k:
            # the draft cache is donated into _d_decode/_d_prefill too
            self._d_cache = self._place_d_cache(
                self.dfam.init_cache(self.dcfg, self.lanes,
                                     self.max_len))
        self._cur = np.zeros((self.lanes, 1), np.int32)
        self._pos = np.zeros((self.lanes,), np.int32)

    def start(self) -> "ContinuousBatchingEngine":
        """Run the scheduler on a background thread (HTTP serving mode)."""
        def loop():
            import logging
            while True:
                with self._cv:
                    while (not self._stopped and not self._queue
                           and not self._active()):
                        self._cv.wait()
                    if self._stopped:
                        return
                try:
                    with self._sched_lock:
                        self._step_once()
                except Exception:  # noqa: BLE001 — a dead loop must not
                    # strand waiters: fail every request and stop accepting
                    logging.getLogger("kubedl_tpu.serving").exception(
                        "batching scheduler failed; cancelling requests")
                    with self._cv:
                        self._stopped = True
                    self._cancel_all()
                    return

        self._thread = threading.Thread(target=loop, name="kubedl-batching",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the background loop; queued and in-flight requests are
        cancelled (their waiters unblock with a RuntimeError)."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._cancel_all()

    def _cancel_all(self) -> None:
        with self._sched_lock:
            abandoned = list(self._queue)
            self._queue.clear()
            for lane in self._lane_state:
                if lane.request is not None:
                    abandoned.append(lane.request)
                    lane.request = None
            for req in abandoned:
                req._finish(cancelled=True)

    # -- scheduler --------------------------------------------------------

    def _active(self) -> bool:
        return any(l.request is not None for l in self._lane_state)

    def _lane_sampling(self, req: Request):
        """(temperature, top_k, top_p) for a request — per-request
        overrides over the engine GenerateConfig."""
        gen = self.gen
        t = gen.temperature if req.temperature is None else req.temperature
        k_ = gen.top_k if req.top_k is None else req.top_k
        p_ = gen.top_p if req.top_p is None else req.top_p
        return t, k_, p_

    def _spec_round_k(self) -> int:
        """Draft lookahead this round: spec_k clamped so every ACTIVE
        lane's [k+1] verify chunk (and the draft backfill at pos+k) stays
        inside the cache. The chunk shape is compiled per k, so at most
        spec_k shapes exist."""
        space = min(self.max_len - 1 - l.pos
                    for l in self._lane_state if l.request is not None)
        return min(self.spec_k, space)

    def _spec_round(self, k: int) -> None:
        """One speculative round for EVERY lane: k draft proposals each
        (k batched [lanes, 1] draft steps), one [lanes, k+1] target
        verify, per-lane acceptance — greedy prefix-match for greedy
        lanes (output token-identical to the non-speculative engine),
        the ``spec_accept`` distribution rule for sampled lanes (each
        emitted token's marginal distribution is exactly the target's).
        Cache bookkeeping per lane is pointer math: rejected slots stay
        causally invisible until overwritten (the single-sequence
        engine's rewind argument, per row)."""
        from .engine import filtered_probs, spec_accept
        gen = self.gen
        lanes_n = self.lanes
        active = np.asarray([l.request is not None
                             for l in self._lane_state])
        # dead lanes still compute (uniform SPMD) but their writes must
        # stay in range: park them at position 0 — those slots are fully
        # rewritten by the next admission's bucket prefill
        pos = np.where(active, self._pos, 0).astype(np.int32)
        cur = self._cur.copy()
        sampled = [l.request is not None
                   and self._lane_sampling(l.request)[0] > 0.0
                   for l in self._lane_state]
        drafts = np.zeros((lanes_n, k), np.int32)
        dprobs = [[None] * k for _ in range(lanes_n)]
        dcur = cur.copy()
        for j in range(k):
            d_logits, self._d_cache = self._d_decode(
                self.dparams, self._d_cache, jnp.asarray(dcur),
                jnp.asarray(pos + j))
            dl = np.asarray(d_logits, np.float32)
            greedy_next = dl.argmax(-1)
            for i, lane in enumerate(self._lane_state):
                if sampled[i]:
                    t, tk, tp = self._lane_sampling(lane.request)
                    p = filtered_probs(dl[i], t, tk, tp)
                    drafts[i, j] = int(
                        lane.request._spec_rng.choice(len(p), p=p))
                    dprobs[i][j] = p
                else:
                    drafts[i, j] = int(greedy_next[i])
            dcur[:, 0] = drafts[:, j]
        chunk = np.concatenate([cur, drafts], axis=1)
        t_logits, self._cache = self._spec_verify(
            self.params, self._cache, jnp.asarray(chunk),
            jnp.asarray(pos))
        tl = np.asarray(t_logits, np.float32)       # [lanes, k+1, V]
        # draft backfill: the k-th proposal joined sequences that accept
        # fully but its KV never entered the draft cache (it was only an
        # output); one batched step ingests it at pos+k for every lane —
        # lanes that accepted less overwrite that slot before it is ever
        # attendable, so the unconditional write is safe and uniform
        _, self._d_cache = self._d_decode(
            self.dparams, self._d_cache, jnp.asarray(drafts[:, k - 1:k]),
            jnp.asarray(pos + k))
        for i, lane in enumerate(self._lane_state):
            req = lane.request
            if req is None:
                continue
            if req.cancel_requested:
                lane.request = None
                req._finish()
                continue
            if sampled[i]:
                t, tk, tp = self._lane_sampling(req)
                tpro = [filtered_probs(tl[i, j], t, tk, tp)
                        for j in range(k + 1)]
                accepted, nxt = spec_accept(drafts[i], dprobs[i], tpro,
                                            req._spec_rng)
            else:
                targets = tl[i].argmax(-1)          # [k+1]
                accepted = 0
                while accepted < k and \
                        drafts[i, accepted] == targets[accepted]:
                    accepted += 1
                nxt = int(targets[accepted])
            self.stats.proposed += k
            self.stats.accepted += accepted
            self.lane_stats[i].proposed += k
            self.lane_stats[i].accepted += accepted
            emitted = [int(x) for x in drafts[i, :accepted]] + [int(nxt)]
            lp_rows = None
            if req.want_logprobs:
                # full-softmax log p of each emitted token under the
                # verify logits of ITS slot — identical numbers to the
                # per-token decode path
                row = tl[i, :len(emitted)]
                row = row - row.max(-1, keepdims=True)
                lp_all = row - np.log(np.exp(row).sum(-1, keepdims=True))
                lp_rows = [float(lp_all[j, emitted[j]])
                           for j in range(len(emitted))]
            finished = False
            for j, tok in enumerate(emitted):
                req._push(tok, lp_rows[j] if lp_rows else None)
                lane.pos += 1
                lane.remaining -= 1
                if (lane.remaining <= 0 or hit_stop(req.tokens, gen)
                        or lane.pos + 1 >= self.max_len):
                    finished = True
                    break
            self._cur[i, 0] = req.tokens[-1]
            self._pos[i] = lane.pos
            if finished:
                lane.request = None
                req._finish()

    def _admit(self, lane_idx: int) -> None:
        gen = self.gen
        with self._cv:
            while self._queue and self._queue[0].cancel_requested:
                # cancelled while queued: never pay the prefill
                self._queue.popleft()._finish()
            if not self._queue:
                return
            req = self._queue.popleft()
        # attach BEFORE the prefill work: a failure mid-prefill must leave
        # the request visible to _recover_locked (a popped-but-unattached
        # request would never be cancelled and its waiter would hang)
        lane = self._lane_state[lane_idx]
        lane.request = req
        prompt = req.prompt or [0]
        plen = len(prompt)
        stored, start = self._match_prefix(prompt)
        if stored is not None:
            self._cache = self._load_prefix(self._cache, stored,
                                            jnp.int32(lane_idx))
        suffix = prompt[start:]
        plen_total = start + len(suffix)
        # prefill the suffix in power-of-two chunks that fit the remaining
        # cache space: keeps the compiled-shape set fixed AND never lets a
        # padded chunk run past the cache end (jax clamps a too-far
        # dynamic_update_slice start, which would overwrite the
        # just-loaded prefix slots). validate() guarantees the suffix fits.
        pos0, remaining = start, suffix
        while remaining:
            space = self.max_len - pos0
            bucket = min(_bucket(len(remaining)), _pow2_floor(space))
            n = min(len(remaining), bucket)
            chunk, remaining = remaining[:n], remaining[n:]
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = chunk
            logits, self._cache = self._prefill(self.params, self._cache,
                                                jnp.asarray(toks),
                                                jnp.int32(lane_idx),
                                                jnp.int32(pos0),
                                                jnp.int32(n))
            pos0 += n
        plen = plen_total
        self._key, sub = jax.random.split(self._key)
        t, k_, p_ = self._lane_sampling(req)
        if t <= 0.0:
            # default/greedy: the one static-arg compile (plain argmax)
            first = int(self._sample(logits, sub, 0.0, 0, 1.0)[0])
        else:
            # TRACED params: distinct client triples must not each pay a
            # fresh XLA trace of a static-arg sampler
            first = int(sample_logits_many(
                logits, sub, jnp.asarray([t], jnp.float32),
                jnp.asarray([k_], jnp.int32),
                jnp.asarray([p_], jnp.float32))[0])
        req._push(first, float(token_logprobs(
            logits, jnp.asarray([first]))[0]) if req.want_logprobs else None)
        lane.pos = plen
        lane.remaining = req.max_new - 1
        self._cur[lane_idx, 0] = first
        self._pos[lane_idx] = plen
        if lane.remaining <= 0 or hit_stop(req.tokens, gen):
            lane.request = None    # finished in prefill
            req._finish()
        elif self.spec_k:
            # draft prefills the FULL prompt into ITS lane (prefix KV
            # blocks are target-model state; the draft pays its own
            # prefill so its cache is exact and proposals stay sharp —
            # a stale draft cache would only cost acceptance, but a
            # deterministic one keeps rounds reproducible)
            pos0, remaining = 0, prompt
            while remaining:
                space = self.max_len - pos0
                bucket = min(_bucket(len(remaining)), _pow2_floor(space))
                n = min(len(remaining), bucket)
                chunk, remaining = remaining[:n], remaining[n:]
                toks = np.zeros((1, bucket), np.int32)
                toks[0, :n] = chunk
                _, self._d_cache = self._d_prefill(
                    self.dparams, self._d_cache, jnp.asarray(toks),
                    jnp.int32(lane_idx), jnp.int32(pos0), jnp.int32(n))
                pos0 += n
            # per-request host rng drives the sampled accept rule
            req._spec_rng = np.random.default_rng(
                self._seed + 1000003 * self._spec_admitted)
            self._spec_admitted += 1

    def _step_once(self) -> bool:
        """Fill free lanes, run one decode tick (or a speculative round
        when a draft model is configured). Returns False once idle."""
        gen = self.gen
        for i, lane in enumerate(self._lane_state):
            while self._queue and lane.request is None:
                self._admit(i)
            if not self._queue:
                break
        if not self._active():
            return bool(self._queue)
        if self.spec_k:
            k = self._spec_round_k()
            if k >= 1:
                self._spec_round(k)
                return True
            # near the cache cap a verify chunk no longer fits: finish
            # with plain single-token ticks (same as the single-sequence
            # engine's tail loop)
        # one decode tick for every lane (dead lanes compute garbage)
        logits, self._cache = self._decode(
            self.params, self._cache, jnp.asarray(self._cur),
            jnp.asarray(self._pos))
        if self.spec_k:
            # near-cap fallback ticks must keep the DRAFT cache in
            # lockstep (ingest the same token at the same position the
            # target just did) — otherwise later spec rounds on other
            # lanes attend stale draft KV and acceptance silently decays
            _, self._d_cache = self._d_decode(
                self.dparams, self._d_cache, jnp.asarray(self._cur),
                jnp.asarray(self._pos))
        self._key, sub = jax.random.split(self._key)

        def lane_param(attr, default):
            return [getattr(l.request, attr, None)
                    if l.request is not None and
                    getattr(l.request, attr) is not None else default
                    for l in self._lane_state]

        temps = lane_param("temperature", gen.temperature)
        active_temps = [t for t, l in zip(temps, self._lane_state)
                        if l.request is not None]
        if all(t <= 0.0 for t in active_temps):
            # free lanes carry the engine default but emit nothing —
            # only live requests decide the fast path
            # all-greedy tick (the default deployment): one argmax, not
            # two full-vocab sorts per decoded token
            nxt = np.asarray(self._sample(logits, sub, 0.0, 0, 1.0))
        else:
            nxt = np.asarray(sample_logits_many(
                logits, sub, jnp.asarray(temps, jnp.float32),
                jnp.asarray(lane_param("top_k", gen.top_k), jnp.int32),
                jnp.asarray(lane_param("top_p", gen.top_p), jnp.float32)))
        lane_lps = None
        if any(l.request is not None and l.request.want_logprobs
               for l in self._lane_state):
            lane_lps = np.asarray(token_logprobs(logits,
                                                 jnp.asarray(nxt)))
        for i, lane in enumerate(self._lane_state):
            req = lane.request
            if req is None:
                continue
            tok = int(nxt[i])
            req._push(tok, float(lane_lps[i]) if req.want_logprobs else None)
            lane.pos += 1
            lane.remaining -= 1
            self._cur[i, 0] = tok
            self._pos[i] = lane.pos
            if (req.cancel_requested or lane.remaining <= 0
                    or hit_stop(req.tokens, gen)
                    or lane.pos + 1 >= self.max_len):
                lane.request = None   # lane freed for the next arrival
                req._finish()
        return True
