"""Continuous batching: slot-scheduled decoding over a shared KV cache.

The static engine (``engine.InferenceEngine``) decodes one left-padded
batch in lockstep: every request waits for the whole batch to finish.
This engine keeps a fixed set of ``lanes`` (batch rows of one shared
cache) and schedules requests onto free lanes as they open — the
vLLM-style recipe, shaped for TPU:

* ONE jitted decode step for all lanes per tick, with **per-row
  positions** (``llama.attention_step``'s vector ``start_pos``): no
  re-padding, no recompilation as requests of different lengths come and
  go;
* prefill writes a single lane of the shared cache in place
  (``dynamic_update_slice`` on the lane axis) with prompts right-padded
  into power-of-two buckets — a handful of compiled shapes total;
* dead lanes keep decoding garbage (uniform SPMD — masking happens in the
  scheduler, not the compiled step), and their cache writes land on slots
  that are overwritten before ever becoming attendable;
* scheduling (arrivals, eos, lane reuse) is host-side Python between
  ticks, exactly where dynamic control flow belongs on TPU.

**Paged KV (the default, ``KUBEDL_KV_MODE=paged``)**: instead of a dense
``max_len`` slab per lane, KV lives in ONE pool of fixed-size token
blocks (``models.llama.init_block_pool``) indexed through per-lane
host-side block tables that grow on demand. Block tables are a traced
operand of the same jitted steps (gather on the block axis), so the
compiled program stays uniform SPMD while HBM tracks *live tokens*, not
``lanes * max_len``. Registered prefixes pin their full blocks once and
every matching request's table references them (copy-on-write sharing
with refcounts — a lane's own writes always land in fresh private
blocks); admission requires free blocks for the prompt plus headroom,
and when the pool runs dry mid-decode the lowest-progress lane is
preempted back to the queue (resumed later by re-prefilling prompt +
generated-so-far) instead of OOMing. ``KUBEDL_KV_MODE=dense`` keeps the
original slab; ``parity`` runs both and asserts token-identical logits
every step — how the test suite keeps the paged rewrite honest
(mirroring the control plane's ``KUBEDL_LIST_MODE`` pattern).

The reference operator serves models via fixed Deployments
(``controllers/serving``); request-level scheduling like this has no
reference analog — TPU-native capability beyond parity.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from ..trace import NOOP_TRACER
from .engine import (GenerateConfig, hit_stop, sample_logits_many,
                     token_logprobs)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (max(n, 1).bit_length() - 1)


ENV_KV_MODE = "KUBEDL_KV_MODE"
KV_MODES = ("dense", "paged", "parity")


def resolve_kv_mode(mode: Optional[str] = None) -> str:
    """KV layout mode: explicit arg wins, then ``$KUBEDL_KV_MODE``, then
    the paged default. ``dense`` keeps the per-lane slab (the baseline
    the bench compares against); ``parity`` runs both and asserts
    token-identical logits each step."""
    import os
    mode = mode or os.environ.get(ENV_KV_MODE, "") or "paged"
    if mode not in KV_MODES:
        raise ValueError(
            f"unknown KV mode {mode!r}; one of {KV_MODES}")
    return mode


def fit_block(block: int, max_len: int) -> int:
    """Largest block size <= ``block`` that divides ``max_len`` (halving
    search, floor 1). Divisibility makes the paged gather view EXACTLY
    ``max_len`` slots, so parity mode's logits are bit-comparable to the
    dense slab (same reduction lengths, same masked tail)."""
    b = max(int(block), 1)
    while max_len % b:
        b //= 2
    return max(b, 1)


class BlockPool:
    """Host-side allocator for the paged KV pool.

    Physical block ids run ``1..total`` — id 0 is the reserved garbage
    sink every free table entry points at (dead lanes keep computing
    under uniform SPMD; their writes must land somewhere that is never
    attendable). Blocks are refcounted so registered prefixes can pin
    blocks that many lanes reference concurrently: ``alloc`` starts a
    block at refcount 1, ``incref`` adds a sharer, ``decref`` returns
    the block to the free list at zero. ``allocs`` counts lifetime block
    allocations — the budget the tier-1 perf guard asserts on (work
    counters, not wall clocks)."""

    def __init__(self, total: int):
        if total < 1:
            raise ValueError(f"pool needs >= 1 usable block, got {total}")
        self.total = total
        # pop() hands out low ids first
        self._free = list(range(total, 0, -1))
        self._ref: dict[int, int] = {}
        self.allocs = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.total - len(self._free)

    @property
    def shared_count(self) -> int:
        """Blocks referenced by more than one holder (prefix sharing)."""
        return sum(1 for r in self._ref.values() if r > 1)

    def alloc(self, n: int) -> Optional[list]:
        """n fresh blocks at refcount 1, or None when the pool is dry
        (all-or-nothing: a partial grant would leak on the retry path)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.allocs += n
        return out

    def incref(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self._ref[b] += 1

    def decref(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            r = self._ref[b] - 1
            if r:
                self._ref[b] = r
            else:
                del self._ref[b]
                self._free.append(b)

    def refcounts(self) -> dict:
        """Live block -> refcount snapshot (leak checks)."""
        return dict(self._ref)


@dataclass(frozen=True)
class _Prefix:
    """One registered prompt prefix. ``stored`` is the dense-mode full
    KV copy (legacy ``_load_prefix`` path); ``blocks`` are the paged
    pool blocks pinned for the prefix's FULL blocks only — the partial
    tail block is never shared (two lanes would write different tokens
    into it), it is re-prefilled per lane instead. ``pinned`` prefixes
    are exempt from the least-recently-hit eviction that makes room at
    ``max_prefixes`` (docs/serving_fleet.md: the fleet router registers
    prefixes opportunistically; an operator-pinned system prompt must
    never be displaced by that churn). ``model`` scopes the entry: the
    cache keys on ``(model, tokens)`` so two models' identical token
    prefixes can never alias each other's KV blocks — a LoRA adapter's
    attention output differs from the base model's even on identical
    tokens, so a cross-model share would serve WRONG KV
    (docs/multimodel.md). "" is the base model; every pre-multi-model
    caller stays on it untouched."""
    key: tuple
    plen: int
    stored: Optional[dict] = None
    blocks: tuple = ()
    pinned: bool = False
    model: str = ""


@dataclass
class Request:
    """One in-flight generation; ``done`` fires when ``tokens`` is final
    (or the engine stopped — then ``cancelled`` is set). With
    ``want_logprobs`` each generated token's full-softmax log p lands
    in ``logprobs``.

    Tokens are appended by the scheduler thread as they decode;
    :meth:`stream` consumes them incrementally (the serving layer's SSE
    path rides this), :meth:`result` waits for the final list."""
    prompt: list
    max_new: int
    tokens: list = field(default_factory=list)
    logprobs: list = field(default_factory=list)
    want_logprobs: bool = False
    #: adapter id this request decodes under ("" = the base model).
    #: Admission gates on the adapter being resident (a cold one faults
    #: its weight pages in through the pool first — docs/multimodel.md)
    model: str = ""
    #: per-request sampling overrides; None = the engine's GenerateConfig
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    cancelled: bool = False
    #: client-requested stop (set via :meth:`cancel`): the scheduler
    #: frees the lane at its next tick; tokens decoded so far remain
    cancel_requested: bool = False
    #: scheduler-reported failure (e.g. a request that can never be
    #: admitted because the KV pool is too small after prefix pins) —
    #: surfaces through result()/stream() instead of the generic
    #: engine-stopped message
    error: Optional[str] = None
    #: request trace id (docs/tracing.md), assigned at submit when the
    #: engine carries an enabled tracer; "" otherwise. The console's
    #: /api/v1/trace/request/{id} endpoint looks spans up by it.
    trace_id: str = ""
    _cond: threading.Condition = field(default_factory=threading.Condition)
    # trace bookkeeping (engine-side; meaningless when trace_id == "")
    _span_root: str = ""
    _t_submit: float = 0.0
    _t_queue: float = 0.0     # when the request (re-)entered the queue
    _t_decode: float = 0.0
    _preempts: int = 0

    def cancel(self) -> None:
        """Stop generating for this request (client went away / got what
        it needed). Unlike engine shutdown, ``result()`` still returns
        the tokens decoded so far."""
        self.cancel_requested = True

    def result(self, timeout: Optional[float] = None) -> list:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.cancelled:
            raise RuntimeError(
                self.error or "generation cancelled: engine stopped")
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Yield ``(token_id, logprob_or_None)`` as the scheduler emits
        them; returns when generation finishes. ``timeout`` bounds the
        wait for EACH next token (a stalled engine surfaces as
        TimeoutError instead of a silent hang)."""
        sent = 0
        while True:
            with self._cond:
                while len(self.tokens) <= sent and not self.done.is_set():
                    if not self._cond.wait(timeout):
                        raise TimeoutError(
                            "no token within the streaming timeout")
                # snapshot UNDER the lock: _push appends token+logprob as
                # one critical section, so pairs read here are aligned (a
                # lock-free read could see the token before its logprob)
                fresh = []
                while sent < len(self.tokens):
                    lp = (self.logprobs[sent] if self.want_logprobs
                          and sent < len(self.logprobs) else None)
                    fresh.append((self.tokens[sent], lp))
                    sent += 1
                finished = self.done.is_set() and sent >= len(self.tokens)
                cancelled = self.cancelled
            yield from fresh
            if finished:
                if cancelled:
                    raise RuntimeError(
                        self.error or "generation cancelled: engine stopped")
                return

    # -- scheduler-side helpers (single writer: the scheduler thread) ----

    def _push(self, tok: int, lp: Optional[float]) -> None:
        with self._cond:
            self.tokens.append(tok)
            if lp is not None:
                self.logprobs.append(lp)
            self._cond.notify_all()

    def _finish(self, cancelled: bool = False) -> None:
        with self._cond:
            self.cancelled = self.cancelled or cancelled
            self.done.set()
            self._cond.notify_all()


@dataclass
class _Lane:
    request: Optional[Request] = None    # None = free
    pos: int = 0               # next write position (== tokens so far)
    remaining: int = 0
    #: paged modes: pool blocks this lane references, in logical order
    #: (shared prefix blocks first, then private). Freed via decref when
    #: the lane finishes/cancels/preempts.
    blocks: list = field(default_factory=list)
    #: disaggregated serving (docs/serving_fleet.md): a prefill lane
    #: whose request finished prefilling and is waiting for a free
    #: decode lane to take the block-table handoff. Parked lanes are
    #: masked out of decode ticks (their KV must not move until the
    #: handoff lands).
    parked: bool = False
    parked_at: float = 0.0     # tracer clock at park (handoff span)
    #: multi-model serving: the adapter this lane decodes under and the
    #: weight pages it increfed at admission (released exactly once via
    #: _free_lane; a handoff MOVES them with the block-table row)
    adapter: str = ""
    adapter_blocks: list = field(default_factory=list)

    def reset(self) -> None:
        self.request = None
        self.pos = 0
        self.remaining = 0
        self.blocks = []
        self.parked = False
        self.parked_at = 0.0
        self.adapter = ""
        self.adapter_blocks = []


class ContinuousBatchingEngine:
    """Slot-scheduled generation over one shared cache.

    ``run(requests)`` takes ``[(prompt_tokens, max_new_tokens), ...]`` in
    arrival order and returns one generated-id list per request; requests
    are admitted to lanes as earlier ones finish, so a short request never
    waits on a long co-batched one."""

    def __init__(self, config: llama.LlamaConfig, params: dict,
                 lanes: int = 4, max_len: int = 1024,
                 gen: Optional[GenerateConfig] = None,
                 quantize: Optional[str] = None, seed: int = 0,
                 mesh=None, draft_config=None, draft_params=None,
                 spec_k: int = 0, quantize_draft: Optional[str] = None,
                 kv_mode: Optional[str] = None, kv_block: int = 64,
                 pool_blocks: Optional[int] = None,
                 headroom_blocks: int = 1, tracer=None,
                 prefill_lanes: int = 0, adapters=None,
                 max_adapters: Optional[int] = None):
        from .engine import (SpecStats, init_mesh_serving, resolve_family,
                             sample_logits)
        self.config = config
        #: per-request span recorder (queue/prefill/decode/preemption
        #: spans, docs/tracing.md); the shared disabled tracer by default
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.family = family = resolve_family(config)
        self.lanes = lanes
        self.max_len = max_len
        self.gen = gen or GenerateConfig(max_len=max_len)
        self.mesh = mesh
        #: KV layout: "paged" (default), "dense" (per-lane slab
        #: baseline), or "parity" (both, asserted token-identical)
        self.kv_mode = resolve_kv_mode(kv_mode)
        #: tokens per pool block, clamped so it divides max_len (keeps
        #: the gather view exactly max_len slots — see fit_block)
        self.kv_block = fit_block(kv_block, max_len)
        self._bpl = max_len // self.kv_block   # table entries per lane
        #: usable pool blocks (the garbage sink rides on top); the
        #: default matches the dense slab's capacity so plain
        #: deployments behave identically — shrink it to overcommit
        #: lanes against actual sequence lengths (the paged win)
        self.pool_blocks = (int(pool_blocks) if pool_blocks
                            else lanes * self._bpl)
        if self.pool_blocks < self._bpl:
            raise ValueError(
                f"pool_blocks {self.pool_blocks} < {self._bpl} blocks "
                f"needed for one full-length request (max_len {max_len} "
                f"/ block {self.kv_block})")
        #: admission watermark: free blocks required beyond the prompt's
        #: so a fresh lane can decode a while before growing
        self.headroom_blocks = max(int(headroom_blocks), 0)
        #: disaggregated prefill/decode (docs/serving_fleet.md): the
        #: first ``prefill_lanes`` lanes only ever run prefills; a
        #: freshly-prefilled request hands its BLOCK TABLE to a free
        #: decode lane (no KV copied — the table entries ARE the KV),
        #: so a long prompt's chunked prefill never occupies a decode
        #: lane. 0 (the default) = the combined engine, byte-identical.
        self.prefill_lanes = int(prefill_lanes)
        if self.prefill_lanes:
            if self.kv_mode != "paged":
                raise ValueError(
                    "disaggregated prefill lanes require the paged KV "
                    "layout (the handoff moves block-table references; "
                    "a dense slab would need a device KV copy)")
            if not 0 < self.prefill_lanes < lanes:
                raise ValueError(
                    f"prefill_lanes {self.prefill_lanes} must leave at "
                    f"least one decode lane (lanes {lanes})")
            if draft_params is not None and spec_k:
                raise ValueError(
                    "speculative decoding and disaggregated prefill "
                    "lanes are mutually exclusive (the verify round "
                    "spans every lane)")
        #: lifetime prefill→decode block-table handoffs (/metrics)
        self.handoffs = 0
        #: prompt tokens prefilled in the current / all scheduler ticks
        #: (the replay's cost-model seam: a combined deployment's decode
        #: cadence stalls for the prefill work a tick performed)
        self.prefill_tokens_step = 0
        self.prefill_tokens_total = 0
        #: lifetime preemption count (pool ran dry; /metrics counter)
        self.preempted = 0
        #: peak simultaneously-active lanes (the bench's concurrency
        #: number; admission caps it by blocks, not just lane count)
        self.peak_active = 0
        # tensor-parallel serving over a local mesh (one host's chips):
        # params by logical specs, cache by kv-heads; the jitted steps
        # are unchanged — GSPMD inserts the collectives.
        self.params, self._place_cache = init_mesh_serving(
            config, params, quantize, mesh)
        cfg = config

        # -- speculative decoding per lane (draft model proposes spec_k
        # tokens for EVERY lane, the target verifies all lanes' chunks in
        # one [lanes, k+1] pass) — concurrent speculative serving
        self.spec_k = int(spec_k) if draft_params is not None else 0
        if self.spec_k:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    "target and draft must share a vocabulary")
            self.dcfg = draft_config
            self.dfam = resolve_family(draft_config)
            # the draft rides the same mesh as the target (its params by
            # ITS logical specs, its cache by ITS kv-heads) — spec lanes
            # compose with tensor-parallel serving; draft quantization
            # only without a mesh (same rule as the target)
            self.dparams, self._place_d_cache = init_mesh_serving(
                draft_config, draft_params, quantize_draft, mesh)
            #: aggregate + per-lane acceptance accounting (/metrics)
            self.stats = SpecStats()
            self.lane_stats = [SpecStats() for _ in range(lanes)]

        def make_decode(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _decode(params, cache, tokens, positions):
                # tokens [lanes, 1], positions [lanes] — per-row writes
                return fam.forward_step(cfg_, params, tokens, cache,
                                        positions)
            return _decode

        def make_prefill(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _prefill(params, cache, tokens, lane, start, n_real):
                # tokens [1, bucket] right-padded; lane/start/n_real are
                # TRACED so only the bucket size (a handful of
                # power-of-two shapes) triggers a compile. The chunk
                # lands at ``start`` (0 for a plain prefill; the prefix
                # length when a cached prefix was loaded first). Returns
                # the real last token's logits (last_pos gathers it
                # pre-LM-head: one vocab projection, not bucket of
                # them). valid marks the live cache region: attention
                # never sees the right-pad anyway (causal +
                # overwrite-before-attend), but MoE ROUTING must not let
                # pad tokens consume expert capacity.
                row = {k: jax.lax.dynamic_slice_in_dim(v, lane, 1, axis=1)
                       for k, v in cache.items()}
                valid = (jnp.arange(row["k"].shape[2])
                         < start + n_real)[None, :]
                last, row = fam.forward_step(cfg_, params, tokens, row,
                                             start, valid=valid,
                                             last_pos=n_real - 1)
                cache = {k: jax.lax.dynamic_update_slice_in_dim(
                    cache[k], row[k], lane, axis=1) for k in cache}
                return last, cache
            return _prefill

        _decode = make_decode(cfg, family)
        _prefill = make_prefill(cfg, family)

        def make_decode_paged(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _decode_p(params, pool, tokens, positions, tables):
                # the pool is donated like the dense cache (decode is
                # HBM-bound); tables are traced so block growth /
                # sharing never recompiles
                return fam.forward_step_paged(cfg_, params, tokens, pool,
                                              tables, positions)
            return _decode_p

        def make_prefill_paged(cfg_, fam):
            @partial(jax.jit, donate_argnums=(1,))
            def _prefill_p(params, pool, tokens, table_row, start, n_real):
                # tokens [1, bucket] right-padded; table_row [bpl] is the
                # ONE lane's block map (host-grown before the call).
                # Same bucket-shape compile story as the dense prefill.
                blk = pool["k"].shape[2]
                view = table_row.shape[0] * blk
                valid = (jnp.arange(view) < start + n_real)[None, :]
                return fam.forward_step_paged(
                    cfg_, params, tokens, pool, table_row[None, :], start,
                    valid=valid, last_pos=n_real - 1)
            return _prefill_p

        @partial(jax.jit, donate_argnums=(1,))
        def _spec_verify(params, cache, tokens, positions):
            # tokens [lanes, k+1] at per-row positions: ONE target pass
            # verifies every lane's draft chunk (all-position logits)
            return family.forward_step(cfg, params, tokens, cache,
                                       positions, all_logits=True)

        @partial(jax.jit, donate_argnums=(1,))
        def _spec_verify_paged(params, pool, tokens, positions, tables):
            return family.forward_step_paged(cfg, params, tokens, pool,
                                             tables, positions,
                                             all_logits=True)

        @partial(jax.jit)
        def _fill_prefix(params, tokens, plen):
            # build a shared-prefix KV block on a scratch single-lane
            # cache sized to the bucket (stored bucket-padded; garbage
            # beyond plen is causally invisible once loaded into a lane)
            scratch = family.init_cache(cfg, 1, tokens.shape[1])
            valid = (jnp.arange(tokens.shape[1]) < plen)[None, :]
            _, scratch = family.forward_step(cfg, params, tokens, scratch,
                                             jnp.int32(0), valid=valid,
                                             last_pos=plen - 1)
            return scratch

        @partial(jax.jit, donate_argnums=(0,))
        def _load_prefix(cache, stored, lane):
            # copy a stored prefix KV block into one lane's cache rows
            def put(c, s):
                return jax.lax.dynamic_update_slice(
                    c, s.astype(c.dtype),
                    (0, lane) + (0,) * (c.ndim - 2))
            return {k: put(cache[k], stored[k]) for k in cache}

        self._decode = _decode
        self._prefill = _prefill
        self._fill_prefix = _fill_prefix
        self._load_prefix = _load_prefix
        self._prefixes: list = []   # sorted [_Prefix], longest first
        #: admission-time hit ordinals per prefix key — the
        #: least-recently-hit order ``register_prefix`` evicts in when
        #: the cap is reached (mutated under ``_sched_lock`` only)
        self._prefix_hits: dict = {}
        self._prefix_hit_clock = 0
        self._sample = sample_logits
        if self.spec_k:
            self._d_decode = make_decode(self.dcfg, self.dfam)
            self._d_prefill = make_prefill(self.dcfg, self.dfam)
            self._spec_verify = _spec_verify
            self._d_cache = self._place_d_cache(
                self.dfam.init_cache(self.dcfg, lanes, max_len))
            #: per-request host rng for the sampled accept rule,
            #: allocated at admission (seed + admission ordinal)
            self._spec_admitted = 0

        # live scheduler state: one shared cache (dense slab and/or
        # paged pool per kv_mode) + lane bookkeeping; the host mirrors
        # (cur/pos/tables) feed the per-tick decode call
        if self.kv_mode in ("dense", "parity"):
            self._cache = self._place_cache(
                family.init_cache(config, lanes, max_len))
        if self.kv_mode in ("paged", "parity"):
            self._pool = self._place_cache(family.init_block_pool(
                config, self.pool_blocks + 1, self.kv_block))
            self._bpool = BlockPool(self.pool_blocks)
            self._tables = np.zeros((lanes, self._bpl), np.int32)
            self._decode_p = make_decode_paged(cfg, family)
            self._prefill_p = make_prefill_paged(cfg, family)
            self._spec_verify_p = _spec_verify_paged
        #: multi-model serving (docs/multimodel.md): an AdapterCatalog
        #: turns this engine into a multiplexer — requests carry a
        #: ``model=`` id and the adapter's weight pages allocate from
        #: the SAME refcounted pool as KV blocks. ``max_adapters`` is
        #: the resident-count cap (the ``max_prefixes`` analog).
        self._adapters = None
        if adapters is not None:
            if self.kv_mode == "dense":
                raise ValueError(
                    "multi-model adapters require a paged KV layout "
                    "(adapter weight pages live in the block pool; a "
                    "dense slab has no pool to page them from)")
            from .adapters import AdapterResidency
            self._adapters = AdapterResidency(
                adapters, self._bpool, max_resident=max_adapters)
        #: monotonic residency generation: bumped whenever the prefix
        #: set or resident-adapter set changes, so the fleet router can
        #: cache residency snapshots and probe without taking
        #: _sched_lock on every submit (invalidation = epoch mismatch)
        self.residency_epoch = 0
        #: adapter weight pages cold-faulted in the current tick (the
        #: replay's cost-model seam, like prefill_tokens_step)
        self.adapter_fault_pages_step = 0
        self._lane_state = [_Lane() for _ in range(lanes)]
        self._cur = np.zeros((lanes, 1), np.int32)
        self._pos = np.zeros((lanes,), np.int32)
        self._seed = seed
        self._key = jax.random.PRNGKey(seed)
        self._queue: deque[Request] = deque()
        self._cv = threading.Condition()
        #: serializes the whole scheduler step (donated cache + lane
        #: bookkeeping are shared mutable state): inline run() callers and
        #: the background loop can never tick concurrently
        self._sched_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- public API -------------------------------------------------------

    def register_prefix(self, tokens: Sequence[int],
                        max_prefixes: Optional[int] = None,
                        pinned: bool = False, model: str = "") -> None:
        """Prefill a shared prompt prefix ONCE; later requests whose
        prompts start with it skip re-prefilling it — the standard
        system-prompt optimization. Greedy outputs are unchanged (the
        shared KV is exactly what the full prefill would have written).

        Dense mode stashes a full KV copy that ``_load_prefix`` writes
        into each matching lane. Paged modes pin the prefix's FULL
        blocks in the pool instead: matching lanes point their block
        tables at them (refcounted copy-on-write sharing, no device
        copy at admission); the partial tail block — where a lane's own
        tokens would land next to prefix tokens — is never shared and
        is re-prefilled per lane.

        At ``max_prefixes`` the LEAST-RECENTLY-HIT unpinned prefix is
        evicted (its pin decref'd — lanes still referencing the blocks
        keep them alive until they finish) instead of the registration
        failing: the fleet router registers prefixes opportunistically
        on whichever replica it warms (docs/serving_fleet.md), and a
        hard raise there would wedge placement on a full cache. Only
        when every stored prefix is ``pinned`` does the cap still
        raise.

        ``model`` scopes the entry to one adapter ("" = base model):
        the cache keys on ``(model, tokens)``, so only requests
        decoding under the SAME model match it — identical token
        prefixes under different adapters hold different KV
        (docs/multimodel.md)."""
        tokens = list(tokens)
        if not tokens:
            raise ValueError("empty prefix")
        plen = len(tokens)
        if plen >= self.max_len:
            raise ValueError(
                f"prefix {plen} exceeds cache capacity {self.max_len}")
        key = tuple(tokens)
        model = model or ""
        if max_prefixes is not None and \
                not any(p.key == key and p.model == model
                        for p in self._prefixes) and \
                len(self._prefixes) >= max_prefixes and \
                all(p.pinned for p in self._prefixes):
            # optimistic pre-check: a rejected registration must not
            # first burn a full device prefill (the authoritative check
            # below runs under the lock)
            raise ValueError(
                f"prefix limit {max_prefixes} reached and every stored "
                "prefix is pinned (each prefix pins a KV block in HBM)")
        stored = None
        if self.kv_mode == "dense":
            bucket = min(_bucket(plen), self.max_len)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :plen] = tokens
            stored = self._fill_prefix(self.params, jnp.asarray(toks),
                                       jnp.int32(plen))
        with self._sched_lock:
            # dedup (re-registering replaces) + longest-first ordering so
            # the best match wins during admission; swap in a NEW list so
            # concurrent _match_prefix iterations never see a mid-sort view
            entries = [p for p in self._prefixes
                       if not (p.key == key and p.model == model)]
            # cap enforced HERE, under the lock: a server-side
            # check-then-call would race concurrent registrations past
            # the limit, and an idempotent re-register (key already
            # stored) must never be rejected — it pins no new HBM.
            # Over-cap registrations evict the least-recently-hit
            # unpinned prefix; the raise survives only for an all-pinned
            # cache (nothing is legally evictable).
            evicted: list = []
            if max_prefixes is not None:
                while len(entries) >= max_prefixes:
                    victims = [p for p in entries if not p.pinned]
                    if not victims:
                        raise ValueError(
                            f"prefix limit {max_prefixes} reached and "
                            "every stored prefix is pinned (each prefix "
                            "pins a KV block in HBM)")
                    victim = min(victims, key=lambda p: (
                        self._prefix_hits.get((p.model, p.key), 0),
                        p.model, p.key))
                    entries = [p for p in entries
                               if not (p.key == victim.key
                                       and p.model == victim.model)]
                    evicted.append(victim)
            blocks: tuple = ()
            if self.kv_mode != "dense":
                # release a replaced pin BEFORE allocating the new one:
                # an idempotent re-register must never need net-new
                # blocks (on a tight pool, alloc-then-decref would
                # refuse a same-key refresh that frees as much as it
                # takes). The entry list is swapped in first so a failed
                # re-fill can never leave a registered entry pointing at
                # freed blocks — the old registration is simply gone.
                # Evicted victims decref the same way: a lane still
                # sharing the blocks keeps them alive; an unreferenced
                # pin returns to the free list right here.
                for old in self._prefixes:
                    if old.key == key and old.model == model \
                            and old.blocks:
                        self._bpool.decref(old.blocks)
                for victim in evicted:
                    if victim.blocks:
                        self._bpool.decref(victim.blocks)
                self._prefixes = entries
                # KV at position p depends only on tokens <= p, so the
                # shareable full blocks need exactly the first
                # n_full*block tokens prefilled — the tail is per-lane
                n_full = plen // self.kv_block
                if n_full:
                    got = self._bpool.alloc(n_full)
                    if got is None:
                        raise ValueError(
                            f"KV pool exhausted: prefix needs {n_full} "
                            f"blocks, {self._bpool.free_count} free")
                    blocks = tuple(got)
                    try:
                        self._fill_prefix_blocks(
                            blocks, tokens[:n_full * self.kv_block])
                    except BaseException:
                        # _prefill_p donates the LIVE pool (unlike the
                        # dense _fill_prefix, which runs on a scratch
                        # buffer): an abort mid-fill may have consumed
                        # it AND strands `got` at refcount 1 with no
                        # owner. Same remedy as a failed inline step —
                        # rebuild pool + allocator + surviving pins
                        # (we hold _sched_lock, as _recover_locked
                        # requires).
                        self._recover_locked()
                        raise
            for victim in evicted:
                self._prefix_hits.pop((victim.model, victim.key), None)
            # seed the hit clock at registration: a never-yet-admitted
            # prefix must rank by registration recency, not tie at 0 —
            # otherwise the victim among fresh registrations falls to
            # arbitrary token-tuple order and router-driven churn can
            # evict the prefix it registered one request ago
            self._record_prefix_hit((model, key))
            entries = entries + [_Prefix(key=key, plen=plen,
                                         stored=stored, blocks=blocks,
                                         pinned=bool(pinned),
                                         model=model)]
            entries.sort(key=lambda p: -p.plen)
            self._prefixes = entries
            self.residency_epoch += 1

    def _chunked_prefill(self, step, seq: list, start: int):
        """THE chunking rule, shared by every prefill path (dense lane,
        paged lane, prefix fill, draft): feed ``seq[start:]`` through
        ``step(toks [1, bucket] np.int32, pos0, n) -> logits`` in
        right-padded power-of-two chunks that fit the remaining cache
        space. That clamp is load-bearing twice over: it keeps the
        compiled-shape set fixed AND never lets a padded chunk run past
        the cache end (jax clamps a too-far dynamic_update_slice start,
        which would overwrite just-loaded prefix slots). Returns the
        last chunk's logits. validate() guarantees the fit."""
        logits = None
        pos0, remaining = start, list(seq[start:])
        while remaining:
            space = self.max_len - pos0
            bucket = min(_bucket(len(remaining)), _pow2_floor(space))
            n = min(len(remaining), bucket)
            chunk, remaining = remaining[:n], remaining[n:]
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = chunk
            logits = step(toks, pos0, n)
            pos0 += n
        return logits

    def _fill_prefix_blocks(self, blocks: Sequence[int],
                            tokens: list) -> None:
        """Chunk-prefill ``tokens`` into ``blocks`` through a scratch
        table row (caller holds ``_sched_lock``; the pool is donated
        through ``_prefill_p`` like every other step)."""
        row = np.zeros((self._bpl,), np.int32)
        row[:len(blocks)] = blocks
        row_j = jnp.asarray(row)

        def step(toks, pos0, n):
            logits, self._pool = self._prefill_p(
                self.params, self._pool, jnp.asarray(toks), row_j,
                jnp.int32(pos0), jnp.int32(n))
            return logits

        self._chunked_prefill(step, list(tokens), 0)

    @property
    def prefix_count(self) -> int:
        return len(self._prefixes)

    def clear_prefixes(self) -> None:
        """Drop every stored prefix KV block (frees device memory /
        unpins pool blocks)."""
        with self._sched_lock:
            for p in self._prefixes:
                if p.blocks:
                    self._bpool.decref(p.blocks)
            self._prefixes = []
            self._prefix_hits = {}
            self.residency_epoch += 1

    def _record_prefix_hit(self, key: tuple) -> None:
        """Admission-time LRU bookkeeping (caller holds _sched_lock)."""
        self._prefix_hit_clock += 1
        self._prefix_hits[key] = self._prefix_hit_clock

    def _match_prefix(self, prompt: list, model: str = "",
                      record_hit: bool = True):
        """Dense-mode match: (stored KV, suffix start). Scoped to
        ``model`` — another model's identical tokens never match."""
        for p in self._prefixes:
            if p.model == model and len(prompt) >= p.plen \
                    and tuple(prompt[:p.plen]) == p.key:
                if record_hit:
                    self._record_prefix_hit((p.model, p.key))
                # keep at least one suffix token so the prefill has a
                # position to read logits from (re-running the prefix's
                # last token overwrites its own slot with identical KV)
                return p.stored, min(p.plen, len(prompt) - 1)
        return None, 0

    def _match_prefix_blocks(self, prompt: list, model: str = "",
                             record_hit: bool = True):
        """Paged-mode match: (shareable block ids, suffix start). Shares
        only FULL blocks, clamped so at least one suffix token remains
        to prefill (start = n_shared * block <= len(prompt) - 1).
        Scoped to ``model`` like :meth:`_match_prefix`."""
        for p in self._prefixes:
            if p.model == model and len(prompt) >= p.plen \
                    and tuple(prompt[:p.plen]) == p.key:
                if record_hit:
                    self._record_prefix_hit((p.model, p.key))
                n = min(len(p.blocks), (len(prompt) - 1) // self.kv_block)
                return list(p.blocks[:n]), n * self.kv_block
        return [], 0

    def prefix_residency(self, prompt: Sequence[int],
                         model: str = "") -> int:
        """Pool blocks a registered prefix would share with this prompt
        right now (0 = no resident prefix). The fleet router's placement
        signal (docs/serving_fleet.md): the refcounted pool makes
        residency a pure host-side read. Deliberately does NOT touch the
        LRU hit clock — the router probes EVERY replica per request, and
        only real admissions should count as hits."""
        if self.kv_mode == "dense":
            return 0
        with self._sched_lock:
            shared, _ = self._match_prefix_blocks(list(prompt),
                                                  model=model or "",
                                                  record_hit=False)
        return len(shared)

    def has_prefix(self, tokens: Sequence[int], model: str = "") -> bool:
        """Whether exactly this (model, prefix) is registered (the
        router's warm-check before a router-driven
        ``register_prefix``)."""
        key = tuple(tokens)
        model = model or ""
        with self._sched_lock:
            return any(p.key == key and p.model == model
                       for p in self._prefixes)

    def residency_snapshot(self) -> tuple:
        """One consistent ``(epoch, prefixes, resident_adapters,
        kv_block)`` view, where ``prefixes`` is the longest-first
        ``(model, key, n_blocks)`` list the match walks. The fleet
        router caches this per replica keyed on the epoch and computes
        residency host-side — a submit takes ZERO engine locks until
        the epoch moves (docs/multimodel.md "probe cost")."""
        with self._sched_lock:
            return (self.residency_epoch,
                    tuple((p.model, p.key, len(p.blocks))
                          for p in self._prefixes),
                    (frozenset(self._adapters.resident_models())
                     if self._adapters is not None else frozenset()),
                    self.kv_block)

    # -- multi-model adapters (docs/multimodel.md) ------------------------

    @property
    def multi_model(self) -> bool:
        return self._adapters is not None

    def load_adapter(self, model: str, pinned: bool = False) -> None:
        """Pin an adapter's weight pages resident ahead of traffic (the
        ``register_prefix`` analog for weights). At ``max_adapters``
        the least-recently-hit unpinned adapter is evicted; an
        all-pinned catalog raises."""
        if self._adapters is None:
            raise ValueError("engine has no adapter catalog (pass "
                             "adapters= to enable multi-model serving)")
        with self._sched_lock:
            self._adapters.load(model, pinned=pinned)
            self.residency_epoch += 1

    def adapter_resident(self, model: str) -> bool:
        if self._adapters is None:
            return False
        with self._sched_lock:
            return self._adapters.is_resident(model)

    def adapter_status(self) -> dict:
        """Resident set + fault/eviction counters (console endpoint)."""
        if self._adapters is None:
            return {}
        with self._sched_lock:
            return self._adapters.status()

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def health(self) -> dict:
        """The autoscaler's control inputs (docs/serving_fleet.md):
        free pool blocks, queue depth, lane occupancy, handoff and
        preemption counters — one consistent snapshot per lock."""
        with self._sched_lock:
            active = sum(1 for l in self._lane_state
                         if l.request is not None)
            parked = sum(1 for l in self._lane_state if l.parked)
            free = (self._bpool.free_count if self.kv_mode != "dense"
                    else None)
            adapters = None
            if self._adapters is not None:
                adapters = {
                    "resident_adapters": len(
                        self._adapters.resident_models()),
                    "adapter_pages": self._adapters.resident_pages(),
                    "adapter_faults": dict(self._adapters.faults),
                    "adapter_evictions": self._adapters.evictions,
                }
        out = {
            "queue_depth": self.queue_depth,
            "active_lanes": active,
            "parked_lanes": parked,
            "free_blocks": free,
            "lanes": self.lanes,
            "prefill_lanes": self.prefill_lanes,
            "handoffs": self.handoffs,
            "preempted": self.preempted,
        }
        if adapters is not None:
            # keys appear ONLY on multi-model engines: single-model
            # health dicts (and everything derived from them — replay
            # scorecards, committed bench artifacts) stay byte-identical
            out.update(adapters)
        return out

    def validate(self, prompt: Sequence[int], max_new: int) -> None:
        """Raise ValueError if the request can never fit the cache —
        callers batching several submits should validate ALL of them
        first so a bad late request doesn't strand earlier ones."""
        plen = max(len(prompt), 1)
        if plen + max_new > self.max_len:
            raise ValueError(
                f"prompt {plen} + new {max_new} exceeds cache capacity "
                f"{self.max_len}")

    def submit(self, prompt: Sequence[int], max_new: int,
               logprobs: bool = False, temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               model: str = "") -> Request:
        """Enqueue one generation; returns a Request whose ``result()``
        blocks until finished. Thread-safe. ``temperature``/``top_k``/
        ``top_p`` override the engine's GenerateConfig for THIS request
        only (each lane samples with its own request's params).
        ``model`` picks the adapter to decode under ("" / the catalog's
        base name = the base model); requires an adapter catalog and a
        registered adapter — validated HERE, in the caller's thread,
        so an unknown model 400s one request instead of reaching the
        scheduler loop."""
        self.validate(prompt, max_new)
        sampling = self.validate_sampling(temperature=temperature,
                                          top_k=top_k, top_p=top_p)
        model = self.validate_model(model)
        req = Request(prompt=list(prompt), max_new=max_new,
                      want_logprobs=logprobs, model=model, **sampling)
        if self.tracer.enabled:
            req.trace_id = self.tracer.new_trace_id()
            req._span_root = self.tracer.new_span_id()
            req._t_submit = req._t_queue = self.tracer.clock()
        if max_new <= 0:
            req._finish()          # nothing requested: empty output
            return req
        with self._cv:
            if self._stopped:
                raise RuntimeError("engine stopped")
            self._queue.append(req)
            self._cv.notify()
        return req

    def validate_model(self, model: Optional[str]) -> str:
        """Normalize + bounds-check a request's adapter id in the
        CALLER's thread (same contract as :meth:`validate_sampling`).
        Returns "" for the base model."""
        if not model:
            return ""
        if self._adapters is None:
            raise ValueError(
                f"model {model!r} requested but this engine serves only "
                "its base model (no adapter catalog configured)")
        model = self._adapters.catalog.normalize(model)
        if model and model not in self._adapters.catalog:
            raise ValueError(f"unknown model {model!r}: not in the "
                             "adapter catalog")
        return model

    def validate_sampling(self, temperature=None, top_k=None,
                          top_p=None) -> dict:
        """Bounds-check per-request sampling overrides in the CALLER's
        thread (a bad value must 400 one request, never reach the
        scheduler loop, where a raise stops the engine and cancels every
        lane). Returns the normalized dict; the server pre-validates
        every instance of a batch with this before submitting any."""
        if temperature is not None:
            temperature = float(temperature)
            if not (0.0 <= temperature < 1e4):
                raise ValueError(f"temperature out of range: {temperature}")
        if top_k is not None:
            top_k = int(top_k)
            if not (0 <= top_k <= self.config.vocab_size):
                raise ValueError(
                    f"top_k out of range [0, {self.config.vocab_size}]: "
                    f"{top_k}")
        if top_p is not None:
            top_p = float(top_p)
            if not (0.0 < top_p <= 1.0):
                raise ValueError(f"top_p out of range (0, 1]: {top_p}")
        return {"temperature": temperature, "top_k": top_k, "top_p": top_p}

    def reseed(self, seed: int) -> None:
        """Rebase the sampling stream on ``seed`` — the determinism seam
        for batch drivers (inline :meth:`run`, the RL rollout tenant):
        identical submissions after an identical ``reseed`` sample
        identical token streams. Refused while the background loop runs
        (other clients share the stream)."""
        if self._thread is not None:
            raise ValueError(
                "cannot reseed a running engine (other clients share "
                "the sampling stream)")
        with self._sched_lock:
            self._key = jax.random.PRNGKey(seed)
            if self.spec_k:
                # the speculative accept rule draws from per-request
                # host rngs (seed + admission ordinal): rebase both
                # or a reseeded sampled run would not reproduce
                self._seed = seed
                self._spec_admitted = 0

    def run(self, requests: Sequence[tuple], seed: Optional[int] = None) -> list:
        """requests: [(prompt_token_list, max_new_tokens), ...] in arrival
        order. Returns one generated-id list per request. Inline when no
        background loop is running; otherwise defers to it."""
        # validate everything up front: a bad late request must not strand
        # earlier ones in the queue
        for prompt, max_new in requests:
            self.validate(prompt, max_new)
        if seed is not None:
            self.reseed(seed)
        reqs = [self.submit(p, n) for p, n in requests]
        if self._thread is None:
            with self._sched_lock:
                try:
                    while self._step_once():
                        pass
                except BaseException:
                    # _prefill/_decode donate self._cache: an abort
                    # mid-step leaves a consumed buffer behind, and the
                    # next inline call would hit a confusing
                    # donated-buffer error. Restore invariants (mirrors
                    # SpeculativeEngine's reset-on-failure) and cancel
                    # in-flight requests so waiters unblock.
                    self._recover_locked()
                    raise
        return [r.result() for r in reqs]

    def step(self) -> bool:
        """Run ONE inline scheduler tick: admit queue heads onto free
        lanes, then one decode round across all lanes. Returns True while
        there is work left (active lanes or queued requests).

        This is the replay harness's seam: an external event-driven
        driver submits arrivals, calls ``step()`` per simulated tick, and
        advances its sim clock between calls — so every request span the
        tracer records (queue wait, TTFT) is measured in deterministic
        simulated time instead of wall time. Mutually exclusive with the
        background loop (:meth:`start`). Same abort-recovery contract as
        inline :meth:`run`: a failed step restores cache/pool invariants
        and cancels in-flight requests before re-raising."""
        if self._thread is not None:
            raise RuntimeError(
                "step() is an inline driver; stop() the background loop "
                "first")
        with self._sched_lock:
            try:
                return self._step_once()
            except BaseException:
                self._recover_locked()
                raise

    def _recover_locked(self) -> None:
        """Reinitialize the donated cache + lane state after a failed
        inline step. Caller holds ``_sched_lock`` (``_cancel_all`` cannot
        be used here: it takes the non-reentrant lock itself)."""
        # queue snapshot must hold _cv: submit() appends under _cv only,
        # so clearing under _sched_lock alone could silently drop (and
        # forever block) a concurrently submitted request
        with self._cv:
            abandoned = list(self._queue)
            self._queue.clear()
        for lane in self._lane_state:
            if lane.request is not None:
                abandoned.append(lane.request)
            lane.reset()
        for req in abandoned:
            req._finish(cancelled=True)
            self._trace_finish(req, status="error")
        if self.kv_mode in ("dense", "parity"):
            self._cache = self._place_cache(
                self.family.init_cache(self.config, self.lanes,
                                       self.max_len))
        if self.kv_mode in ("paged", "parity"):
            # the pool was donated into the failed step too: rebuild the
            # arena AND the allocator, then re-pin + re-prefill every
            # registered prefix (their blocks lived in the dead buffer)
            self._tables[:] = 0
            self._bpool = BlockPool(self.pool_blocks)
            self._pool = self._place_cache(self.family.init_block_pool(
                self.config, self.pool_blocks + 1, self.kv_block))
            entries = []
            for p in self._prefixes:
                blocks: tuple = ()
                if p.blocks:
                    # cannot fail: a fresh pool has at least as much
                    # room as when the prefix was first registered
                    blocks = tuple(self._bpool.alloc(len(p.blocks)))
                    self._fill_prefix_blocks(
                        blocks, list(p.key)[:len(blocks) * self.kv_block])
                entries.append(_Prefix(key=p.key, plen=p.plen,
                                       stored=p.stored, blocks=blocks,
                                       pinned=p.pinned, model=p.model))
            self._prefixes = entries
            if self._adapters is not None:
                # adapter pins lived in the dead pool too; re-pin them
                # into the fresh one (every lane incref died with its
                # lane above, so active counts legitimately restart)
                self._adapters.rebuild(self._bpool)
            self.residency_epoch += 1
        if self.spec_k:
            # the draft cache is donated into _d_decode/_d_prefill too
            self._d_cache = self._place_d_cache(
                self.dfam.init_cache(self.dcfg, self.lanes,
                                     self.max_len))
        self._cur = np.zeros((self.lanes, 1), np.int32)
        self._pos = np.zeros((self.lanes,), np.int32)

    def start(self) -> "ContinuousBatchingEngine":
        """Run the scheduler on a background thread (HTTP serving mode)."""
        def loop():
            import logging
            while True:
                with self._cv:
                    while (not self._stopped and not self._queue
                           and not self._active()):
                        self._cv.wait()
                    if self._stopped:
                        return
                try:
                    with self._sched_lock:
                        self._step_once()
                except Exception:  # noqa: BLE001 — a dead loop must not
                    # strand waiters: fail every request and stop accepting
                    logging.getLogger("kubedl_tpu.serving").exception(
                        "batching scheduler failed; cancelling requests")
                    with self._cv:
                        self._stopped = True
                    self._cancel_all()
                    return

        self._thread = threading.Thread(target=loop, name="kubedl-batching",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the background loop; queued and in-flight requests are
        cancelled (their waiters unblock with a RuntimeError)."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._cancel_all()

    def _cancel_all(self) -> None:
        with self._sched_lock:
            abandoned = list(self._queue)
            self._queue.clear()
            for i, lane in enumerate(self._lane_state):
                if lane.request is not None:
                    abandoned.append(lane.request)
                self._free_lane(i)
            for req in abandoned:
                req._finish(cancelled=True)
                # the root span must still land: children with no
                # recorded parent read as orphans forever, and failed
                # requests are exactly the ones worth debugging
                self._trace_finish(req, status="error")

    def pool_stats(self) -> dict:
        """Pool occupancy + scheduler counters for /metrics. Dense mode
        reports only the mode (no pool exists). Takes the scheduler lock:
        allocator state mutates under it on the scheduler thread, and an
        unsynchronized scrape could catch the refcount dict mid-resize
        (RuntimeError) or report mutually inconsistent numbers."""
        out = {"kv_mode": self.kv_mode, "peak_active": self.peak_active}
        if self.kv_mode == "dense":
            return out
        with self._sched_lock:
            bp = self._bpool
            out.update({
                "kv_block": self.kv_block,
                "blocks_total": bp.total,
                "blocks_free": bp.free_count,
                "blocks_used": bp.used_count,
                "blocks_shared": bp.shared_count,
                "blocks_pinned": sum(len(p.blocks)
                                     for p in self._prefixes),
                "block_allocs": bp.allocs,
                "preempted": self.preempted,
                "handoffs": self.handoffs,
                "prefill_tokens": self.prefill_tokens_total,
            })
            if self._adapters is not None:
                # multi-model only: single-model scrapes stay identical
                out.update({
                    "adapter_pages": self._adapters.resident_pages(),
                    "adapter_peak_pages": self._adapters.peak_pages,
                    "adapter_faults": self._adapters.faults_total(),
                    "adapter_evictions": self._adapters.evictions,
                })
        return out

    # -- scheduler --------------------------------------------------------

    def _trace_finish(self, req: Request, status: str = "ok") -> None:
        """Record the request's decode span and its root span (the whole
        submit→finish window). No-op for untraced requests."""
        if not (self.tracer.enabled and req.trace_id and req._span_root):
            return
        now = self.tracer.clock()
        if req._t_decode:
            self.tracer.record(
                "request.decode", req._t_decode, now,
                trace_id=req.trace_id, parent_id=req._span_root,
                component="serving",
                attributes={"tokens": len(req.tokens)})
        self.tracer.record(
            "serving.request", req._t_submit, now,
            trace_id=req.trace_id, span_id=req._span_root,
            component="serving", status=status,
            attributes={"tokens": len(req.tokens),
                        "promptTokens": len(req.prompt),
                        "preemptions": req._preempts,
                        **({"model": req.model} if req.model else {}),
                        **({"error": req.error} if req.error else {})})
        req._span_root = ""   # finalized: never re-record this root

    def _active(self) -> bool:
        return any(l.request is not None for l in self._lane_state)

    # -- paged-pool bookkeeping (host side; caller holds _sched_lock) -----

    def _blocks_for(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.kv_block)

    def _ensure_blocks(self, i: int, last_pos: int) -> bool:
        """Grow lane i's block table to cover a write at ``last_pos``.
        False when the pool is dry (caller preempts or waits)."""
        lane = self._lane_state[i]
        need = last_pos // self.kv_block + 1
        have = len(lane.blocks)
        if have >= need:
            return True
        got = self._bpool.alloc(need - have)
        if got is None:
            return False
        self._tables[i, have:need] = got
        lane.blocks.extend(got)
        return True

    def _free_lane(self, i: int) -> None:
        """Detach lane i's request and return its pool blocks (shared
        prefix blocks drop one refcount; private ones free). The lane's
        adapter weight-page share releases here too — and ONLY here, so
        every finish/cancel/preempt/handoff-cancel path decrefs the
        adapter exactly once."""
        lane = self._lane_state[i]
        if lane.blocks:
            self._bpool.decref(lane.blocks)
            lane.blocks = []
            self._tables[i, :] = 0
        if lane.adapter_blocks:
            self._adapters.release(lane.adapter, lane.adapter_blocks)
            lane.adapter_blocks = []
        lane.adapter = ""
        lane.request = None
        lane.parked = False
        lane.parked_at = 0.0

    def _handoff(self, src: int, dst: int) -> None:
        """Move a freshly-prefilled request from prefill lane ``src`` to
        decode lane ``dst``: the block-table row, the cursor token, and
        the position move; the KV itself never does — the table entries
        reference the same shared-pool blocks (docs/serving_fleet.md).
        Caller holds ``_sched_lock``."""
        s, d = self._lane_state[src], self._lane_state[dst]
        req = s.request
        d.request, d.pos, d.remaining = req, s.pos, s.remaining
        d.blocks, s.blocks = s.blocks, []
        # the adapter refcount MOVES with the block-table row: the
        # decode lane inherits the prefill lane's weight-page share
        # (no incref/decref pair — the share itself transfers)
        d.adapter, s.adapter = s.adapter, ""
        d.adapter_blocks, s.adapter_blocks = s.adapter_blocks, []
        d.parked = False
        self._tables[dst, :] = self._tables[src, :]
        self._tables[src, :] = 0
        self._cur[dst, 0] = self._cur[src, 0]
        self._pos[dst] = self._pos[src]
        s.request = None
        s.pos = 0
        s.remaining = 0
        s.parked = False
        self.handoffs += 1
        if self.tracer.enabled and req.trace_id:
            now = self.tracer.clock()
            self.tracer.record(
                "request.handoff", s.parked_at or now, now,
                trace_id=req.trace_id, parent_id=req._span_root,
                component="serving",
                attributes={"fromLane": src, "toLane": dst,
                            "blocks": len(d.blocks)})
            # decode genuinely starts on the decode lane, not at the
            # prefill lane's first-token emit — the decode span must
            # not swallow the parked wait
            req._t_decode = now
        s.parked_at = 0.0

    def _try_handoffs(self) -> None:
        """Hand each parked prefill lane's request to a free decode
        lane, FIFO over lane index (admission fills lanes in index
        order, so lower index == earlier arrival). A cancelled request
        parked mid-handoff is freed here — its blocks decref exactly
        like a cancelled decode lane's, so a cancel between prefill and
        handoff leaks nothing."""
        for src in range(self.prefill_lanes):
            lane = self._lane_state[src]
            if not lane.parked:
                continue
            req = lane.request
            if req.cancel_requested:
                self._free_lane(src)
                req._finish()
                self._trace_finish(req)
                continue
            dst = next((j for j in range(self.prefill_lanes, self.lanes)
                        if self._lane_state[j].request is None), None)
            if dst is None:
                return           # every decode lane busy: wait parked
            self._handoff(src, dst)

    def _preempt_for_blocks(self) -> bool:
        """Pool ran dry mid-step: evict the lowest-progress active lane
        back to the queue HEAD (resumed later by re-prefilling prompt +
        generated-so-far — greedy-deterministic, so the resumed stream
        continues exactly). Returns False when nothing is evictable."""
        cands = [(len(l.request.tokens), i)
                 for i, l in enumerate(self._lane_state)
                 if l.request is not None]
        if not cands:
            return False
        _, victim = min(cands)
        req = self._lane_state[victim].request
        self._free_lane(victim)
        self.preempted += 1
        if self.tracer.enabled and req.trace_id:
            now = self.tracer.clock()
            if req._t_decode:
                self.tracer.record(
                    "request.decode", req._t_decode, now,
                    trace_id=req.trace_id, parent_id=req._span_root,
                    component="serving",
                    attributes={"tokens": len(req.tokens),
                                "preempted": True})
                req._t_decode = 0.0
            self.tracer.record(
                "request.preempted", now, now, trace_id=req.trace_id,
                parent_id=req._span_root, component="serving",
                attributes={"tokens": len(req.tokens)})
            req._t_queue = now
            req._preempts += 1
        with self._cv:
            self._queue.appendleft(req)
        return True

    def _grow_active(self, extra: int) -> None:
        """Ensure every active lane's table covers a write at
        ``pos + extra``, preempting lowest-progress lanes while the pool
        is dry (the growing lane itself can be the victim — it is then
        simply requeued). Parked lanes are skipped: they write nothing
        until their handoff lands, and growing them early could trigger
        a needless preemption."""
        for i, lane in enumerate(self._lane_state):
            while lane.request is not None and not lane.parked and \
                    not self._ensure_blocks(i, lane.pos + extra):
                if not self._preempt_for_blocks():
                    break

    def _assert_parity(self, dense_logits, paged_logits, what: str,
                       rows: Optional[list] = None) -> None:
        """Parity mode's contract: on every ACTIVE lane the paged path's
        logits pick the same tokens as the dense path's (and track them
        numerically). Dead-lane rows are garbage in both layouts and
        legitimately differ."""
        act = rows if rows is not None else \
            [i for i, l in enumerate(self._lane_state)
             if l.request is not None]
        if not act:
            return
        ld = np.asarray(dense_logits, np.float32)[act]
        lp = np.asarray(paged_logits, np.float32)[act]
        if not np.array_equal(ld.argmax(-1), lp.argmax(-1)) or \
                not np.allclose(ld, lp, rtol=1e-4, atol=1e-5):
            raise AssertionError(
                f"KV parity violation in {what}: dense and paged logits "
                f"diverge (max abs diff {np.abs(ld - lp).max():.3e})")

    def _lane_sampling(self, req: Request):
        """(temperature, top_k, top_p) for a request — per-request
        overrides over the engine GenerateConfig."""
        gen = self.gen
        t = gen.temperature if req.temperature is None else req.temperature
        k_ = gen.top_k if req.top_k is None else req.top_k
        p_ = gen.top_p if req.top_p is None else req.top_p
        return t, k_, p_

    def _spec_round_k(self) -> int:
        """Draft lookahead this round: spec_k clamped so every ACTIVE
        lane's [k+1] verify chunk (and the draft backfill at pos+k) stays
        inside the cache. The chunk shape is compiled per k, so at most
        spec_k shapes exist."""
        space = min(self.max_len - 1 - l.pos
                    for l in self._lane_state if l.request is not None)
        return min(self.spec_k, space)

    def _spec_round(self, k: int) -> None:
        """One speculative round for EVERY lane: k draft proposals each
        (k batched [lanes, 1] draft steps), one [lanes, k+1] target
        verify, per-lane acceptance — greedy prefix-match for greedy
        lanes (output token-identical to the non-speculative engine),
        the ``spec_accept`` distribution rule for sampled lanes (each
        emitted token's marginal distribution is exactly the target's).
        Cache bookkeeping per lane is pointer math: rejected slots stay
        causally invisible until overwritten (the single-sequence
        engine's rewind argument, per row)."""
        from .engine import filtered_probs, spec_accept
        gen = self.gen
        lanes_n = self.lanes
        active = np.asarray([l.request is not None
                             for l in self._lane_state])
        # dead lanes still compute (uniform SPMD) but their writes must
        # stay in range: park them at position 0 — those slots are fully
        # rewritten by the next admission's bucket prefill
        pos = np.where(active, self._pos, 0).astype(np.int32)
        cur = self._cur.copy()
        sampled = [l.request is not None
                   and self._lane_sampling(l.request)[0] > 0.0
                   for l in self._lane_state]
        drafts = np.zeros((lanes_n, k), np.int32)
        dprobs = [[None] * k for _ in range(lanes_n)]
        dcur = cur.copy()
        for j in range(k):
            d_logits, self._d_cache = self._d_decode(
                self.dparams, self._d_cache, jnp.asarray(dcur),
                jnp.asarray(pos + j))
            dl = np.asarray(d_logits, np.float32)
            greedy_next = dl.argmax(-1)
            for i, lane in enumerate(self._lane_state):
                if sampled[i]:
                    t, tk, tp = self._lane_sampling(lane.request)
                    p = filtered_probs(dl[i], t, tk, tp)
                    drafts[i, j] = int(
                        lane.request._spec_rng.choice(len(p), p=p))
                    dprobs[i][j] = p
                else:
                    drafts[i, j] = int(greedy_next[i])
            dcur[:, 0] = drafts[:, j]
        chunk = np.concatenate([cur, drafts], axis=1)
        chunk_j, pos_j = jnp.asarray(chunk), jnp.asarray(pos)
        if self.kv_mode == "dense":
            t_logits, self._cache = self._spec_verify(
                self.params, self._cache, chunk_j, pos_j)
        elif self.kv_mode == "paged":
            t_logits, self._pool = self._spec_verify_p(
                self.params, self._pool, chunk_j, pos_j,
                jnp.asarray(self._tables))
        else:
            t_logits, self._cache = self._spec_verify(
                self.params, self._cache, chunk_j, pos_j)
            t_logits_p, self._pool = self._spec_verify_p(
                self.params, self._pool, chunk_j, pos_j,
                jnp.asarray(self._tables))
            self._assert_parity(t_logits, t_logits_p, "spec_verify")
        tl = np.asarray(t_logits, np.float32)       # [lanes, k+1, V]
        # draft backfill: the k-th proposal joined sequences that accept
        # fully but its KV never entered the draft cache (it was only an
        # output); one batched step ingests it at pos+k for every lane —
        # lanes that accepted less overwrite that slot before it is ever
        # attendable, so the unconditional write is safe and uniform
        _, self._d_cache = self._d_decode(
            self.dparams, self._d_cache, jnp.asarray(drafts[:, k - 1:k]),
            jnp.asarray(pos + k))
        for i, lane in enumerate(self._lane_state):
            req = lane.request
            if req is None:
                continue
            if req.cancel_requested:
                self._free_lane(i)
                req._finish()
                self._trace_finish(req)
                continue
            if sampled[i]:
                t, tk, tp = self._lane_sampling(req)
                tpro = [filtered_probs(tl[i, j], t, tk, tp)
                        for j in range(k + 1)]
                accepted, nxt = spec_accept(drafts[i], dprobs[i], tpro,
                                            req._spec_rng)
            else:
                targets = tl[i].argmax(-1)          # [k+1]
                accepted = 0
                while accepted < k and \
                        drafts[i, accepted] == targets[accepted]:
                    accepted += 1
                nxt = int(targets[accepted])
            emitted = [int(x) for x in drafts[i, :accepted]] + [int(nxt)]
            lp_rows = None
            if req.want_logprobs:
                # full-softmax log p of each emitted token under the
                # verify logits of ITS slot — identical numbers to the
                # per-token decode path
                row = tl[i, :len(emitted)]
                row = row - row.max(-1, keepdims=True)
                lp_all = row - np.log(np.exp(row).sum(-1, keepdims=True))
                lp_rows = [float(lp_all[j, emitted[j]])
                           for j in range(len(emitted))]
            finished = False
            pushed = 0
            for j, tok in enumerate(emitted):
                req._push(tok, lp_rows[j] if lp_rows else None)
                pushed += 1
                lane.pos += 1
                lane.remaining -= 1
                if (lane.remaining <= 0 or hit_stop(req.tokens, gen)
                        or lane.pos + 1 >= self.max_len):
                    finished = True
                    break
            # acceptance accounting clamped to tokens actually EMITTED
            # (ADVICE r5): a lane stopping mid-chunk at eos/max_new only
            # counts the drafts that reached the client — drafts past
            # the stop were never consulted, so counting all k would
            # skew the /metrics rate low for short completions. When the
            # bonus/resample token was reached (pushed > accepted), all
            # k drafts were judged and count in full.
            acc_inc = min(pushed, accepted)
            prop_inc = k if pushed > accepted else pushed
            self.stats.proposed += prop_inc
            self.stats.accepted += acc_inc
            self.lane_stats[i].proposed += prop_inc
            self.lane_stats[i].accepted += acc_inc
            self._cur[i, 0] = req.tokens[-1]
            self._pos[i] = lane.pos
            if finished:
                self._free_lane(i)
                req._finish()
                self._trace_finish(req)

    def _prefill_dense(self, lane_idx: int, seq: list, start: int):
        """Chunked dense-slab prefill of ``seq[start:]`` into one lane
        (``_chunked_prefill`` owns the chunking rule)."""
        def step(toks, pos0, n):
            logits, self._cache = self._prefill(self.params, self._cache,
                                                jnp.asarray(toks),
                                                jnp.int32(lane_idx),
                                                jnp.int32(pos0),
                                                jnp.int32(n))
            return logits

        return self._chunked_prefill(step, seq, start)

    def _prefill_paged(self, lane_idx: int, seq: list, start: int):
        """Chunked paged prefill of ``seq[start:]`` through lane
        ``lane_idx``'s block table (grown by the caller)."""
        row = jnp.asarray(self._tables[lane_idx])

        def step(toks, pos0, n):
            logits, self._pool = self._prefill_p(
                self.params, self._pool, jnp.asarray(toks), row,
                jnp.int32(pos0), jnp.int32(n))
            return logits

        return self._chunked_prefill(step, seq, start)

    def _admit(self, lane_idx: int) -> bool:
        """Admit the queue head onto free lane ``lane_idx``. Returns
        False when admission must stop this tick: queue empty, or (paged
        modes) the head needs more free blocks than the pool has — FCFS,
        the head is never skipped, it waits at the front until lanes
        finish and free blocks. A head that can NEVER be admitted (pool
        too small after prefix pins, nothing running to preempt) is
        failed with a descriptive error instead of wedging the queue."""
        gen = self.gen
        with self._cv:
            while self._queue and self._queue[0].cancel_requested:
                # cancelled while queued: never pay the prefill
                r = self._queue.popleft()
                r._finish()
                self._trace_finish(r)
            if not self._queue:
                return False
            req = self._queue[0]
            shared, start_p = [], 0
            if self.kv_mode != "dense":
                if req.model and self._adapters is not None:
                    # the adapter must be resident BEFORE the request's
                    # first tick: a cold one faults its weight pages in
                    # through the pool here (counted per model). Runs
                    # ahead of the KV watermark so the pages it takes
                    # are visible to the free-count check below.
                    v0 = self._adapters.version
                    pages, faulted = self._adapters.ensure(req.model)
                    if self._adapters.version != v0:
                        # fault-in OR evictions along the way: either
                        # way the resident set moved — invalidate the
                        # router's cached snapshot of this replica
                        self.residency_epoch += 1
                    if faulted:
                        self.adapter_fault_pages_step += len(pages)
                    if pages is None:
                        if not self._active():
                            # nothing running will ever free pages —
                            # the adapter can never fit (pool too small
                            # after prefix + pinned-adapter pins)
                            self._queue.popleft()
                            spec = self._adapters.catalog.spec(req.model)
                            req.error = (
                                f"adapter {req.model} needs "
                                f"{spec.pages} weight pages but only "
                                f"{self._bpool.free_count} blocks are "
                                f"free and no unpinned adapter is "
                                f"evictable (pool {self.pool_blocks})")
                            req._finish(cancelled=True)
                            self._trace_finish(req, status="error")
                            return True
                        return False
                # admission watermark: the prompt's private blocks plus
                # headroom must be free, or the head waits (degrading to
                # fewer concurrent lanes instead of OOM/preempt-thrash).
                # The match is reused by the attach path below — nothing
                # can change it in between (we hold _sched_lock, which
                # register_prefix also needs).
                seq = (req.prompt or [0]) + req.tokens
                shared, start_p = self._match_prefix_blocks(
                    seq, model=req.model)
                need = self._blocks_for(len(seq)) - len(shared)
                free = self._bpool.free_count
                if not self._active():
                    # nothing running: nothing will ever free blocks
                    # (only prefix pins hold them) — the request either
                    # fits its WHOLE generation now or never will
                    total = self._blocks_for(min(
                        len(seq) + req.max_new - len(req.tokens),
                        self.max_len)) - len(shared)
                    if total > free:
                        self._queue.popleft()
                        req.error = (
                            f"request needs {total} free KV blocks but "
                            f"only {free} are free (pool "
                            f"{self.pool_blocks}, "
                            f"{sum(len(p.blocks) for p in self._prefixes)}"
                            " pinned by prefixes)")
                        req._finish(cancelled=True)
                        self._trace_finish(req, status="error")
                        return True
                elif free < need + self.headroom_blocks:
                    return False
            self._queue.popleft()
        t_admit = (self.tracer.clock()
                   if self.tracer.enabled and req.trace_id else 0.0)
        # attach BEFORE the prefill work: a failure mid-prefill must leave
        # the request visible to _recover_locked (a popped-but-unattached
        # request would never be cancelled and its waiter would hang)
        lane = self._lane_state[lane_idx]
        lane.request = req
        if req.model and self._adapters is not None:
            # bind the lane to the (now-resident) adapter: incref its
            # weight pages for the life of the lane. The residency gate
            # above ran under the same _sched_lock hold, so nothing can
            # have evicted it in between.
            lane.adapter = req.model
            lane.adapter_blocks = self._adapters.attach(req.model)
        # resume-aware: a preempted request re-prefills prompt PLUS the
        # tokens it already streamed, then continues its budget — the
        # client-visible stream never replays
        prior = len(req.tokens)
        seq = (req.prompt or [0]) + req.tokens
        plen = len(seq)
        logits = logits_p = None
        prefill_from = 0      # first position actually prefilled (traces)
        if self.kv_mode in ("dense", "parity"):
            if self.kv_mode == "dense":
                stored, start = self._match_prefix(seq, model=req.model)
                prefill_from = start
                if stored is not None:
                    self._cache = self._load_prefix(self._cache, stored,
                                                    jnp.int32(lane_idx))
            else:
                # parity's dense shadow prefills from 0: prefix KV lives
                # only in the pool there, and a full prefill writes
                # bit-identical KV anyway (position-exact chunks)
                start = 0
            logits = self._prefill_dense(lane_idx, seq, start)
        if self.kv_mode in ("paged", "parity"):
            if shared:
                self._bpool.incref(shared)
                lane.blocks = list(shared)
                self._tables[lane_idx, :len(shared)] = shared
            # the admission gate reserved capacity under the same
            # scheduler lock, so this cannot fail
            self._ensure_blocks(lane_idx, plen - 1)
            prefill_from = start_p
            logits_p = self._prefill_paged(lane_idx, seq, start_p)
            if self.kv_mode == "parity":
                self._assert_parity(logits, logits_p, "prefill", rows=[0])
            else:
                logits = logits_p
        self.prefill_tokens_step += plen - prefill_from
        self.prefill_tokens_total += plen - prefill_from
        self._key, sub = jax.random.split(self._key)
        t, k_, p_ = self._lane_sampling(req)
        if t <= 0.0:
            # default/greedy: the one static-arg compile (plain argmax)
            first = int(self._sample(logits, sub, 0.0, 0, 1.0)[0])
        else:
            # TRACED params: distinct client triples must not each pay a
            # fresh XLA trace of a static-arg sampler
            first = int(sample_logits_many(
                logits, sub, jnp.asarray([t], jnp.float32),
                jnp.asarray([k_], jnp.int32),
                jnp.asarray([p_], jnp.float32))[0])
        req._push(first, float(token_logprobs(
            logits, jnp.asarray([first]))[0]) if req.want_logprobs else None)
        lane.pos = plen
        lane.remaining = req.max_new - prior - 1
        self._cur[lane_idx, 0] = first
        self._pos[lane_idx] = plen
        if self.tracer.enabled and req.trace_id:
            now_t = self.tracer.clock()
            self.tracer.record(
                "request.queue", req._t_queue, t_admit,
                trace_id=req.trace_id, parent_id=req._span_root,
                component="serving",
                attributes={"resumed": prior > 0, "lane": lane_idx})
            self.tracer.record(
                "request.prefill", t_admit, now_t,
                trace_id=req.trace_id, parent_id=req._span_root,
                component="serving",
                attributes={"tokens": plen - prefill_from,
                            "lane": lane_idx,
                            "sharedBlocks": len(shared),
                            **({"model": req.model} if req.model
                               else {})})
            req._t_decode = now_t
        if lane.remaining <= 0 or hit_stop(req.tokens, gen):
            self._free_lane(lane_idx)    # finished in prefill
            req._finish()
            self._trace_finish(req)
        elif self.spec_k:
            # draft prefills the FULL sequence into ITS lane (prefix KV
            # blocks are target-model state; the draft pays its own
            # prefill so its cache is exact and proposals stay sharp —
            # a stale draft cache would only cost acceptance, but a
            # deterministic one keeps rounds reproducible). The draft
            # cache stays a dense slab in every kv mode: it is small,
            # and paging it would double the host bookkeeping for no
            # capacity win.
            def d_step(toks, pos0, n):
                logits, self._d_cache = self._d_prefill(
                    self.dparams, self._d_cache, jnp.asarray(toks),
                    jnp.int32(lane_idx), jnp.int32(pos0), jnp.int32(n))
                return logits

            self._chunked_prefill(d_step, seq, 0)
            # per-request host rng drives the sampled accept rule; a
            # RESUMED request keeps its rng (the stream must continue,
            # not restart)
            if not hasattr(req, "_spec_rng"):
                req._spec_rng = np.random.default_rng(
                    self._seed + 1000003 * self._spec_admitted)
                self._spec_admitted += 1
        return True

    def _step_once(self) -> bool:
        """Fill free lanes, run one decode tick (or a speculative round
        when a draft model is configured). Returns False once idle.

        Disaggregated mode (``prefill_lanes`` > 0): handoffs land
        first (a decode lane freed last tick takes the oldest parked
        request), admissions target prefill lanes only, and a
        just-prefilled request parks for handoff — so a long prompt's
        chunked prefill never occupies a decode lane, and the decode
        tick's cadence is independent of prefill work."""
        gen = self.gen
        self.prefill_tokens_step = 0
        self.adapter_fault_pages_step = 0
        stalled = False
        if self.prefill_lanes:
            self._try_handoffs()
            for i in range(self.prefill_lanes):
                lane = self._lane_state[i]
                while self._queue and lane.request is None:
                    if not self._admit(i):
                        # FCFS: the head is waiting on pool capacity —
                        # every other free lane would stall on it too
                        stalled = True
                        break
                    if lane.request is not None:
                        # prefilled, first token emitted: park for the
                        # block-table handoff (an immediately-free
                        # decode lane takes it now, re-opening this
                        # prefill lane within the same tick)
                        lane.parked = True
                        if self.tracer.enabled and lane.request.trace_id:
                            lane.parked_at = self.tracer.clock()
                        self._try_handoffs()
                if stalled or not self._queue:
                    break
        else:
            for i, lane in enumerate(self._lane_state):
                while self._queue and lane.request is None:
                    if not self._admit(i):
                        # FCFS: the head is waiting on pool capacity —
                        # every other free lane would stall on it too
                        stalled = True
                        break
                if stalled or not self._queue:
                    break
        self.peak_active = max(self.peak_active, sum(
            1 for l in self._lane_state if l.request is not None))
        if not self._active():
            return bool(self._queue)
        if self.prefill_lanes and not any(
                l.request is not None and not l.parked
                for l in self._lane_state):
            # only parked work left: nothing may decode this tick (the
            # parked KV must not move before its handoff), but work
            # remains — the next tick's handoff pass places it
            return True
        if self.spec_k:
            k = self._spec_round_k()
            if k >= 1:
                if self.kv_mode != "dense":
                    # the verify chunk writes pos..pos+k; grow (and
                    # preempt if dry) BEFORE the uniform device round
                    self._grow_active(k)
                    if not self._active():
                        return bool(self._queue)
                self._spec_round(k)
                return True
            # near the cache cap a verify chunk no longer fits: finish
            # with plain single-token ticks (same as the single-sequence
            # engine's tail loop)
        if self.kv_mode != "dense":
            self._grow_active(0)
            if not self._active():
                return bool(self._queue)
        # one decode tick for every lane (dead lanes compute garbage).
        # Parked prefill lanes are masked to the garbage sink: their
        # tables hold LIVE blocks awaiting handoff, and an unmasked
        # decode write would corrupt the position their decode lane is
        # about to continue from.
        parked_rows = ([i for i, l in enumerate(self._lane_state)
                        if l.parked] if self.prefill_lanes else [])
        pos_np = self._pos
        tbl_np = self._tables if self.kv_mode != "dense" else None
        if parked_rows:
            pos_np = self._pos.copy()
            pos_np[parked_rows] = 0
            tbl_np = self._tables.copy()
            tbl_np[parked_rows, :] = 0
        cur, pos = jnp.asarray(self._cur), jnp.asarray(pos_np)
        if self.kv_mode == "dense":
            logits, self._cache = self._decode(self.params, self._cache,
                                               cur, pos)
        elif self.kv_mode == "paged":
            logits, self._pool = self._decode_p(
                self.params, self._pool, cur, pos,
                jnp.asarray(tbl_np))
        else:
            logits, self._cache = self._decode(self.params, self._cache,
                                               cur, pos)
            logits_p, self._pool = self._decode_p(
                self.params, self._pool, cur, pos,
                jnp.asarray(self._tables))
            self._assert_parity(logits, logits_p, "decode")
        if self.spec_k:
            # near-cap fallback ticks must keep the DRAFT cache in
            # lockstep (ingest the same token at the same position the
            # target just did) — otherwise later spec rounds on other
            # lanes attend stale draft KV and acceptance silently decays
            _, self._d_cache = self._d_decode(
                self.dparams, self._d_cache, jnp.asarray(self._cur),
                jnp.asarray(self._pos))
        self._key, sub = jax.random.split(self._key)

        def lane_param(attr, default):
            return [getattr(l.request, attr, None)
                    if l.request is not None and
                    getattr(l.request, attr) is not None else default
                    for l in self._lane_state]

        temps = lane_param("temperature", gen.temperature)
        active_temps = [t for t, l in zip(temps, self._lane_state)
                        if l.request is not None]
        if all(t <= 0.0 for t in active_temps):
            # free lanes carry the engine default but emit nothing —
            # only live requests decide the fast path
            # all-greedy tick (the default deployment): one argmax, not
            # two full-vocab sorts per decoded token
            nxt = np.asarray(self._sample(logits, sub, 0.0, 0, 1.0))
        else:
            nxt = np.asarray(sample_logits_many(
                logits, sub, jnp.asarray(temps, jnp.float32),
                jnp.asarray(lane_param("top_k", gen.top_k), jnp.int32),
                jnp.asarray(lane_param("top_p", gen.top_p), jnp.float32)))
        lane_lps = None
        if any(l.request is not None and l.request.want_logprobs
               for l in self._lane_state):
            lane_lps = np.asarray(token_logprobs(logits,
                                                 jnp.asarray(nxt)))
        for i, lane in enumerate(self._lane_state):
            req = lane.request
            if req is None or lane.parked:
                continue                 # parked: awaiting handoff
            tok = int(nxt[i])
            req._push(tok, float(lane_lps[i]) if req.want_logprobs else None)
            lane.pos += 1
            lane.remaining -= 1
            self._cur[i, 0] = tok
            self._pos[i] = lane.pos
            if (req.cancel_requested or lane.remaining <= 0
                    or hit_stop(req.tokens, gen)
                    or lane.pos + 1 >= self.max_len):
                self._free_lane(i)   # lane freed for the next arrival
                req._finish()
                self._trace_finish(req)
        return True
