"""Controllee expectations cache.

Behavioral port of the reference's expectations mechanism
(``pkg/job_controller/expectations.go:31-68``, itself the upstream k8s
controller pattern): after issuing N creates/deletes, a controller expects to
*observe* N watch events before trusting its (possibly stale) cache again.
``satisfied()`` gates reconciliation; observations arrive from the watch
stream. With the in-memory API server the cache is never stale, but against
a real apiserver (REST client mode) this is what stops reconcile storms from
double-creating pods — including the AlreadyExists trap documented at
reference ``pkg/job_controller/pod.go:282-307``.
"""

from __future__ import annotations

import threading
import time


class Expectations:
    TIMEOUT = 5 * 60.0  # stale expectations expire, like upstream

    def __init__(self, clock=time.time, timeout: float = TIMEOUT):
        self._clock = clock
        self.timeout = timeout
        self._lock = threading.Lock()
        # key -> [pending_creations, pending_deletions, timestamp]
        self._exp: dict[str, list] = {}

    @staticmethod
    def pods_key(job_key: str, replica_type: str) -> str:
        return f"{job_key}/{replica_type.lower()}/pods"

    @staticmethod
    def services_key(job_key: str, replica_type: str) -> str:
        return f"{job_key}/{replica_type.lower()}/services"

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            e = self._exp.setdefault(key, [0, 0, self._clock()])
            e[0] += n
            e[2] = self._clock()

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            e = self._exp.setdefault(key, [0, 0, self._clock()])
            e[1] += n
            e[2] = self._clock()

    def creation_observed(self, key: str) -> None:
        self._observed(key, 0)

    def deletion_observed(self, key: str) -> None:
        self._observed(key, 1)

    def _observed(self, key: str, idx: int) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e and e[idx] > 0:
                e[idx] -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            e = self._exp.get(key)
            if e is None:
                return True
            if e[0] <= 0 and e[1] <= 0:
                return True
            if self._clock() - e[2] > self.timeout:
                # expired: a watch event was dropped or never came.
                # Clear the stale record too — leaving the phantom counts
                # behind would poison every future expect_* on this key
                # (each new expectation would start from the missed debt)
                del self._exp[key]
                return True
            return False

    def expires_in(self, key: str) -> float:
        """Seconds until an unsatisfied expectation on ``key`` expires
        (0 when none is pending) — what a blocked reconcile should
        requeue after, so recovery from a dropped watch event does not
        depend on some unrelated event happening to arrive."""
        with self._lock:
            e = self._exp.get(key)
            if e is None:
                return 0.0
            return max(0.0, self.timeout - (self._clock() - e[2]))

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._exp.pop(key, None)

    def delete_prefix(self, job_key: str) -> None:
        """Drop every expectation of a deleted job (all replica types)."""
        prefix = job_key + "/"
        with self._lock:
            for k in [k for k in self._exp if k.startswith(prefix)]:
                del self._exp[k]
