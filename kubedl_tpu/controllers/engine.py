"""The generic job reconciliation engine.

Behavioral port of the reference's core engine (``pkg/job_controller/
job.go:71-370``, ``pod.go:237-448``, ``service.go:197-322``,
``status.go:19-41``) with the pod/service symmetry collapsed into one typed
child-resource diff loop and the GPU-era placement replaced by TPU slice
rendering (``kubedl_tpu.tpu.placement``).

One ``JobEngine`` instance serves one workload kind (its
``WorkloadController`` plugin provides the framework seams); the engine owns:

* pod/service diff loops with stable ``{job}-{rt}-{index}`` naming,
* restart semantics (ExitCode retryability, restart-policy mapping),
* backoff limit / active deadline / TTL-after-finished / clean-pod policy,
* gang lifecycle (one PodGroup per TPU slice, all-or-nothing),
* DAG stage gating (``dag_sched.go:29-67``) and the AIMaster gate,
* job condition state machine + replica status counting,
* launch-delay metrics and lifecycle events,
* ModelVersion creation on success (``job.go:500-541``) via hook.
"""

from __future__ import annotations

import copy
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api import common as c
from ..api.common import JobStatus, ReplicaSpec, RunPolicy
from ..core import meta as m
from ..core.apiserver import (AlreadyExists, APIServer, Conflict, NotFound,
                              ServerError)
from ..core.events import Recorder, TYPE_NORMAL, TYPE_WARNING
from ..core.manager import Reconciler, Request, Result
from ..metrics import JobMetrics
from ..platform.cache import CacheError, reconcile_job_cache
from ..platform.codesync import inject_code_sync_init_containers
from ..platform.models import add_model_path_env, build_model_version_spec
from ..platform.tensorboard import reconcile_tensorboard
from ..scheduling import queue as qsched
from ..scheduling.gang import GangScheduler, is_gang_admitted
from ..tpu import placement as pl
from ..trace import (ENV_TRACEPARENT, NOOP_TRACER, JobLifecycleTracer,
                     derive_phase, format_traceparent, job_trace_context)
from ..utils import status as st
from ..utils import train
from ..utils.retry import RetryPolicy, restart_delay, retry_transient
from . import hostnetwork as hn
from .elastic import ANNOTATION_WORLD_SIZE
from .expectations import Expectations
from .interface import TPUPolicy, WorkloadController

log = logging.getLogger("kubedl_tpu.engine")


@dataclass
class EngineConfig:
    enable_gang_scheduling: bool = True
    enable_dag_scheduling: bool = True
    #: slice-scheduler admission gate (docs/scheduling.md): when True, no
    #: pod is created until every PodGroup of the job carries the
    #: scheduler's ``Admitted`` condition — the job waits in its queue
    #: (``Queuing`` condition) instead of racing pods into the cluster
    gate_on_gang_admission: bool = False
    dns_domain: str = ""
    default_ttl_seconds: Optional[int] = None
    #: (base, size) for hostnetwork random ports (reference main.go:69
    #: --hostnetwork-port-range, default [20000, 30000))
    hostnetwork_port_range: tuple = hn.DEFAULT_PORT_RANGE
    #: HostNetWithHeadlessSvc gate: keep headless services even in
    #: hostnetwork mode (reference features.go:36-40)
    hostnet_with_headless_svc: bool = False
    #: transient-error (5xx/timeout) retry bounds for every api write the
    #: engine issues; ``retry_sleep`` is injectable so deterministic tests
    #: advance a fake clock instead of blocking
    retry_policy: RetryPolicy = field(default_factory=RetryPolicy)
    retry_sleep: Callable = time.sleep
    #: slice-atomic failover backoff: round r waits a decorrelated-jitter
    #: delay in [base, cap] (docs/failover.md has the formula); the round
    #: counter resets after ``restart_backoff_reset`` seconds of stability
    restart_backoff_base: float = 10.0
    restart_backoff_cap: float = 300.0
    restart_backoff_reset: float = 600.0
    #: seeds the retry/backoff jitter; None (default) takes OS entropy so
    #: operator replicas de-correlate — pin in tests for reproducibility
    backoff_jitter_seed: Optional[int] = None
    #: how long an unobserved create/delete expectation blocks reconciles
    #: before it is declared lost (dropped watch event) and cleared
    expectation_timeout: float = Expectations.TIMEOUT
    #: admission-gate dropped-event net: a Queuing job re-reconciles this
    #: often even if no PodGroup admission event arrives. The event path
    #: (PodGroup watch) does the real work — at fleet scale (hundreds of
    #: queued jobs) a tight poll is a thundering herd, so the cluster
    #: replay widens it; 5s keeps the historical single-job snappiness
    gate_requeue_s: float = 5.0
    #: concurrency-elastic slices (docs/elastic.md, TPUElasticSlices
    #: gate): jobs declaring ``schedulingPolicy.minSlices`` run on any
    #: admitted width in [min, numSlices]; scheduler shrink preemptions
    #: of surplus slices become restart-free world reconfigurations
    #: driven through the 2-phase checkpoint protocol instead of
    #: whole-gang failover. False (default) = byte-identical pre-elastic
    #: engine behavior.
    elastic_slices: bool = False


@dataclass
class _ReplicaPlan:
    """Resolved TPU shape for one job (or None for CPU-only jobs).

    ``offsets[rtype]`` maps a TPU replica type to its base in the *global*
    TPU process index space (reconcile order over TPU types, cumulative
    replicas) — e.g. Master(1) + Worker(3) on a 4-host slice gives Master
    process 0 and Workers processes 1..3, preserving the reference's
    Master/Worker shape while keeping one flat SPMD index space.
    """
    policy: Optional[TPUPolicy] = None
    slice_spec: object = None
    num_slices: int = 1
    offsets: dict = field(default_factory=dict)
    global_dns: list = field(default_factory=list)  # hostname per global id


@dataclass
class _ElasticPlan:
    """One reconcile's concurrency-elastic view of the gang
    (docs/elastic.md): which slice ids are admitted-and-live
    (``active``), which the scheduler marked for in-place shedding
    (``leaving``), and the slice set the job is RECORDED as running on
    (the ``kubedl.io/elastic-slices`` annotation; None before the first
    world forms). ``active != recorded`` is what triggers a
    reconfiguration. Built only when the active width is at or above the
    gang's min — below the floor, pre-elastic whole-gang semantics
    apply unchanged."""
    min_slices: int
    num_slices: int
    active: tuple            # sorted admitted, non-preempted slice ids
    leaving: tuple           # sorted admitted-but-preempted slice ids
    recorded: Optional[tuple]

    @property
    def exempt(self) -> tuple:
        """Slices the slice-atomic failover must NOT treat as disrupted:
        everything outside the active set (leaving slices are being shed
        in place; pending slices have no world to tear down)."""
        act = set(self.active)
        return tuple(s for s in range(self.num_slices) if s not in act)


def _gang_slice_id(pg_name: str, job_name: str) -> Optional[int]:
    """Slice id encoded in a multislice gang's PodGroup name
    (``gang_name``: ``{job}-slice-{sid}``), or None for foreign names."""
    prefix = job_name + "-slice-"
    if pg_name.startswith(prefix):
        try:
            return int(pg_name[len(prefix):])
        except ValueError:
            return None
    return 0 if pg_name == job_name else None


@dataclass
class _FailoverDecision:
    """What ``_slice_failover`` decided this round: ``fail`` (permanent
    exit code — job dies via ``_fail_permanently``), ``wait`` (disruption
    seen but the backoff gate holds; the ``frozen`` slices must not be
    touched by the diff loops while reconciliation otherwise proceeds), or
    ``restart`` (slice torn down; recreation rides the next reconcile)."""
    action: str
    requeue: float = 0.0
    message: str = ""
    frozen: tuple = ()


class JobEngine(Reconciler):
    def __init__(self, api: APIServer, controller: WorkloadController,
                 config: Optional[EngineConfig] = None,
                 metrics: Optional[JobMetrics] = None,
                 recorder: Optional[Recorder] = None,
                 gang: Optional[GangScheduler] = None,
                 tracer=None, telemetry=None, elastic_metrics=None):
        self.api = api
        self.controller = controller
        self.config = config or EngineConfig()
        self.metrics = metrics or JobMetrics()
        self.recorder = recorder or Recorder(api)
        self.gang = gang
        #: fleet telemetry bundle (docs/telemetry.md): goodput harvest at
        #: job retirement + the straggler scan driver; None when the
        #: FleetTelemetry gate is off (every hook is one None check)
        self.telemetry = telemetry
        #: kubedl_elastic_* families (docs/elastic.md); None when the
        #: TPUElasticSlices gate is off
        self.elastic_metrics = elastic_metrics
        #: span recorder (docs/tracing.md); the shared disabled tracer by
        #: default, so every trace call below is one attribute check
        self.tracer = tracer if tracer is not None else NOOP_TRACER
        self.lifecycle = JobLifecycleTracer(self.tracer)
        self.expectations = Expectations(
            clock=api.now, timeout=self.config.expectation_timeout)
        self._jitter_rng = random.Random(self.config.backoff_jitter_seed)
        self.kind = controller.kind
        # PodGroup admission flips must re-trigger the owning job when the
        # scheduler gate is on (PodGroups are controller-owned by the job)
        self.owns = ("Pod", "Service") + (
            ("PodGroup",) if self.config.gate_on_gang_admission else ())
        self._job_states: dict[str, str] = {}  # job uid -> running|pending
        self._tb_jobs: set = set()  # uids that have carried a TB annotation
        self._tb_reap_checked: set = set()  # uids whose TB reap ran at least once
        #: pod uids whose deletionTimestamp has been counted against the
        #: deletion expectation (finalizer-held pods emit several MODIFIED
        #: events while deleting; only the transition counts)
        self._deletion_seen: set = set()
        #: job uid -> outage start (first restart-round stamp of the
        #: current outage); popped into the restart-MTTR histogram on the
        #: first all-active reconcile after it
        self._mttr_start: dict[str, float] = {}
        api.watch(self._observe)

    def _retry(self, fn):
        """Run one api write with bounded decorrelated-jitter retries on
        transient (5xx/timeout) errors; anything else propagates."""
        return retry_transient(
            fn, self.config.retry_policy, retry_on=(ServerError,),
            rng=self._jitter_rng, sleep=self.config.retry_sleep,
            on_retry=lambda n, delay, e: log.warning(
                "transient api error (retry %d in %.3fs): %s", n, delay, e))

    # ------------------------------------------------------------------
    # watch observation (expectations bookkeeping + deletion metrics)
    # ------------------------------------------------------------------

    def _observe(self, event_type: str, obj: dict) -> None:
        kd = m.kind(obj)
        if kd == self.kind:
            # incremental running/pending gauges (avoids a cluster-wide list
            # per reconcile) + per-job bookkeeping cleanup on deletion
            uid = m.uid(obj)
            if event_type == "DELETED":
                self.metrics.deleted.inc(kind=self.kind)
                self._job_states.pop(uid, None)
                self.lifecycle.forget(uid)
                if self.telemetry is not None:
                    self.telemetry.forget(uid)
                self._tb_jobs.discard(uid)
                self._tb_reap_checked.discard(uid)
                self._mttr_start.pop(uid, None)
                self.expectations.delete_prefix(m.key(obj))
            else:
                s = JobStatus.from_dict(obj.get("status"))
                if st.is_finished(s):
                    self._job_states.pop(uid, None)
                else:
                    self._job_states[uid] = "running" if st.is_running(s) else "pending"
            states = list(self._job_states.values())
            self.metrics.running.set(states.count("running"), kind=self.kind)
            self.metrics.pending.set(states.count("pending"), kind=self.kind)
            return
        if kd not in ("Pod", "Service"):
            return
        ref = m.get_controller_ref(obj)
        if not ref or ref.get("kind") != self.kind:
            return
        job_key = f"{m.namespace(obj)}/{ref['name']}"
        rt = m.meta(obj).get("labels", {}).get(c.LABEL_REPLICA_TYPE, "")
        key_fn = (Expectations.pods_key if kd == "Pod" else Expectations.services_key)
        if event_type == "ADDED":
            self.expectations.creation_observed(key_fn(job_key, rt))
        elif event_type == "DELETED":
            if m.uid(obj) not in self._deletion_seen:
                self.expectations.deletion_observed(key_fn(job_key, rt))
            self._deletion_seen.discard(m.uid(obj))
        elif event_type == "MODIFIED" and m.is_deleting(obj) \
                and m.uid(obj) not in self._deletion_seen:
            # a finalizer-held pod (preempt protector) never emits DELETED
            # until a reconcile releases the finalizer — but an unsatisfied
            # deletion expectation would block exactly that reconcile. The
            # deletionTimestamp appearing proves our delete call landed, so
            # count it once per pod uid here (the reference escapes this
            # deadlock by GC'ing finalizers outside ReconcileJobs,
            # pytorchjob_controller.go:335-355); the DELETED branch skips
            # uids already counted so a pod is never observed twice
            self._deletion_seen.add(m.uid(obj))
            self.expectations.deletion_observed(key_fn(job_key, rt))

    # ------------------------------------------------------------------
    # top-level reconcile
    # ------------------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        if self.telemetry is not None:
            # rate-limited straggler scan rides the reconcile stream (the
            # detector itself bounds how often a scan actually runs)
            self.telemetry.maybe_scan(self.api.now())
        job = self.api.try_get(self.kind, req.namespace, req.name)
        if job is None or m.is_deleting(job):
            return None
        self.controller.set_defaults(job)
        raw_specs = m.get_in(job, "spec", self.controller.replica_specs_field_name,
                             default={}) or {}
        # model-output volume + KUBEDL_MODEL_PATH env (reference job.go:471-498)
        mv_spec = m.get_in(job, "spec", "modelVersion")
        if mv_spec:
            add_model_path_env(raw_specs, mv_spec)
        replicas = self.controller.get_replica_specs(job)
        run_policy = self.controller.get_run_policy(job)
        job_key = m.key(job)

        # stale-cache gate (reference SatisfyExpectations, job.go:129 area).
        # When blocked, requeue for when the expectation would expire: if
        # the awaited watch event was dropped, nothing else is guaranteed
        # to re-trigger this reconcile, and the expiry path in
        # Expectations.satisfied can only run when somebody calls it
        for rt in replicas:
            for key in (Expectations.pods_key(job_key, rt),
                        Expectations.services_key(job_key, rt)):
                if not self.expectations.satisfied(key):
                    return Result(requeue_after=max(
                        0.01, self.expectations.expires_in(key)))

        status = JobStatus.from_dict(job.get("status"))
        old_status = copy.deepcopy(status)

        # scheduled jobs convert themselves into a Cron wrapper
        # (reference job.go:372-455)
        if run_policy.cron_policy and run_policy.cron_policy.schedule:
            self._reconcile_cron(job, run_policy)
            return None

        if not status.conditions:
            st.update_job_conditions(
                status, c.JOB_CREATED, st.REASON_JOB_CREATED,
                f"{self.kind} {req.name} is created.", now=self.api.now())
            self.metrics.created.inc(kind=self.kind)
            self.recorder.event(job, TYPE_NORMAL, st.REASON_JOB_CREATED,
                                f"{self.kind} {req.name} is created.")
        if self.tracer.enabled:
            self._ensure_traceparent(job)

        pods = self.get_pods_for_job(job)
        services = self.get_services_for_job(job)

        # ---- backoff limit / active deadline ---------------------------
        # preempted pods (DisruptionTarget) are a voluntary disruption, not
        # the job's fault: spot/preemptible TPU training must survive any
        # number of them without burning backoffLimit budget
        failed_now = sum(1 for p in pods if _pod_phase(p) == c.POD_FAILED
                         and not _has_disruption_target(p))
        prev_failed = sum(rs.failed for rs in status.replica_statuses.values())
        exceeds, failure_msg = False, ""
        if run_policy.backoff_limit is not None:
            if failed_now > prev_failed and not st.is_finished(status):
                # counted in job.status so an operator restart cannot
                # forget a job's failure history (round-2 VERDICT missing
                # #3; reference reconstructs from restartCounts). Terminal
                # jobs never count again: the terminal path skips
                # _reconcile_pods, so prev_failed stays stale and an
                # unguarded increment would re-fire on every status event
                status.failure_rounds += 1
            restarts = _total_restart_count(pods)
            if (status.failure_rounds > run_policy.backoff_limit
                    or restarts > run_policy.backoff_limit):
                exceeds = True
                failure_msg = (f"{self.kind} {req.name} has failed because it "
                               f"has reached the specified backoff limit")
        deadline_requeue = 0.0
        if not exceeds and run_policy.active_deadline_seconds is not None \
                and status.start_time:
            elapsed = self.api.now() - _parse_ts(status.start_time)
            if elapsed >= run_policy.active_deadline_seconds:
                exceeds = True
                failure_msg = (f"{self.kind} {req.name} has failed because it "
                               f"was active longer than specified deadline")
            else:
                deadline_requeue = run_policy.active_deadline_seconds - elapsed

        # ---- terminal path ---------------------------------------------
        if st.is_finished(status) or exceeds:
            return self._finish(job, replicas, run_policy, status, old_status,
                                pods, exceeds, failure_msg)

        # git/GCS code-sync init containers (reference job.go:110), after the
        # terminal gate so a bad config fails the job but still lets the next
        # pass reach _finish and clean up pods
        try:
            inject_code_sync_init_containers(job, raw_specs)
        except ValueError as e:
            return self._fail_permanently(
                job, f"invalid code-sync config: {e}",
                "InvalidCodeSyncConfig", status, old_status)

        # dataset cache: create CacheBackend, wait for its PVC, mount it
        # (reference job.go:117-132 → job_controller.go:202-315)
        cache_spec = m.get_in(job, "spec", "cacheBackend")
        if cache_spec:
            try:
                cache_requeue = reconcile_job_cache(self.api, job, cache_spec,
                                                    raw_specs, status)
            except CacheError as e:
                return self._fail_permanently(job, str(e), "CacheFailed",
                                              status, old_status)
            if cache_requeue:
                self._flush_status(job, status, old_status)
                return Result(requeue_after=cache_requeue)
        replicas = self.controller.get_replica_specs(job)

        try:
            plan = self._resolve_tpu(job, replicas)
        except ValueError as e:
            # invalid slice shape is a permanent config error: fail the job
            # loudly instead of retrying forever
            return self._fail_permanently(job, f"invalid tpuPolicy: {e}",
                                          "InvalidTPUPolicy", status, old_status)

        # ---- gang: one PodGroup per slice ------------------------------
        if self.config.enable_gang_scheduling and self.gang is not None:
            gang_ann = qsched.gang_annotations(
                job, run_policy.scheduling_policy, plan.slice_spec,
                plan.num_slices if plan.policy is not None else 1)
            if self.tracer.enabled:
                # the scheduler attaches its queue-wait / preemption spans
                # to the job's trace via this PodGroup annotation
                gang_ann = {**gang_ann,
                            c.ANNOTATION_TRACEPARENT:
                                format_traceparent(*job_trace_context(job))}
            self._retry(lambda: self.gang.create_gang(
                job, self._gang_min_members(replicas, plan),
                run_policy.scheduling_policy, annotations=gang_ann))

        # ---- concurrency-elastic context (docs/elastic.md) -------------
        # built BEFORE failover: slices the scheduler is shedding in
        # place (Preempted PodGroups with >= min survivors) must be
        # exempt from the whole-gang disruption scan, or the shrink
        # would degrade into exactly the full restart it exists to avoid
        elastic = None
        if (self.config.elastic_slices and plan.policy is not None
                and plan.num_slices > 1
                and self.config.gate_on_gang_admission
                and self.config.enable_gang_scheduling
                and self.gang is not None):
            elastic = self._elastic_plan(job, run_policy, plan)

        # ---- slice-atomic failover (TPU jobs only) ---------------------
        # A gang-scheduled slice whose member was preempted/killed is a
        # dead world: the PJRT coordinator topology is fixed at startup,
        # so recovery replaces the whole slice, never a single pod
        slice_wait, slice_frozen = None, ()
        if plan.policy is not None:
            dec = self._slice_failover(job, status, old_status, pods,
                                       replicas, plan,
                                       exempt=(elastic.exempt
                                               if elastic is not None
                                               else ()))
            if dec is not None:
                if dec.action == "fail":
                    return self._fail_permanently(
                        job, dec.message, "PermanentExitCode",
                        status, old_status)
                if dec.action == "restart":
                    # recount replica statuses from the (pre-teardown) pods
                    # before the early return: leaving them stale would make
                    # the failure-round accounting above re-count the same
                    # failed pod next round
                    self._recount_replica_statuses(status, replicas, pods)
                    self._trace_phase(job, status, pods, replicas)
                    flushed = self._flush_status(job, status, old_status)
                    # deletion events re-trigger reconcile; a failed flush
                    # still needs a timed nudge
                    return None if flushed else Result(requeue_after=1.0)
                # wait: the disrupted slices are frozen until the backoff
                # gate opens, but reconciliation continues so *other*
                # slices (e.g. one torn down just before this disruption)
                # still get their pods recreated on time
                slice_wait, slice_frozen = dec.requeue, dec.frozen
                slice_wait_msg = dec.message

        # ---- slice-scheduler admission gate ----------------------------
        # pods are never created ahead of admission: the job sits Queuing
        # until the scheduler stamps every PodGroup Admitted. Placed after
        # the failover block on purpose — a preempted slice must finish its
        # teardown (which deletes the PodGroup via readmit_slice) before
        # the gate sees the recreated, un-admitted gang and parks the job
        if self.config.gate_on_gang_admission \
                and self.config.enable_gang_scheduling and self.gang is not None:
            # an elastic gang at or above its min width runs NOW on the
            # admitted subset (docs/elastic.md); pending surplus slices
            # regrow later instead of parking the whole job
            waiting = [] if elastic is not None \
                else [m.name(g) for g in self.gang.get_gangs(job)
                      if not is_gang_admitted(g)]
            if waiting:
                st.update_job_conditions(
                    status, c.JOB_QUEUING, st.REASON_JOB_QUEUING,
                    f"{self.kind} {req.name} waiting for gang admission "
                    f"({len(waiting)} PodGroup(s) pending)",
                    now=self.api.now())
                self._recount_replica_statuses(status, replicas, pods)
                self._trace_phase(job, status, pods, replicas)
                flushed = self._flush_status(job, status, old_status)
                # admission flips re-trigger via the PodGroup watch; the
                # timed requeue is the safety net for a dropped event (a
                # failed flush polls faster)
                return Result(requeue_after=self.config.gate_requeue_s
                              if flushed else 1.0)
            for cond in status.conditions:
                # admitted: the queue wait is over even though pods are
                # only now being created (Running flips it too, but the
                # gap between admission and first pod running should not
                # read as still-queued)
                if cond.type == c.JOB_QUEUING and cond.status == "True":
                    cond.status = "False"
                    cond.message = "gang admitted"
                    # the Admitted phase marks the queue-exit instant; pod
                    # creation (below, same pass) opens PodsCreated
                    self.lifecycle.transition(
                        job, "Admitted", self.api.now(),
                        created_at=_parse_ts(
                            m.meta(job).get("creationTimestamp")))

        # ---- restart-free world reconfiguration (docs/elastic.md) ------
        # shrink: leaving slices tear down AFTER the checkpoint ack;
        # grow: new slices' pods are created only after the ack, so the
        # survivors reshard from a state the whole new world agrees on
        reconf_requeue = None
        elastic_allowed: Optional[set] = None
        if elastic is not None:
            reconf_requeue, elastic_allowed = self._elastic_reconfigure(
                job, status, plan, elastic, pods)

        # ---- elastic scaling hook --------------------------------------
        # scale_out/scale_in may return a requeue delay while waiting to
        # confirm in-place restarts (the CRR-status analog)
        elastic_requeue = None
        if st.is_running(old_status) and \
                self.controller.enable_elastic_scaling(job, run_policy):
            if self.controller.checkpoint_if_necessary(job, pods) \
                    and m.generation(job) > 1:
                total = sum(int(rs.replicas or 1) for rs in replicas.values())
                latest = _replicas_at_generation(pods, m.generation(job))
                if total > latest:
                    elastic_requeue = self.controller.scale_out(
                        job, replicas, pods, services)
                elif total < latest:
                    elastic_requeue = self.controller.scale_in(
                        job, replicas, pods, services)

        # ---- per-replica-type diff loops -------------------------------
        # a pending (backoff-gated) slice restart counts as restarting so
        # _update_job_status keeps the job Restarting instead of Failed
        restart = [slice_wait is not None]
        if elastic_allowed is not None:
            # pods exist only on the allowed slice set: the recorded
            # world plus, once a reconfiguration completes, the grown one
            slice_frozen = tuple(sorted(
                set(slice_frozen)
                | {s for s in range(plan.num_slices)
                   if s not in elastic_allowed}))
        # hostnetwork: replica -> live port, re-learned every round so
        # service targetPorts track fail-overed pods (reference pod.go:337-340)
        hostnet_ports: Optional[dict] = \
            {} if hn.enable_hostnetwork(job) else None
        for rtype in self._orders(replicas):
            spec = replicas.get(rtype)
            if spec is None:
                continue
            # AIMaster gate (reference job.go:293-298): AIMaster is always
            # first in _orders, so breaking here never starves it
            if (c.REPLICA_AIMASTER in replicas and rtype != c.REPLICA_AIMASTER
                    and not _aimaster_ready(pods)):
                break
            if (self.config.enable_dag_scheduling and spec.depend_on
                    and not self._dag_ready(pods, spec.depend_on)):
                continue
            try:
                self._reconcile_pods(job, status, pods, rtype, spec, replicas,
                                     run_policy, plan, restart, hostnet_ports,
                                     frozen_slices=slice_frozen)
            except ValueError as e:
                return self._fail_permanently(
                    job, f"invalid {self.kind} spec: {e}", "InvalidJobSpec",
                    status, old_status)
            if self.controller.needs_service(rtype, job):
                self._reconcile_services(job, services, rtype, spec,
                                         hostnet_ports)

        self._update_job_status(job, replicas, status, restart[0], pods)
        if slice_wait is not None:
            # the surviving members of the frozen slice look healthy, so
            # _update_job_status just promoted Running — but their PJRT
            # world is dead; the honest state until the gate opens is
            # Restarting (Running and Restarting are mutually exclusive)
            st.update_job_conditions(status, c.JOB_RESTARTING,
                                     st.REASON_JOB_RESTARTING,
                                     slice_wait_msg, now=self.api.now())
        self.controller.on_job_running(job)
        tb_requeue = self._reconcile_tb(job, status, replicas)

        # ---- launch-delay metrics (job.go:339-356) ---------------------
        created_at = _parse_ts(m.meta(job).get("creationTimestamp"))
        if st.is_created(old_status) and st.is_running(status) and created_at:
            self.metrics.first_pod_launch_delay.observe(
                self.api.now() - created_at, kind=self.kind)
        total = sum(int(rs.replicas or 1) for rs in replicas.values())
        if (sum(rs.active for rs in status.replica_statuses.values()) == total
                and sum(rs.active for rs in old_status.replica_statuses.values()) < total
                and not st.is_restarting(old_status) and created_at):
            self.metrics.all_pods_launch_delay.observe(
                self.api.now() - created_at, kind=self.kind)
            # TPU analog: gang (PodGroup) creation -> whole slice running
            if self.gang is not None:
                gang_ts = [_parse_ts(m.meta(g).get("creationTimestamp"))
                           for g in self.gang.get_gangs(job)]
                gang_ts = [t for t in gang_ts if t]
                if gang_ts:
                    self.metrics.gang_to_all_running.observe(
                        self.api.now() - min(gang_ts), kind=self.kind)
                # rendezvous-ready timestamp: every gang pod reports
                # Running, so the PJRT world can form — the event's
                # timestamp bounds rendezvous latency for traces and
                # humans alike instead of leaving it inferred
                self.recorder.event(
                    job, TYPE_NORMAL, st.REASON_RENDEZVOUS_READY,
                    f"all {total} gang pod(s) of {self.kind} {req.name} "
                    f"are running; rendezvous can complete")
        # restart-MTTR: first disruption of the outage (marked when
        # _slice_failover stamps a restart round, or when an elastic
        # reconfiguration is requested) -> every replica of the CURRENT
        # world active again. Consecutive restart rounds extend one
        # outage window. For elastic jobs the expected count is the
        # allowed width's pods, not the full declared shape.
        uid = m.uid(job)
        eff_total = total
        if elastic_allowed is not None and plan.policy is not None:
            eff_total = total - plan.slice_spec.num_hosts * (
                plan.num_slices - len(elastic_allowed))
        if (eff_total and uid in self._mttr_start
                and sum(rs.active
                        for rs in status.replica_statuses.values())
                == eff_total):
            self.metrics.restart_mttr.observe(
                self.api.now() - self._mttr_start.pop(uid), kind=self.kind)

        self._trace_phase(job, status, pods, replicas)
        flushed = self._flush_status(job, status, old_status)
        requeues = [r for r in (deadline_requeue, tb_requeue, elastic_requeue,
                                reconf_requeue, slice_wait)
                    if r and r > 0]
        if not flushed:
            requeues.append(1.0)  # status write kept failing: try again soon
        if requeues:
            return Result(requeue_after=min(requeues))
        return None

    def _reconcile_tb(self, job, status: JobStatus, replicas) -> Optional[float]:
        """TensorBoard sync with a cheap common-case skip: jobs that never
        carried the annotation don't pay the reap lookups — but each uid
        pays them at least once, so TB resources created before an operator
        restart (when ``_tb_jobs`` starts empty) still get reaped after the
        annotation is removed."""
        uid = m.uid(job)
        has_cfg = c.ANNOTATION_TENSORBOARD_CONFIG in m.annotations(job)
        had = has_cfg or uid in self._tb_jobs or uid not in self._tb_reap_checked
        if has_cfg:
            self._tb_jobs.add(uid)
        r = reconcile_tensorboard(self.api, job, status,
                                  self._tb_master_spec(replicas),
                                  recorder=self.recorder, had_config=had)
        if not has_cfg:
            self._tb_jobs.discard(uid)
            self._tb_reap_checked.add(uid)
        return r

    def _tb_master_spec(self, replicas) -> dict:
        """The replica template a TensorBoard pod derives from: the master's
        when present, else the first in reconcile order."""
        masters = self.controller.master_replica_types(replicas)
        order = masters + [rt for rt in self._orders(replicas)
                           if rt not in masters]
        for rt in order:
            spec = replicas.get(rt)
            if spec is not None and spec.template:
                return {"template": spec.template}
        return {"template": {}}

    def _fail_permanently(self, job, msg: str, reason: str,
                          status: Optional[JobStatus] = None,
                          old_status: Optional[JobStatus] = None) -> None:
        """Fail the job on a permanent config error (no retry would fix it).
        Idempotent: a job already failed records nothing new. Pass the
        round's live status/old_status to keep its mutations; otherwise they
        are re-read from the object."""
        if status is None:
            status = JobStatus.from_dict(job.get("status"))
            old_status = copy.deepcopy(status)
        if st.is_failed(status):
            return None
        self.recorder.event(job, TYPE_WARNING, reason, msg)
        st.update_job_conditions(status, c.JOB_FAILED, st.REASON_JOB_FAILED,
                                 msg, now=self.api.now())
        if status.completion_time is None:
            status.completion_time = m.rfc3339(self.api.now())
        self.metrics.failed.inc(kind=self.kind)
        self._trace_phase(job, status, attrs={"reason": reason})
        if not self._flush_status(job, status, old_status):
            return Result(requeue_after=1.0)
        return None

    # ------------------------------------------------------------------
    # tracing (docs/tracing.md) — every hook is a no-op unless enabled
    # ------------------------------------------------------------------

    def _trace_phase(self, job, status: JobStatus, pods=None, replicas=None,
                     attrs: Optional[dict] = None) -> None:
        """Report the job's current lifecycle phase to the span recorder
        (the lifecycle tracer turns phase *changes* into spans)."""
        if not self.tracer.enabled:
            return
        phase = derive_phase(status, pods, replicas, st, m)
        attributes = dict(attrs or {})
        if phase == "Restarting":
            attributes.setdefault("restartRound", status.restart_rounds)
            attributes.setdefault("restartCount", status.restart_count)
        self.lifecycle.transition(
            job, phase, self.api.now(), attributes=attributes,
            created_at=_parse_ts(m.meta(job).get("creationTimestamp")))

    def _ensure_traceparent(self, job) -> None:
        """Stamp the job with its (UID-derived) traceparent annotation so
        clients and out-of-process tools see the trace id. Best-effort:
        the derivation is deterministic, so a failed patch only loses the
        annotation's visibility, never span correlation."""
        if c.ANNOTATION_TRACEPARENT in m.get_annotations(job):
            return
        value = format_traceparent(*job_trace_context(job))
        try:
            self.api.patch_merge(
                self.kind, m.namespace(job), m.name(job),
                {"metadata": {"annotations": {
                    c.ANNOTATION_TRACEPARENT: value}}})
        except (Conflict, NotFound, ServerError):
            pass

    # ------------------------------------------------------------------
    # terminal path
    # ------------------------------------------------------------------

    def _finish(self, job, replicas, run_policy: RunPolicy, status: JobStatus,
                old_status: JobStatus, pods, exceeds: bool,
                failure_msg: str) -> Optional[Result]:
        self._delete_pods_and_services(job, run_policy, pods)
        if exceeds:
            self.recorder.event(job, TYPE_NORMAL, st.REASON_JOB_FAILED, failure_msg)
            if status.completion_time is None:
                status.completion_time = m.rfc3339(self.api.now())
            st.update_job_conditions(status, c.JOB_FAILED, st.REASON_JOB_FAILED,
                                     failure_msg, now=self.api.now())
            if not st.is_failed(old_status):
                self.metrics.failed.inc(kind=self.kind)

        if st.is_succeeded(status):
            for rs in status.replica_statuses.values():
                rs.succeeded += rs.active
                rs.active = 0
            self._create_model_version(job, pods, status)

        if self.config.enable_gang_scheduling and self.gang is not None:
            self.gang.delete_gang(job)

        self.controller.on_job_finished(job, pods)
        # TensorBoard outlives the job for its own TTL (tensorboard.go:99-135)
        tb_requeue = self._reconcile_tb(job, status, replicas)
        self._trace_phase(job, status, pods, replicas)
        if self.telemetry is not None:
            # the lifecycle root span is closed by the _trace_phase above,
            # so the full trace is harvestable — goodput decomposition +
            # throughput-profile observations. Idempotent per job UID
            # (terminal reconciles repeat on TTL requeues)
            self.telemetry.on_job_terminal(job)
        flushed = self._flush_status(job, status, old_status)

        requeues = [tb_requeue] if tb_requeue else []
        if not flushed:
            requeues.append(1.0)
        # TTL-after-finished cleanup (reference job.go:596-620)
        ttl = run_policy.ttl_seconds_after_finished
        if ttl is None:
            ttl = self.config.default_ttl_seconds
        if ttl is not None:
            finished_at = _parse_ts(status.completion_time) or self.api.now()
            remaining = finished_at + ttl - self.api.now()
            if remaining <= 0:
                try:
                    self.api.delete(self.kind, m.namespace(job), m.name(job))
                except NotFound:
                    pass
                return None
            requeues.append(remaining)
        if requeues:
            return Result(requeue_after=min(requeues))
        return None

    def _delete_pods_and_services(self, job, run_policy: RunPolicy, pods) -> None:
        policy = run_policy.clean_pod_policy or c.CLEAN_POD_RUNNING
        if policy == c.CLEAN_POD_NONE:
            return
        for pod in pods:
            if policy == c.CLEAN_POD_RUNNING and _pod_phase(pod) != c.POD_RUNNING:
                continue
            try:
                self._retry(lambda p=pod: self.api.delete(
                    "Pod", m.namespace(p), m.name(p)))
            except NotFound:
                pass
            # services share the pod's name (reference job.go:60-64)
            try:
                self._retry(lambda p=pod: self.api.delete(
                    "Service", m.namespace(p), m.name(p)))
            except NotFound:
                pass

    def _create_model_version(self, job, pods, status: JobStatus) -> None:
        """On success, emit a ModelVersion CR (reference job.go:500-541)."""
        mv_spec = m.get_in(job, "spec", "modelVersion")
        if not mv_spec or status.model_version_name:
            return
        name = f"mv-{m.name(job)}-{m.uid(job)[:5]}"
        mv = m.new_obj("model.kubedl.io/v1alpha1", "ModelVersion", name,
                       m.namespace(job),
                       spec=build_model_version_spec(job, mv_spec, pods))
        m.set_controller_ref(mv, job)
        try:
            self.api.create(mv)
        except AlreadyExists:
            pass
        status.model_version_name = name

    # ------------------------------------------------------------------
    # children: pods
    # ------------------------------------------------------------------

    def get_pods_for_job(self, job) -> list:
        return self._claim(job, "Pod")

    def get_services_for_job(self, job) -> list:
        return self._claim(job, "Service")

    def _claim(self, job, kind: str) -> list:
        """List + adopt orphans matching our selector (reference
        ``pod.go:532-554`` / ``service_ref_manager.go``)."""
        sel = self.gen_labels(m.name(job))
        out = []
        for obj in self.api.list(kind, m.namespace(job), selector=sel):
            ref = m.get_controller_ref(obj)
            if ref is None and not m.is_deleting(job):
                lbl = m.get_labels(obj)
                if not (lbl.get(c.LABEL_REPLICA_TYPE)
                        and lbl.get(c.LABEL_REPLICA_INDEX, "").isdigit()):
                    continue  # orphan we couldn't manage; leave it alone
                # list() hands out shared snapshots: copy before adopting
                obj = m.deep_copy(obj)
                m.set_controller_ref(obj, job)
                try:
                    obj = self.api.update(obj)
                except (Conflict, NotFound):
                    continue
            elif ref is not None and ref.get("uid") != m.uid(job):
                continue  # controlled by someone else
            out.append(obj)
        return out

    def gen_labels(self, job_name: str) -> dict:
        return {
            c.LABEL_GROUP_NAME: self.controller.group_name,
            c.LABEL_JOB_NAME: job_name.replace("/", "-"),
        }

    def _reconcile_pods(self, job, status: JobStatus, all_pods, rtype: str,
                        spec: ReplicaSpec, replicas, run_policy: RunPolicy,
                        plan: _ReplicaPlan, restart: list,
                        hostnet_ports: Optional[dict] = None,
                        frozen_slices: tuple = ()) -> None:
        rt = rtype.lower()
        tpu_managed = plan.policy is not None and rtype in plan.offsets

        def slice_of(index: int):
            if not tpu_managed:
                return None
            return (plan.offsets[rtype] + index) // plan.slice_spec.num_hosts
        pods = [p for p in all_pods
                if m.labels(p).get(c.LABEL_REPLICA_TYPE) == rt]
        num = int(spec.replicas or 1)
        status.replica_statuses.setdefault(rtype, c.ReplicaStatus())
        rs = status.replica_statuses[rtype]
        rs.active = rs.succeeded = rs.failed = rs.evicted = 0

        by_index: dict[int, list] = {}
        job_key = m.key(job)
        for p in pods:
            idx_str = m.labels(p).get(c.LABEL_REPLICA_INDEX, "")
            if not idx_str.isdigit():
                # a pod of ours with a broken index is unmanageable: delete it
                # or it skews failure counting forever while staying invisible
                self.recorder.event(job, TYPE_WARNING, "DeletePod",
                                    f"pod {m.key(p)} has invalid replica-index "
                                    f"label {idx_str!r}; deleting")
                self._delete_pod(job_key, rtype, p)
                continue
            by_index.setdefault(int(idx_str), []).append(p)
        for index in range(max([num] + [i + 1 for i in by_index])):
            slice_pods = by_index.get(index, [])
            if len(slice_pods) > 1:
                log.warning("too many pods for %s %s %d", job_key, rt, index)
            elif not slice_pods:
                if index >= num:
                    continue
                if slice_of(index) in frozen_slices:
                    # this slice's teardown is waiting out restart backoff:
                    # recreating members piecemeal would patch pods into
                    # the dead world the wait exists to replace
                    continue
                self.expectations.expect_creations(
                    Expectations.pods_key(job_key, rtype), 1)
                try:
                    self._create_pod(job, rtype, index, spec, replicas,
                                     run_policy, plan, hostnet_ports)
                except AlreadyExists:
                    # the AlreadyExists trap (reference pod.go:282-307):
                    # balance the expectation we just set or reconcile stalls
                    self.expectations.creation_observed(
                        Expectations.pods_key(job_key, rtype))
                except ValueError:
                    # permanent config error from set_cluster_spec (e.g. two
                    # PyTorch masters): balance the expectation, then let
                    # reconcile() fail the job loudly
                    self.expectations.creation_observed(
                        Expectations.pods_key(job_key, rtype))
                    raise
                except ServerError:
                    # transient retries exhausted: balance the expectation
                    # (nothing was created) and surface the error so the
                    # manager requeues with backoff
                    self.expectations.creation_observed(
                        Expectations.pods_key(job_key, rtype))
                    raise
                continue
            else:
                pod = slice_pods[0]
                if hostnet_ports is not None:
                    port = hn.get_pod_hostnetwork_port(
                        pod, self.controller.default_container_name,
                        self.controller.default_port_name)
                    if port is not None:
                        hostnet_ports[(rt, index)] = port
                if index >= num:  # scale-in: out-of-range index
                    if not m.is_deleting(pod):
                        self.recorder.event(
                            job, TYPE_NORMAL, "DeletePod",
                            f"pod {m.key(pod)} with index {index} is out of "
                            f"expected replicas {num} and should be deleted")
                        self._delete_pod(job_key, rtype, pod)
                    continue
                exit_code = _exit_code(pod, self.controller.default_container_name)
                # TPU replicas are restarted slice-atomically by
                # _slice_failover; the per-pod delete below would patch a
                # single pod into a dead PJRT world
                if spec.restart_policy == c.RESTART_EXIT_CODE \
                        and _pod_phase(pod) == c.POD_FAILED \
                        and not tpu_managed:
                    reason = m.get_in(pod, "status", "reason", default="")
                    if (exit_code is not None and train.is_retryable_exit_code(exit_code)) \
                            or train.is_retryable_pod_failed_reason(reason):
                        self.recorder.event(job, TYPE_WARNING, "RestartPod",
                                            f"need to restart the pod {m.key(pod)}")
                        self._delete_pod(job_key, rtype, pod)
                        restart[0] = True
                # the failed pod still counts this round (reference pod.go:
                # 356-360 falls through to updateJobReplicaStatuses), which is
                # what lets UpdateJobStatus flip the job to Restarting
                _count_pod(rs, pod, spec.restart_policy)

    def _delete_pod(self, job_key: str, rtype: str, pod) -> None:
        self.expectations.expect_deletions(Expectations.pods_key(job_key, rtype), 1)
        try:
            self._retry(lambda: self.api.delete("Pod", m.namespace(pod),
                                                m.name(pod)))
        except (NotFound, ServerError):
            # NotFound: already gone (a timed-out delete may have landed);
            # exhausted transient errors: the pod is still there, so balance
            # the expectation and let the next reconcile retry the delete
            self.expectations.deletion_observed(Expectations.pods_key(job_key, rtype))

    def _create_pod(self, job, rtype: str, index: int, spec: ReplicaSpec,
                    replicas, run_policy: RunPolicy, plan: _ReplicaPlan,
                    hostnet_ports: Optional[dict] = None) -> None:
        rt = rtype.lower()
        template = m.deep_copy(spec.template) or {}
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": m.deep_copy(template.get("metadata", {})),
            "spec": m.deep_copy(template.get("spec", {})),
        }
        labels = self.gen_labels(m.name(job))
        labels[c.LABEL_REPLICA_TYPE] = rt
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        master = self.controller.is_master_role(replicas, rtype, index)
        if master:
            labels[c.LABEL_JOB_ROLE] = "master"
        if self.controller.enable_elastic_scaling(job, run_policy):
            m.finalizers(pod).append(c.FINALIZER_PREEMPT_PROTECTOR)
            labels[c.LABEL_GENERATION] = str(m.generation(job))
        md = pod["metadata"]
        md["name"] = pl.replica_name(m.name(job), rt, index)
        md["namespace"] = m.namespace(job)
        md["labels"] = {**(md.get("labels") or {}), **labels}

        # replica restart policy overrides template (reference pod.go:410)
        pod["spec"]["restartPolicy"] = (
            c.RESTART_NEVER if spec.restart_policy in (c.RESTART_EXIT_CODE, "")
            else spec.restart_policy)

        # hostnetwork: random port per replica (reference pod.go:509-521)
        hostnet_port: Optional[int] = None
        if hostnet_ports is not None:
            port = hn.random_port(self.config.hostnetwork_port_range,
                                  exclude=set(hostnet_ports.values()))
            if hn.setup_pod_hostnetwork(
                    pod, self.controller.default_container_name,
                    self.controller.default_port_name, port):
                hostnet_port = port

        # TPU slice placement + PJRT rendezvous env. Non-TPU roles of a
        # multislice job still gang with slice 0 (their minMember home).
        slice_id = 0
        num_slices = plan.num_slices if plan.policy is not None else 1
        if plan.policy is not None and rtype in plan.offsets:
            global_id = plan.offsets[rtype] + index
            slice_id = global_id // plan.slice_spec.num_hosts
            pl.render_tpu_worker(
                pod, slice_spec=plan.slice_spec, job_name=m.name(job),
                namespace=m.namespace(job), replica_type=rt, worker_id=global_id,
                num_slices=num_slices,
                container_name=self.controller.default_container_name,
                dns_domain=self.config.dns_domain,
                worker_hostnames=plan.global_dns,
                coordinator_address=f"{plan.global_dns[0]}:{pl.DEFAULT_COORDINATOR_PORT}")

        # job self-identity env: lets in-container agents (the elastic
        # checkpoint half of the 2-phase protocol, train/checkpoint.py
        # ElasticCheckpointAgent; python -m kubedl_tpu.train) find their
        # own CR without guessing from pod labels
        identity_env = [("KUBEDL_JOB_KIND", self.kind),
                        ("KUBEDL_JOB_NAMESPACE", m.namespace(job)),
                        ("KUBEDL_JOB_NAME", m.name(job))]
        if self.tracer.enabled:
            # in-container payloads (trainer step/checkpoint spans) join
            # the job's trace through this context
            identity_env.append((ENV_TRACEPARENT,
                                 format_traceparent(*job_trace_context(job))))
        for container in m.get_in(pod, "spec", "containers",
                                  default=[]) or []:
            env = container.setdefault("env", [])
            for k, v in identity_env:
                if not any(e.get("name") == k for e in env):
                    env.append({"name": k, "value": v})

        # framework-specific rendezvous on top (THE plugin seam)
        self.controller.set_cluster_spec(job, pod, rtype, index)

        if self.config.enable_gang_scheduling and self.gang is not None:
            self.gang.bind_pod_to_gang(job, pod, slice_id, num_slices)

        # spot replica overlay (reference pod.go:437-461)
        if spec.spot_replica_spec is not None:
            num = int(spec.replicas or 1)
            if index >= num - spec.spot_replica_spec.spot_replica_number:
                if spec.spot_replica_spec.priority_class_name:
                    pod["spec"]["priorityClassName"] = \
                        spec.spot_replica_spec.priority_class_name
                md["labels"].update(spec.spot_replica_spec.labels)

        m.set_controller_ref(pod, job)
        self._retry(lambda: self.api.create(pod))
        # record the host port only once the pod really exists; on
        # AlreadyExists the next round re-learns the live pod's port instead
        if hostnet_ports is not None and hostnet_port is not None:
            hostnet_ports[(rt, index)] = hostnet_port
        self.recorder.event(job, TYPE_NORMAL, "SuccessfulCreatePod",
                            f"Created pod: {md['name']}")

    # ------------------------------------------------------------------
    # children: services
    # ------------------------------------------------------------------

    def _reconcile_services(self, job, all_services, rtype: str,
                            spec: ReplicaSpec,
                            hostnet_ports: Optional[dict] = None) -> None:
        rt = rtype.lower()
        services = [s for s in all_services
                    if m.labels(s).get(c.LABEL_REPLICA_TYPE) == rt]
        num = int(spec.replicas or 1)
        by_index = {}
        for s in services:
            try:
                by_index.setdefault(
                    int(m.labels(s).get(c.LABEL_REPLICA_INDEX, "-1")), []).append(s)
            except ValueError:
                continue
        job_key = m.key(job)
        for index in range(max([num] + [i + 1 for i in by_index])):
            group = by_index.get(index, [])
            if not group:
                if index >= num:
                    continue
                self.expectations.expect_creations(
                    Expectations.services_key(job_key, rtype), 1)
                try:
                    self._create_service(job, rtype, index, spec, hostnet_ports)
                except (AlreadyExists, ServerError) as e:
                    self.expectations.creation_observed(
                        Expectations.services_key(job_key, rtype))
                    if isinstance(e, ServerError):
                        raise
            elif index >= num and not m.is_deleting(group[0]):
                self.expectations.expect_deletions(
                    Expectations.services_key(job_key, rtype), 1)
                try:
                    self._retry(lambda g=group[0]: self.api.delete(
                        "Service", m.namespace(g), m.name(g)))
                except (NotFound, ServerError):
                    self.expectations.deletion_observed(
                        Expectations.services_key(job_key, rtype))
            elif hostnet_ports is not None:
                # fail-over port re-sync (reference service.go:236-250): the
                # replica's pod may have restarted on a new random host port;
                # point the stable service at wherever it listens now
                svc = group[0]
                live = hostnet_ports.get((rt, index))
                ports = m.get_in(svc, "spec", "ports", default=[]) or []
                if live is not None and ports \
                        and ports[0].get("targetPort") != live:
                    # svc is a shared list() snapshot: mutate a copy
                    svc = m.deep_copy(svc)
                    svc["spec"]["ports"][0]["targetPort"] = live
                    try:
                        self.api.update(svc)
                    except (Conflict, NotFound):
                        pass

    def _create_service(self, job, rtype: str, index: int, spec: ReplicaSpec,
                        hostnet_ports: Optional[dict] = None) -> None:
        rt = rtype.lower()
        labels = self.gen_labels(m.name(job))
        labels[c.LABEL_REPLICA_TYPE] = rt
        labels[c.LABEL_REPLICA_INDEX] = str(index)
        port = _port_from_template(spec.template,
                                   self.controller.default_container_name,
                                   self.controller.default_port_name) \
            or self.controller.default_port
        # headless services can't remap ports, so hostnetwork mode uses a
        # normal service whose targetPort tracks the pod's random host port
        # (reference service.go:276-305), unless HostNetWithHeadlessSvc
        cluster_ip = "None"
        target_port = port
        if hostnet_ports is not None and not self.config.hostnet_with_headless_svc:
            cluster_ip = ""
            target_port = hostnet_ports.get((rt, index), port)
        svc = m.new_obj("v1", "Service", pl.replica_name(m.name(job), rt, index),
                        m.namespace(job), labels=labels)
        svc["spec"] = {
            "clusterIP": cluster_ip,  # "None" = headless DNS fabric
            "selector": dict(labels),
            "ports": [{"name": self.controller.default_port_name,
                       "port": port, "targetPort": target_port}],
        }
        m.set_controller_ref(svc, job)
        self._retry(lambda: self.api.create(svc))

    # ------------------------------------------------------------------
    # status
    # ------------------------------------------------------------------

    def _update_job_status(self, job, replicas, status: JobStatus,
                           restart: bool, pods) -> None:
        """Generalized form of the per-framework updateGeneralJobStatus
        (reference ``controllers/tensorflow/status.go:69-228``)."""
        name = m.name(job)
        previous_restarting = st.is_restarting(status)
        previous_failed = st.is_failed(status)
        if status.start_time is None:
            status.start_time = m.rfc3339(self.api.now())

        worker0_completed = self._worker0_completed(pods)
        has_master = self.controller.contains_master_spec(replicas)
        master_types = {t.lower() for t in self.controller.master_replica_types(replicas)}

        for rtype, spec in replicas.items():
            rs = status.replica_statuses.get(rtype)
            if rs is None:
                continue
            expected = int(spec.replicas or 1) - rs.succeeded
            if has_master and rtype.lower() in master_types:
                if rs.active > 0:
                    st.update_job_conditions(
                        status, c.JOB_RUNNING, st.REASON_JOB_RUNNING,
                        f"{self.kind} {name} is running.", now=self.api.now())
                if expected == 0:
                    self._mark_succeeded(job, status)
            elif not has_master and rtype == self.controller.worker_replica_type():
                if self.controller.judge_worker_success(
                        job, int(spec.replicas or 1), rs.succeeded,
                        worker0_completed):
                    self._mark_succeeded(job, status)
                elif rs.active > 0:
                    st.update_job_conditions(
                        status, c.JOB_RUNNING, st.REASON_JOB_RUNNING,
                        f"{self.kind} {name} is running.", now=self.api.now())
            if rs.failed > 0:
                if restart:
                    st.update_job_conditions(
                        status, c.JOB_RESTARTING, st.REASON_JOB_RESTARTING,
                        f"{self.kind} {name} is restarting because "
                        f"{rs.failed} {rtype} replica(s) failed.",
                        now=self.api.now())
                    self.recorder.event(job, TYPE_WARNING, st.REASON_JOB_RESTARTING,
                                        f"{rs.failed} {rtype} replica(s) failed")
                    if not previous_restarting:
                        self.metrics.failed.inc(kind=self.kind)
                        self.metrics.restarted.inc(kind=self.kind)
                else:
                    if status.completion_time is None:
                        status.completion_time = m.rfc3339(self.api.now())
                    st.update_job_conditions(
                        status, c.JOB_FAILED, st.REASON_JOB_FAILED,
                        f"{self.kind} {name} is failed because "
                        f"{rs.failed} {rtype} replica(s) failed.",
                        now=self.api.now())
                    self.recorder.event(job, TYPE_NORMAL, st.REASON_JOB_FAILED,
                                        f"{rs.failed} {rtype} replica(s) failed")
                    if not previous_failed:
                        self.metrics.failed.inc(kind=self.kind)

    def _mark_succeeded(self, job, status: JobStatus) -> None:
        if st.is_succeeded(status):
            return
        if status.completion_time is None:
            status.completion_time = m.rfc3339(self.api.now())
        st.update_job_conditions(
            status, c.JOB_SUCCEEDED, st.REASON_JOB_SUCCEEDED,
            f"{self.kind} {m.name(job)} successfully completed.",
            now=self.api.now())
        self.recorder.event(job, TYPE_NORMAL, st.REASON_JOB_SUCCEEDED,
                            f"{self.kind} {m.name(job)} successfully completed.")
        self.metrics.successful.inc(kind=self.kind)

    def _worker0_completed(self, pods) -> bool:
        wt = self.controller.worker_replica_type().lower()
        for p in pods:
            lbl = m.labels(p)
            if lbl.get(c.LABEL_REPLICA_TYPE) == wt \
                    and lbl.get(c.LABEL_REPLICA_INDEX) == "0":
                code = _exit_code(p, self.controller.default_container_name)
                return _pod_phase(p) == c.POD_SUCCEEDED and (code in (0, None))
        return False

    def _flush_status(self, job, status: JobStatus, old_status: JobStatus) -> bool:
        """Write the round's status back. A 409 means another writer moved
        the object under us: re-read for a fresh resourceVersion and
        re-apply our status delta (the controller owns ``.status``, and
        this round's conditions were computed from live pods — dropping
        them would lose a phase transition), bounded so a pathological
        conflict storm degrades to a requeue instead of a livelock.
        Transient 5xx/timeouts retry with jitter inside each attempt.
        Returns False only when the flush could not land (caller requeues)."""
        status.last_reconcile_time = m.rfc3339(self.api.now())
        old_status.last_reconcile_time = status.last_reconcile_time
        if status.to_dict() == old_status.to_dict():
            return True
        for _ in range(8):
            fresh = self.api.try_get(self.kind, m.namespace(job), m.name(job))
            if fresh is None:
                return True  # job deleted: nothing to flush
            fresh["status"] = status.to_dict()
            try:
                self._retry(lambda f=fresh: self.api.update_status(f))
                return True
            except Conflict:
                continue
            except ServerError as e:
                log.warning("status flush for %s failed: %s", m.key(job), e)
                return False
        log.warning("status flush for %s kept conflicting; will requeue",
                    m.key(job))
        return False

    # ------------------------------------------------------------------
    # TPU plan / gang membership / DAG / cron
    # ------------------------------------------------------------------

    def _resolve_tpu(self, job, replicas) -> _ReplicaPlan:
        policy = TPUPolicy.from_job(job)
        if policy is None:
            return _ReplicaPlan()
        slice_spec = policy.resolve()
        num_slices = max(1, policy.num_slices)
        # one flat TPU process index space across TPU replica types, in
        # reconcile order (Master first => Master is process 0)
        orders = self._orders(replicas)
        offsets, total = {}, 0
        for rtype in orders:
            spec = replicas.get(rtype)
            if spec is not None and self.controller.is_tpu_replica(rtype):
                offsets[rtype] = total
                total += int(spec.replicas or 1)
        want = slice_spec.num_hosts * num_slices
        if total != want:
            raise ValueError(
                f"TPU replica count mismatch: {total} TPU replica(s) "
                f"({', '.join(offsets) or 'none'}) but "
                f"{policy.accelerator_type or slice_spec.accelerator_type} x "
                f"{num_slices} slice(s) needs exactly {want} worker pod(s) "
                f"(one per TPU host)")
        global_dns = []
        for rtype, off in sorted(offsets.items(), key=lambda kv: kv[1]):
            n = int(replicas[rtype].replicas or 1)
            global_dns += [
                pl.service_dns(m.name(job), rtype.lower(), i, m.namespace(job),
                               self.config.dns_domain)
                for i in range(n)]
        return _ReplicaPlan(policy=policy, slice_spec=slice_spec,
                            num_slices=num_slices, offsets=offsets,
                            global_dns=global_dns)

    def _orders(self, replicas) -> list[str]:
        """Reconcile order with AIMaster forced first (its gate freezes all
        other types, so it must be created before any of them)."""
        orders = [rt for rt in (self.controller.get_reconcile_orders() or list(replicas))
                  if rt in replicas]
        for rt in replicas:
            if rt not in orders:
                orders.append(rt)
        if c.REPLICA_AIMASTER in orders:
            orders.remove(c.REPLICA_AIMASTER)
            orders.insert(0, c.REPLICA_AIMASTER)
        return orders

    def _gang_min_members(self, replicas, plan: _ReplicaPlan) -> list[int]:
        """minMember per slice gang: hosts-per-slice for TPU workers, with
        non-TPU roles folded into slice 0 (SURVEY.md §2-P gang row)."""
        if plan.policy is None:
            return [sum(int(rs.replicas or 1) for rs in replicas.values())]
        members = [0] * plan.num_slices
        hosts = plan.slice_spec.num_hosts
        for rtype, rs in replicas.items():
            n = int(rs.replicas or 1)
            if rtype in plan.offsets:
                for idx in range(n):
                    members[(plan.offsets[rtype] + idx) // hosts] += 1
            else:
                members[0] += n
        return members

    def _slice_failover(self, job, status: JobStatus, old_status: JobStatus,
                        pods, replicas, plan: _ReplicaPlan,
                        exempt: tuple = ()
                        ) -> Optional[_FailoverDecision]:
        """Slice-atomic recovery for gang-scheduled TPU jobs.

        A slice is *disrupted* when any member pod carries a
        ``DisruptionTarget`` condition, failed with a retryable exit code /
        reason, or — once the job has been running — is simply missing
        (preemption deleted it). Recovery tears down the **whole** slice
        and re-enters gang admission: the surviving pods belong to a PJRT
        world whose membership died with the lost worker, so patching one
        replacement in can never converge. Permanent exit codes fail the
        job instead; a *failed* pod under restartPolicy ``Never`` defers to
        the normal failure path, while a *missing* pod is self-heal
        territory — the engine has always recreated missing pods for any
        policy, and on TPU the slice-atomic form of that self-heal is the
        only one that converges. Repeated restarts wait out a growing
        decorrelated-jitter
        delay persisted in ``JobStatus`` (restartRounds/lastRestartTime) so
        a flapping node can't hot-loop slice recreation.
        """
        hosts = plan.slice_spec.num_hosts
        container = self.controller.default_container_name
        rt_of = {rt.lower(): rt for rt in plan.offsets}
        members: dict[int, list] = {sid: [] for sid in range(plan.num_slices)}
        for p in pods:
            lbl = m.labels(p)
            rtype = rt_of.get(lbl.get(c.LABEL_REPLICA_TYPE, ""))
            idx = lbl.get(c.LABEL_REPLICA_INDEX, "")
            if rtype is None or not idx.isdigit():
                continue  # non-TPU roles keep per-pod semantics
            sid = (plan.offsets[rtype] + int(idx)) // hosts
            if 0 <= sid < plan.num_slices:
                members[sid].append((rtype, p))

        was_up = st.is_running(old_status) or st.is_restarting(old_status)
        disrupted: set[int] = set()
        for sid in range(plan.num_slices):
            if sid in exempt:
                # concurrency-elastic exemption (docs/elastic.md): this
                # slice is being shed in place or has no world yet —
                # its disruption marks are the reconfiguration protocol
                # at work, not a failure to recover from
                continue
            mem = members[sid]
            if was_up and 0 < len(mem) < hosts \
                    and any(_pod_phase(p) != c.POD_PENDING for _, p in mem):
                # a member vanished out from under a slice whose world had
                # started. An all-Pending partial slice is just a rollout
                # interrupted mid-create (e.g. a transient error aborted
                # the diff loop): no world formed yet, so completing the
                # creation converges — tearing it down would burn a
                # backoff round per hiccup
                disrupted.add(sid)
            for rtype, p in mem:
                spec = replicas.get(rtype)
                policy = (spec.restart_policy if spec else "") or c.RESTART_NEVER
                if _pod_disrupted(p, container):
                    if policy != c.RESTART_NEVER:
                        disrupted.add(sid)
                elif policy == c.RESTART_EXIT_CODE \
                        and _pod_phase(p) == c.POD_FAILED:
                    code = _exit_code(p, container)
                    if code is not None and not train.is_retryable_exit_code(code):
                        return _FailoverDecision(
                            "fail", message=(
                                f"replica {m.name(p)} exited with permanent "
                                f"code {code}; not restarting the slice"))
        if not disrupted:
            return None

        # ---- backoff gate (persisted in JobStatus) ---------------------
        now = self.api.now()
        rounds = status.restart_rounds
        last = _parse_ts(status.last_restart_time)
        if last is not None and rounds \
                and now - last >= self.config.restart_backoff_reset:
            rounds = status.restart_rounds = 0  # stable long enough: decay
        # seed 0 unless pinned: the per-job delay must be stable across
        # operator restarts (the job uid already de-correlates jobs)
        delay = restart_delay(rounds, self.config.restart_backoff_base,
                              self.config.restart_backoff_cap,
                              key=m.uid(job),
                              seed=self.config.backoff_jitter_seed or 0)
        if last is not None and delay > 0:
            remaining = last + delay - now
            if remaining > 0:
                st.update_job_conditions(
                    status, c.JOB_RESTARTING, st.REASON_JOB_RESTARTING,
                    f"{self.kind} {m.name(job)} slice restart backing off "
                    f"{delay:.1f}s (round {rounds})", now=now)
                return _FailoverDecision(
                    "wait", requeue=remaining,
                    message=(f"{self.kind} {m.name(job)} slice restart "
                             f"backing off {delay:.1f}s (round {rounds})"),
                    frozen=tuple(sorted(disrupted)))

        # ---- teardown: the whole slice goes together -------------------
        job_key = m.key(job)
        deleted = 0
        for sid in sorted(disrupted):
            for rtype, p in members[sid]:
                if not m.is_deleting(p):
                    self._delete_pod(job_key, rtype, p)
                    deleted += 1
            if self.config.enable_gang_scheduling and self.gang is not None:
                try:
                    self._retry(lambda s=sid: self.gang.readmit_slice(
                        job, s, plan.num_slices))
                except ServerError as e:
                    # pods are already gone: keep the restart bookkeeping
                    # below (losing it would defeat the backoff gate) and
                    # accept the stale PodGroup — create_gang reconciles
                    # its minMember on the next pass
                    log.warning("gang re-admission for slice %d of %s "
                                "failed: %s", sid, job_key, e)
        status.restart_count += 1
        status.restart_rounds = rounds + 1
        status.last_restart_time = m.rfc3339(now)
        # outage-start mark for the restart-MTTR histogram: only the
        # FIRST round of an outage sets it (round 2 of the same outage
        # must not shrink the measured window)
        self._mttr_start.setdefault(m.uid(job), now)
        msg = (f"slice(s) {sorted(disrupted)} of {self.kind} {m.name(job)} "
               f"disrupted; restarting all {deleted} slice pod(s) together "
               f"(restart #{status.restart_count})")
        st.update_job_conditions(status, c.JOB_RESTARTING,
                                 st.REASON_JOB_RESTARTING, msg, now=now)
        self.recorder.event(job, TYPE_WARNING, "SliceRestart", msg)
        self.metrics.restarted.inc(kind=self.kind)
        return _FailoverDecision("restart")

    # ------------------------------------------------------------------
    # concurrency-elastic slices (docs/elastic.md)
    # ------------------------------------------------------------------

    def _elastic_plan(self, job, run_policy: RunPolicy,
                      plan: _ReplicaPlan) -> Optional[_ElasticPlan]:
        """The gang's elastic view this round, or None when pre-elastic
        semantics apply: the job declares no slice range, or the live
        width fell below its min (whole-gang failover is then the only
        move that converges — a world under the floor cannot train)."""
        policy = run_policy.scheduling_policy
        mn = policy.min_slices if policy is not None else None
        if not mn:
            return None
        mn = max(min(int(mn), plan.num_slices), 1)
        if mn >= plan.num_slices:
            return None
        active, leaving = [], []
        for g in self.gang.get_gangs(job):
            sid = _gang_slice_id(m.name(g), m.name(job))
            if sid is None or not (0 <= sid < plan.num_slices) \
                    or not is_gang_admitted(g) or m.is_deleting(g):
                continue
            from ..scheduling.gang import is_gang_preempted
            if is_gang_preempted(g):
                leaving.append(sid)
            else:
                active.append(sid)
        if len(active) < mn:
            return None
        raw = m.get_annotations(job).get(c.ANNOTATION_ELASTIC_SLICES)
        recorded = None
        if raw is not None:
            try:
                recorded = tuple(sorted(
                    int(x) for x in raw.split(",") if x != ""))
            except ValueError:
                recorded = None
        return _ElasticPlan(min_slices=mn, num_slices=plan.num_slices,
                            active=tuple(sorted(active)),
                            leaving=tuple(sorted(leaving)),
                            recorded=recorded)

    def _elastic_reconfigure(self, job, status: JobStatus,
                             plan: _ReplicaPlan, ctx: _ElasticPlan,
                             pods) -> tuple:
        """Drive one restart-free world reconfiguration through the
        2-phase checkpoint protocol (docs/elastic.md):

        1. *Request*: the admitted width diverged from the recorded
           world — bump ``ckpt-requested-version``; the in-container
           agent (``ElasticCheckpointAgent``) saves and acks via
           ``ckpt-completed-version``. The job keeps Running; leaving
           slices keep computing until the checkpoint is down.
        2. *Execute* (ack landed): leaving slices' pods are deleted and
           their PodGroups re-enter gang admission (``readmit_slice`` —
           the regrow source); survivors get a fresh ``world-size``
           annotation (the downward-API in-place restart contract);
           the job's ``elastic-slices`` record adopts the new set. The
           job never transitions back to Created/Queuing.

        Returns ``(requeue_or_None, allowed_slice_set)`` — the diff
        loops create pods only for allowed slices.
        """
        now = self.api.now()
        ann = m.get_annotations(job)
        active = set(ctx.active)
        sig = ",".join(str(s) for s in ctx.active)
        if ctx.recorded is None:
            # first world: record the width the job is starting at
            self._patch_job_annotations(
                job, {c.ANNOTATION_ELASTIC_SLICES: sig})
            return None, active
        if tuple(sorted(ctx.recorded)) == ctx.active and not ctx.leaving:
            return None, active
        survivors = active & set(ctx.recorded)
        has_world = any(_pod_phase(p) == c.POD_RUNNING for p in pods)
        if not has_world and not ctx.leaving:
            # no live world yet: adopt the grown width for free — there
            # is nothing to checkpoint or reshard
            self._patch_job_annotations(
                job, {c.ANNOTATION_ELASTIC_SLICES: sig})
            return None, active
        requested = int(
            ann.get(c.ANNOTATION_CKPT_REQUESTED_VERSION, 0) or 0)
        completed = int(
            ann.get(c.ANNOTATION_CKPT_COMPLETED_VERSION, 0) or 0)
        #: the version gating the in-flight reconfiguration; 0 = none.
        #: Needed because requested == completed is ambiguous between
        #: "our ack just landed" and "nothing in flight"
        gate_v = int(
            ann.get(c.ANNOTATION_ELASTIC_CKPT_VERSION, 0) or 0)
        uid = m.uid(job)
        if gate_v <= 0:
            # phase 1: request a checkpoint for this reconfiguration
            version = max(requested, completed) + 1
            if self._patch_job_annotations(job, {
                    c.ANNOTATION_CKPT_REQUESTED_VERSION: str(version),
                    c.ANNOTATION_ELASTIC_CKPT_VERSION: str(version),
                    c.ANNOTATION_ELASTIC_RECONFIGURE_AT:
                        m.rfc3339(now)}):
                self._mttr_start.setdefault(uid, now)
                self.recorder.event(
                    job, TYPE_NORMAL, "ElasticCheckpointRequested",
                    f"world change {len(ctx.recorded)} -> "
                    f"{len(ctx.active)} slice(s): checkpoint "
                    f"v{version} requested before reconfiguration")
            return 2.0, survivors
        if completed < gate_v:
            return 2.0, survivors       # phase 2 pending: no ack yet
        # ---- execute: the checkpoint is down ---------------------------
        hosts = plan.slice_spec.num_hosts
        rt_of = {rt.lower(): rt for rt in plan.offsets}
        members: dict[int, list] = {}
        for p in pods:
            lbl = m.labels(p)
            rtype = rt_of.get(lbl.get(c.LABEL_REPLICA_TYPE, ""))
            idx = lbl.get(c.LABEL_REPLICA_INDEX, "")
            if rtype is None or not idx.isdigit():
                continue
            sid = (plan.offsets[rtype] + int(idx)) // hosts
            members.setdefault(sid, []).append((rtype, p))
        job_key = m.key(job)
        removed = sorted((set(ctx.recorded) | set(ctx.leaving)) - active)
        for sid in removed:
            for rtype, p in members.get(sid, []):
                if not m.is_deleting(p):
                    self._delete_pod(job_key, rtype, p)
            try:
                self._retry(lambda s=sid: self.gang.readmit_slice(
                    job, s, plan.num_slices))
            except ServerError as e:
                log.warning("elastic re-admission for slice %d of %s "
                            "failed: %s", sid, job_key, e)
        world = hosts * len(active)
        for sid in sorted(survivors):
            for rtype, p in members.get(sid, []):
                try:
                    self._retry(lambda pp=p: self.api.patch_merge(
                        "Pod", m.namespace(pp), m.name(pp),
                        {"metadata": {"annotations": {
                            ANNOTATION_WORLD_SIZE: str(world)}}}))
                except (Conflict, NotFound, ServerError):
                    pass                # downward-API visibility only
        t0 = _parse_ts(
            ann.get(c.ANNOTATION_ELASTIC_RECONFIGURE_AT)) or now
        direction = "shrink" if len(active) < len(ctx.recorded) else "grow"
        self._patch_job_annotations(
            job, {c.ANNOTATION_ELASTIC_SLICES: sig,
                  c.ANNOTATION_ELASTIC_CKPT_VERSION: "0"})
        self.recorder.event(
            job, TYPE_NORMAL, "ElasticReconfigured",
            f"reconfigured in place ({direction}): {len(ctx.recorded)} "
            f"-> {len(active)} slice(s), world size {world} process(es); "
            f"the job never left Running")
        if self.elastic_metrics is not None:
            self.elastic_metrics.reconfigurations.inc(
                kind=self.kind, direction=direction)
            self.elastic_metrics.reconfigure_seconds.observe(
                max(now - t0, 0.0), kind=self.kind)
        if self.tracer.enabled:
            trace_id, root = job_trace_context(job)
            self.tracer.record(
                "elastic.reconfigure", t0, now, trace_id=trace_id,
                parent_id=root, component="engine",
                attributes={"direction": direction,
                            "fromSlices": len(ctx.recorded),
                            "toSlices": len(active),
                            "world": world})
        return None, active

    def _patch_job_annotations(self, job, ann: dict) -> bool:
        """Merge-patch job annotations with bounded conflict re-reads
        plus transient retries — the ack-write discipline shared with
        ``ElasticCheckpointAgent`` (docs/elastic.md): a chaos 409 must
        re-apply, never silently drop a protocol step."""
        for _ in range(8):
            try:
                self._retry(lambda: self.api.patch_merge(
                    self.kind, m.namespace(job), m.name(job),
                    {"metadata": {"annotations": dict(ann)}}))
                return True
            except Conflict:
                continue
            except NotFound:
                return False
            except ServerError as e:
                log.warning("annotation patch for %s failed: %s",
                            m.key(job), e)
                return False
        log.warning("annotation patch for %s kept conflicting",
                    m.key(job))
        return False

    def _recount_replica_statuses(self, status: JobStatus, replicas,
                                  pods) -> None:
        """Refresh per-type active/succeeded/failed counters from live pods
        without running the create/delete diff (used when slice failover
        short-circuits the normal per-replica loops)."""
        for rtype in replicas:
            rt = rtype.lower()
            rs = status.replica_statuses.setdefault(rtype, c.ReplicaStatus())
            rs.active = rs.succeeded = rs.failed = rs.evicted = 0
            for p in pods:
                if m.labels(p).get(c.LABEL_REPLICA_TYPE) == rt:
                    _count_pod(rs, p, replicas[rtype].restart_policy)

    def _dag_ready(self, pods, conditions) -> bool:
        """DAG stage gating (reference ``dag_sched.go:29-67``): all upstream
        replicas must have reached the condition's phase."""
        order = [c.POD_PENDING, c.POD_RUNNING, c.POD_SUCCEEDED]
        for cond in conditions:
            upstream = [p for p in pods
                        if m.labels(p).get(c.LABEL_REPLICA_TYPE) == cond.upstream.lower()]
            if not upstream:
                return False
            for p in upstream:
                phase = _pod_phase(p)
                if phase == c.POD_FAILED:
                    return False
                want = cond.on_phase
                if want in order and phase in order:
                    if order.index(phase) < order.index(want):
                        return False
                elif phase != want:
                    return False
        return True

    def _reconcile_cron(self, job, run_policy: RunPolicy) -> None:
        """A job carrying CronPolicy converts itself into a Cron CR wrapping
        a cleaned copy of the job (reference job.go:372-455)."""
        existing = self.api.try_get("Cron", m.namespace(job), m.name(job))
        if existing is not None:
            return
        workload = copy.deepcopy(job)
        wmeta = workload.get("metadata", {})
        for k in ("resourceVersion", "uid", "creationTimestamp", "generation",
                  "ownerReferences", "managedFields"):
            wmeta.pop(k, None)
        workload.pop("status", None)
        workload.get("spec", {}).pop("cronPolicy", None)
        cp = run_policy.cron_policy
        cron = m.new_obj("apps.kubedl.io/v1alpha1", "Cron", m.name(job),
                         m.namespace(job))
        cron["spec"] = {
            "schedule": cp.schedule,
            "concurrencyPolicy": cp.concurrency_policy,
            "template": {"workload": workload},
        }
        if cp.suspend is not None:
            cron["spec"]["suspend"] = cp.suspend
        if cp.deadline is not None:
            cron["spec"]["deadline"] = cp.deadline
        if cp.history_limit is not None:
            cron["spec"]["historyLimit"] = cp.history_limit
        m.set_controller_ref(cron, job)
        try:
            self.api.create(cron)
            self.recorder.event(job, TYPE_NORMAL, "CronCreated",
                                f"created cron {m.name(job)} for scheduled job")
        except AlreadyExists:
            pass


# ---------------------------------------------------------------------------
# module helpers
# ---------------------------------------------------------------------------

def _pod_phase(pod) -> str:
    return m.get_in(pod, "status", "phase", default=c.POD_PENDING)


def _count_pod(rs, pod, restart_policy: str = "") -> None:
    """Reference ``status.go:19-41``: Pending counts as active only once
    scheduled with init containers passed. Disruption-marked failures are
    tracked as ``evicted``, not ``failed`` — keeping ``rs.failed``
    symmetric with the backoff-limit accounting's live count, which also
    excludes voluntary disruptions (a preemption must never mask or fake
    a genuine failure round). Exception: under restartPolicy ``Never``
    (the default) there is no restart path to absorb the disruption, so
    it also counts as ``failed`` — otherwise a preempted-but-not-deleted
    pod would leave the job Running forever."""
    phase = _pod_phase(pod)
    if phase == c.POD_PENDING:
        if m.get_in(pod, "spec", "nodeName") and _init_containers_passed(pod):
            rs.active += 1
    elif phase == c.POD_RUNNING:
        rs.active += 1
    elif phase == c.POD_SUCCEEDED:
        rs.succeeded += 1
    elif phase == c.POD_FAILED:
        if _has_disruption_target(pod):
            rs.evicted += 1
            if (restart_policy or c.RESTART_NEVER) == c.RESTART_NEVER:
                rs.failed += 1
        else:
            rs.failed += 1
            if m.get_in(pod, "status", "reason", default="") == "Evicted":
                rs.evicted += 1


def _has_disruption_target(pod) -> bool:
    """True when the scheduler/kubelet marked this pod for voluntary
    disruption (preemption, drain, spot reclaim)."""
    for cond in m.get_in(pod, "status", "conditions", default=[]) or []:
        if cond.get("type") == c.POD_COND_DISRUPTION_TARGET \
                and cond.get("status", "True") == "True":
            return True
    return False


def _pod_disrupted(pod, container_name: str) -> bool:
    """A transiently-lost pod: disruption-marked, or failed in a way the
    exit-code taxonomy (``utils.train``) classifies as retryable."""
    if _has_disruption_target(pod):
        return True
    if _pod_phase(pod) != c.POD_FAILED:
        return False
    if train.is_retryable_pod_failed_reason(
            m.get_in(pod, "status", "reason", default="")):
        return True
    code = _exit_code(pod, container_name)
    return code is not None and train.is_retryable_exit_code(code)


def _init_containers_passed(pod) -> bool:
    for cs in m.get_in(pod, "status", "initContainerStatuses", default=[]) or []:
        state = cs.get("state", {})
        if "terminated" not in state and "running" not in state:
            return False
    return True


def _exit_code(pod, container_name: str) -> Optional[int]:
    for cs in m.get_in(pod, "status", "containerStatuses", default=[]) or []:
        if cs.get("name") == container_name:
            term = m.get_in(cs, "state", "terminated")
            if term is not None:
                return int(term.get("exitCode", 0))
    return None


def _total_restart_count(pods) -> int:
    total = 0
    for p in pods:
        for cs in m.get_in(p, "status", "containerStatuses", default=[]) or []:
            total += int(cs.get("restartCount", 0))
    return total


def _replicas_at_generation(pods, generation: int) -> int:
    return sum(1 for p in pods
               if m.labels(p).get(c.LABEL_GENERATION) == str(generation))


def _aimaster_ready(pods) -> bool:
    for p in pods:
        if m.labels(p).get(c.LABEL_REPLICA_TYPE) == c.REPLICA_AIMASTER.lower():
            return _pod_phase(p) == c.POD_RUNNING
    return False


def _parse_ts(ts) -> Optional[float]:
    if not ts:
        return None
    import calendar
    import time as _time
    try:
        return calendar.timegm(_time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError):
        return None


def _port_from_template(template: dict, container_name: str,
                        port_name: str) -> Optional[int]:
    for ct in m.get_in(template, "spec", "containers", default=[]) or []:
        if ct.get("name") == container_name:
            for p in ct.get("ports", []) or []:
                if p.get("name") == port_name:
                    return int(p.get("containerPort"))
    return None
