"""The workload-controller plugin contract.

Python rendering of the reference's ``ControllerInterface`` + the elastic
scaling contract (``pkg/job_controller/api/v1/interface.go:12-90``). Every
framework controller (PyTorch/XLA, TF, JAX, XGBoost, XDL, Mars, ElasticDL)
implements this; the generic engine owns everything else. ``set_cluster_spec``
is deliberately kept as THE single point where a framework's rendezvous
contract lives (SURVEY.md §7 "hard parts").

TPU-native addition: ``TPUPolicy`` — a job-level declaration of the slice
shape (``spec.tpuPolicy`` or annotations). The engine uses it to render
every TPU replica with slice placement + PJRT env before the framework's
``set_cluster_spec`` runs, so frameworks only add their own glue on top.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api import common as c
from ..api.common import ReplicaSpec, RunPolicy
from ..core import meta as m
from ..tpu import topology
from ..tpu.topology import SliceSpec


@dataclass
class TPUPolicy:
    accelerator_type: str = ""       # "v5p-32"
    generation: str = ""             # alternative: generation + topology
    topology: str = ""               # "2x2x4"
    num_slices: int = 1
    host_chips: Optional[int] = None  # force v5e/v6e host machine shape

    @classmethod
    def from_spec(cls, d: dict) -> "TPUPolicy":
        """Parse a ``tpuPolicy`` spec dict. "accelerator" is the friendly
        alias: a full type ("v5p-32") or a bare generation ("v5p") paired
        with topology."""
        alias = d.get("accelerator", "")
        accel = d.get("acceleratorType", "") or (
            alias if "-" in alias else "")
        gen = d.get("generation", "") or (
            alias if alias and "-" not in alias else "")
        return cls(
            accelerator_type=accel,
            generation=gen,
            topology=d.get("topology", ""),
            num_slices=int(d.get("numSlices", 1) or 1),
            host_chips=d.get("hostChips"),
        )

    @classmethod
    def from_job(cls, job: dict) -> Optional["TPUPolicy"]:
        d = m.get_in(job, "spec", "tpuPolicy")
        if d:
            return cls.from_spec(d)
        ann = m.meta(job).get("annotations", {}) or {}
        if c.ANNOTATION_TPU_ACCELERATOR in ann or c.ANNOTATION_TPU_TOPOLOGY in ann:
            accel_ann = ann.get(c.ANNOTATION_TPU_ACCELERATOR, "")
            topo = ann.get(c.ANNOTATION_TPU_TOPOLOGY, "")
            # the accelerator annotation may be a full type ("v5p-32") or a
            # bare generation ("v5p") paired with the topology annotation
            if accel_ann and "-" in accel_ann:
                accel, gen = accel_ann, ""
            else:
                accel, gen = "", accel_ann
            if topo and not _looks_like_topology(topo):
                topo = ""
            return cls(accelerator_type=accel, generation=gen, topology=topo,
                       num_slices=int(ann.get(c.ANNOTATION_TPU_NUM_SLICES, 1) or 1))
        return None

    def resolve(self) -> SliceSpec:
        if self.accelerator_type:
            spec = topology.parse_accelerator(self.accelerator_type)
            if self.host_chips:
                spec = topology.from_chips(spec.generation.name, spec.chips,
                                           host_chips=self.host_chips)
            return spec
        if self.generation and self.topology:
            import math
            chips = math.prod(int(x) for x in self.topology.lower().split("x"))
            return topology.from_chips(self.generation, chips, self.topology,
                                       host_chips=self.host_chips)
        raise ValueError("tpuPolicy needs acceleratorType or generation+topology")


def _looks_like_topology(s: str) -> bool:
    parts = s.lower().split("x")
    return len(parts) >= 2 and all(p.isdigit() for p in parts)


class WorkloadController:
    """Base class per-framework controllers extend (reference
    ``interface.go:12-72``). Attributes identify the kind; methods are the
    framework-specific seams the generic engine calls into."""

    kind: str = ""
    api_version: str = "training.kubedl.io/v1alpha1"
    group_name: str = "kubedl.io"
    #: name of the framework's main container in pod templates
    default_container_name: str = "main"
    default_port_name: str = "kubedl-port"
    default_port: int = 8476
    #: spec field holding map[ReplicaType]ReplicaSpec (wire-compatible with
    #: the reference's irregular names: tfReplicaSpecs, pytorchReplicaSpecs,
    #: xgbReplicaSpecs, ...)
    replica_specs_field_name: str = "replicaSpecs"

    def __init__(self, api=None):
        #: API-server handle for controllers that manage extra resources
        #: (MPI hostfile ConfigMaps, elastic checkpoint patches); None in
        #: pure-rendering unit tests.
        self.api = api
        #: cluster DNS suffix, set by the operator registry from
        #: OperatorConfig.dns_domain so controller-rendered endpoints match
        #: the engine-rendered TPU env.
        self.dns_domain = ""

    # -- identity / spec access ------------------------------------------

    def get_replica_specs(self, job: dict) -> dict[str, ReplicaSpec]:
        raw = m.get_in(job, "spec", self.replica_specs_field_name, default={}) or {}
        return {rt: ReplicaSpec.from_dict(rs) for rt, rs in raw.items()}

    def get_run_policy(self, job: dict) -> RunPolicy:
        # reference kinds inline RunPolicy fields at spec top level
        return RunPolicy.from_dict(job.get("spec", {}))

    def validate(self, job: dict) -> None:
        """Kind-specific validation hook, run by the admission chain after
        the generic job validators. Raise ValueError to reject."""

    def set_defaults(self, job: dict) -> None:
        """Defaulting webhook analog (reference ``apis/training/v1alpha1/
        *_defaults.go``): replicas=1, restart policy, port."""
        raw = m.get_in(job, "spec", self.replica_specs_field_name, default={}) or {}
        for rt, rs in raw.items():
            rs.setdefault("replicas", 1)
            rs.setdefault("restartPolicy", self.default_restart_policy(rt))
        spec = job.setdefault("spec", {})
        spec.setdefault("cleanPodPolicy", c.CLEAN_POD_RUNNING)

    def default_restart_policy(self, rtype: str) -> str:
        return c.RESTART_NEVER

    # -- reconcile behavior ----------------------------------------------

    def get_reconcile_orders(self) -> list[str]:
        """Replica types in creation order (AIMaster first when present)."""
        return []

    def is_master_role(self, replicas: dict, rtype: str, index: int) -> bool:
        return rtype.lower() in ("master", "chief")

    def needs_service(self, rtype: str, job: Optional[dict] = None) -> bool:
        """Whether this replica type gets a headless service (PyTorch: master
        only, reference ``job.go:320-326``; MPI/ElasticDL: none). TPU jobs
        need per-replica DNS regardless — TPU_WORKER_HOSTNAMES resolves
        through these services — so controllers should return True for TPU
        replicas when the job carries a tpuPolicy."""
        return True

    def is_tpu_replica(self, rtype: str) -> bool:
        """Which replica types run on TPU hosts (get slice placement + PJRT
        env). PS/scheduler/launcher-style roles stay on CPU nodes."""
        return rtype.lower() in ("worker", "master", "chief")

    def set_cluster_spec(self, job: dict, pod_template: dict, rtype: str,
                         index: int) -> None:
        """Framework-specific rendezvous env injection. THE plugin seam."""

    # -- success semantics -----------------------------------------------

    def contains_master_spec(self, replicas: dict) -> bool:
        return any(rt.lower() in ("master", "chief") for rt in replicas)

    def success_policy(self, job: dict) -> str:
        return m.get_in(job, "spec", "successPolicy", default=c.SUCCESS_POLICY_DEFAULT) or ""

    def master_replica_types(self, replicas: dict) -> list[str]:
        return [rt for rt in replicas if rt.lower() in ("master", "chief")]

    def worker_replica_type(self) -> str:
        return "Worker"

    def judge_worker_success(self, job: dict, total: int, succeeded: int,
                             worker0_completed: bool) -> bool:
        """Whether a master-less job counts as succeeded given its worker
        tally (reference TF ``status.go:170-171``; XDL overrides with its
        min-finish-work-rate)."""
        if succeeded >= total:
            return True
        return (worker0_completed
                and self.success_policy(job) != c.SUCCESS_POLICY_ALL_WORKERS)

    # -- optional hooks ---------------------------------------------------

    def enable_elastic_scaling(self, job: dict, run_policy: RunPolicy) -> bool:
        return m.annotations(job).get(c.ANNOTATION_ENABLE_ELASTIC) == "true"

    def checkpoint_if_necessary(self, job: dict, pods: list) -> bool:
        """Returns True when no checkpoint is in flight (scaling may go)."""
        return True

    def scale_out(self, job: dict, replicas: dict, pods: list, services: list) -> None:
        pass

    def scale_in(self, job: dict, replicas: dict, pods: list, services: list) -> None:
        pass

    def on_job_finished(self, job: dict, pods: list) -> None:
        """Post-terminal hook (e.g. TensorBoard TTL, MPI launcher cleanup)."""

    def on_job_running(self, job: dict) -> None:
        """Hook fired while job is live (e.g. TensorBoard reconcile)."""
